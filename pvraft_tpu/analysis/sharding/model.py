"""Static SPMD model of one module: the facts the GS rules consume.

Pure stdlib ``ast``. One walk extracts, per module:

* **axis sites** — string-literal mesh-axis names at every
  ``PartitionSpec``/``P``/``Mesh``/collective (``ppermute``, ``psum``,
  ``pvary``, ``axis_index``, ``axis_size``, ...) call and every
  ``mesh.shape["..."]`` subscript (GS002);
* **fragile spellings** — direct ``lax.axis_size`` use outside
  ``compat.py`` (the in-jit spelling that moved between jax versions;
  GL004 precedent, GS002);
* **eager stack sites** — the ``tree_map(lambda *xs: jnp.stack(xs),
  *pending)`` host-materialization idiom, with the class/module
  process-count guards that must accompany it (GS003);
* **write sites** — filesystem mutations with a guard analysis:
  lexical ``jax.process_index() == 0`` dominators, terminating guard
  clauses, process-0 flag fields (the ``EventLog.enabled`` pattern) and
  module-local writer helpers dominated by guarded call sites (GS004);
* **batch-contract sites** — arithmetic crossing a ``process_count``
  boundary with a batch dimension, and device-placement calls that
  bypass ``parallel/mesh.py`` (GS005).

The guard analysis is deliberately syntactic and local: it recognizes
the repo's actual conventions (guard clause + early return, rank-0
``if`` bodies, tainted boolean fields, guarded helper call sites) and
nothing cleverer — a write the model cannot prove guarded is a finding,
the same fail-closed posture as kernelcheck's GK000.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

# Collective / sharding APIs whose string-literal args name mesh axes.
AXIS_APIS = frozenset({
    "PartitionSpec", "ppermute", "psum", "psum_scatter", "pmean", "pmax",
    "pmin", "pvary", "pbroadcast", "all_gather", "all_to_all",
    "axis_index", "axis_size", "pswapaxes",
})

# Filesystem mutations GS004 watches. ``os.makedirs``/``os.mkdir`` with
# ``exist_ok=True`` are exempt (idempotent ensure — concurrent-safe by
# construction); everything else here mutates shared state.
WRITE_APIS = frozenset({
    "os.makedirs", "os.mkdir", "os.replace", "os.rename", "os.unlink",
    "os.remove", "os.rmdir", "np.save", "np.savez", "np.savez_compressed",
    "numpy.save", "numpy.savez", "numpy.savez_compressed",
    "shutil.rmtree", "shutil.copytree", "shutil.copy", "shutil.copy2",
    "shutil.move",
})

PLACEMENT_APIS = frozenset({
    "device_put", "make_array_from_process_local_data",
})


def _dotted(node: ast.AST) -> str:
    """'os.path.join'-style spelling of a Name/Attribute chain ('' when
    the chain bottoms out in a call/subscript — those roots are dynamic)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _tail(node: ast.AST) -> str:
    d = _dotted(node)
    return d.rsplit(".", 1)[-1] if d else ""


def _contains_text(node: ast.AST, text: str) -> bool:
    """Does any Name/Attribute in the subtree spell ``text``?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == text:
            return True
        if isinstance(n, ast.Name) and n.id == text:
            return True
    return False


def _str_constants(node: ast.AST) -> List[Tuple[int, int, str]]:
    return [(n.lineno, n.col_offset, n.value) for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


@dataclasses.dataclass(frozen=True)
class AxisSite:
    line: int
    col: int
    axis: str
    api: str


@dataclasses.dataclass(frozen=True)
class FragileSpelling:
    line: int
    col: int
    spelling: str


@dataclasses.dataclass(frozen=True)
class StackSite:
    line: int
    col: int
    owner: str          # enclosing class name, "" for module level


@dataclasses.dataclass(frozen=True)
class ProcessGuard:
    line: int
    owner: str


@dataclasses.dataclass(frozen=True)
class WriteSite:
    line: int
    col: int
    call: str
    func: str           # enclosing function name ("" = module body)
    owner: str          # enclosing class name
    guarded: bool


@dataclasses.dataclass(frozen=True)
class BatchArithSite:
    line: int
    col: int
    detail: str


@dataclasses.dataclass(frozen=True)
class PlacementSite:
    line: int
    col: int
    api: str


@dataclasses.dataclass(frozen=True)
class RuleEntry:
    line: int
    col: int
    pattern: Optional[str]                       # None: unparseable
    spec: Optional[Tuple[Optional[str], ...]]


@dataclasses.dataclass
class PartitionRulesDecl:
    line: int
    entries: List[RuleEntry]


@dataclasses.dataclass
class ModuleShardModel:
    axis_sites: List[AxisSite]
    fragile: List[FragileSpelling]
    stack_sites: List[StackSite]
    process_guards: List[ProcessGuard]
    write_sites: List[WriteSite]
    batch_arith: List[BatchArithSite]
    placements: List[PlacementSite]
    partition_rules: Optional[PartitionRulesDecl]


# --- guard grammar ---------------------------------------------------------

def _is_rank_compare(node: ast.AST, api: str, values: Sequence[int],
                     ops) -> bool:
    """``<...api...> OP <int in values>`` (either side)."""
    if not isinstance(node, ast.Compare) or len(node.ops) != 1:
        return False
    left, op, right = node.left, node.ops[0], node.comparators[0]
    if not isinstance(op, ops):
        return False
    for a, b in ((left, right), (right, left)):
        if (_contains_text(a, api) and isinstance(b, ast.Constant)
                and b.value in values):
            return True
    return False


class _GuardLattice:
    """Per-class taint of process-0 flags + the guard-test classifier."""

    def __init__(self):
        self.rank0_fields: Set[str] = set()   # self.<field> is a p0 flag
        self.rank0_locals: Set[str] = set()   # per-function, reset often

    def is_rank0_true(self, test: ast.AST) -> bool:
        """Inside ``if test:`` the process is provably 0 (or provably the
        only process)."""
        if _is_rank_compare(test, "process_index", (0,), (ast.Eq,)):
            return True
        if _is_rank_compare(test, "process_count", (1,), (ast.Eq,)) or \
                _is_rank_compare(test, "process_count", (1, 2),
                                 (ast.Lt, ast.LtE)):
            # count == 1 / count <= 1 / count < 2: single-process.
            return True
        if isinstance(test, ast.Name) and test.id in self.rank0_locals:
            return True
        if isinstance(test, ast.Attribute) and \
                isinstance(test.value, ast.Name) and \
                test.value.id == "self" and test.attr in self.rank0_fields:
            return True
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self.is_rank0_exit(test.operand)
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            return any(self.is_rank0_true(v) for v in test.values)
        return False

    def is_rank0_exit(self, test: ast.AST) -> bool:
        """``if test: return/raise`` leaves only process 0 (or a single
        process) on the fall-through path."""
        if _is_rank_compare(test, "process_index", (0,), (ast.NotEq,)) or \
                _is_rank_compare(test, "process_index", (0,), (ast.Gt,)):
            return True
        if _is_rank_compare(test, "process_count", (1,), (ast.NotEq,)) or \
                _is_rank_compare(test, "process_count", (1, 2),
                                 (ast.Gt, ast.GtE)):
            return True
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self.is_rank0_true(test.operand)
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            # `if flag and count > 1: raise` — the fall-through is only
            # single-process WHEN flag holds; accepting it mirrors the
            # evaluator's dump_dir guard (the write is gated on the same
            # flag). Deliberately permissive in the flag direction.
            return any(self.is_rank0_exit(v) for v in test.values)
        return False

    def taint_function(self, fn: ast.AST) -> None:
        """Collect rank-0 locals: names assigned (anywhere in ``fn``)
        from an expression containing a process_index-vs-0 compare."""
        self.rank0_locals = set()
        if not _contains_text(fn, "process_index"):
            return  # cheap prefilter: nothing to taint from
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and n.targets:
                if self._rank0_expr(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            self.rank0_locals.add(t.id)

    def _rank0_expr(self, expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if _is_rank_compare(n, "process_index", (0,), (ast.Eq,)):
                return True
            if isinstance(n, ast.Name) and n.id in self.rank0_locals:
                return True
        return False

    def taint_class(self, cls: ast.ClassDef) -> None:
        """Two passes: function-local flags, then ``self.X = <flag>``."""
        self.rank0_fields = set()
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self.taint_function(item)
            for n in ast.walk(item):
                if isinstance(n, ast.Assign) and self._rank0_expr(n.value):
                    for t in n.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            self.rank0_fields.add(t.attr)


def _body_terminates(body: Sequence[ast.stmt]) -> bool:
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Continue, ast.Break))
               for s in body)


# --- write-site extraction -------------------------------------------------

def _write_call(node: ast.Call) -> Optional[str]:
    """The WRITE_APIS spelling of a call, or 'open' for a write-mode
    open, or None."""
    dotted = _dotted(node.func)
    if dotted in WRITE_APIS:
        if dotted in ("os.makedirs", "os.mkdir"):
            for kw in node.keywords:
                if kw.arg == "exist_ok" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is True:
                    return None  # idempotent ensure: concurrent-safe
        return dotted
    if isinstance(node.func, ast.Name) and node.func.id == "open":
        mode = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if isinstance(mode, str) and any(c in mode for c in "wax+"):
            return "open"
    return None


class _FunctionWrites:
    """Write sites (and writer-helper call sites) of one function with
    lexical guard state."""

    def __init__(self, lattice: _GuardLattice):
        self.lattice = lattice
        self.writes: List[Tuple[int, int, str, bool]] = []
        self.calls: List[Tuple[str, bool]] = []   # (callee name, guarded)

    def scan(self, fn) -> None:
        self.lattice.taint_function(fn)
        self._block(fn.body, False)

    def _expr(self, node: ast.AST, guarded: bool) -> None:
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue  # nested scopes handled by the module pass
            if isinstance(n, ast.Call):
                w = _write_call(n)
                if w:
                    self.writes.append(
                        (n.lineno, n.col_offset, w, guarded))
                callee = _tail(n.func)
                if callee:
                    self.calls.append((callee, guarded))

    def _block(self, body: Sequence[ast.stmt], guarded: bool) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # the module pass scans nested scopes itself
            if isinstance(stmt, ast.If):
                self._expr(stmt.test, guarded)
                if self.lattice.is_rank0_true(stmt.test):
                    self._block(stmt.body, True)
                    self._block(stmt.orelse, guarded)
                elif self.lattice.is_rank0_exit(stmt.test) and \
                        _body_terminates(stmt.body):
                    self._block(stmt.body, guarded)
                    self._block(stmt.orelse, guarded)
                    guarded = True  # fall-through is process-0/single
                else:
                    self._block(stmt.body, guarded)
                    self._block(stmt.orelse, guarded)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._expr(stmt.iter, guarded)
                self._block(stmt.body, guarded)
                self._block(stmt.orelse, guarded)
                continue
            if isinstance(stmt, ast.While):
                self._expr(stmt.test, guarded)
                self._block(stmt.body, guarded)
                self._block(stmt.orelse, guarded)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._expr(item.context_expr, guarded)
                self._block(stmt.body, guarded)
                continue
            if isinstance(stmt, ast.Try):
                self._block(stmt.body, guarded)
                for h in stmt.handlers:
                    self._block(h.body, guarded)
                self._block(stmt.orelse, guarded)
                self._block(stmt.finalbody, guarded)
                continue
            self._expr(stmt, guarded)


def _collect_write_sites(tree: ast.Module) -> List[WriteSite]:
    """Module-wide GS004 model: per-function lexical analysis (the
    module body itself is analyzed as the ``<module>`` scope — an
    import-time write is as multi-process-hot as any), then the
    writer-helper dominance fixpoint (a helper whose every in-module
    call site is guarded inherits the guard — the ``checkpoint.py
    _write``/``_swap_in`` shape)."""
    functions: List[Tuple[str, str, ast.AST]] = [("", "<module>", tree)]

    def discover(body, owner: str) -> None:
        """Every def/class, wherever nested (incl. under if/try/with)."""
        for item in body:
            if isinstance(item, ast.ClassDef):
                discover(item.body, item.name)
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                functions.append((owner, item.name, item))
                discover(item.body, owner)
            else:
                for sub in (getattr(item, "body", ()),
                            getattr(item, "orelse", ()),
                            getattr(item, "finalbody", ())):
                    discover(sub, owner)
                for h in getattr(item, "handlers", ()):
                    discover(h.body, owner)

    discover(tree.body, "")

    lattice = _GuardLattice()
    class_nodes = {n.name: n for n in ast.walk(tree)
                   if isinstance(n, ast.ClassDef)}
    field_cache: Dict[str, Set[str]] = {}
    per_fn: Dict[Tuple[str, str], _FunctionWrites] = {}
    order: List[Tuple[str, str]] = []
    for owner, name, fn in functions:
        if owner and owner in class_nodes:
            if owner not in field_cache:
                lattice.taint_class(class_nodes[owner])
                field_cache[owner] = set(lattice.rank0_fields)
            lattice.rank0_fields = field_cache[owner]
        else:
            lattice.rank0_fields = set()
        fw = _FunctionWrites(lattice)
        fw.scan(fn)
        key = (owner, name)
        if key not in per_fn:       # first def wins on duplicate names
            per_fn[key] = fw
            order.append(key)

    # Least-fixpoint dominance, grown from lexically-guarded call
    # sites: a function is guard-dominated iff it HAS in-module call
    # sites and every one is lexically guarded or inside a dominated
    # function. (A greatest fixpoint would prove a mutually-recursive
    # writer pair with no outside callers "guarded" — fail closed.)
    name_to_keys: Dict[str, List[Tuple[str, str]]] = {}
    for k in per_fn:
        name_to_keys.setdefault(k[1], []).append(k)
    call_sites: Dict[Tuple[str, str], List[Tuple[Tuple[str, str], bool]]] = \
        {k: [] for k in per_fn}
    for caller, fw in per_fn.items():
        for callee, guarded in fw.calls:
            for key in name_to_keys.get(callee, ()):
                if key != caller:
                    call_sites[key].append((caller, guarded))
    dominated: Set[Tuple[str, str]] = set()
    changed = True
    while changed:
        changed = False
        for key, sites in call_sites.items():
            if key in dominated or not sites:
                continue
            if all(guarded or caller in dominated
                   for caller, guarded in sites):
                dominated.add(key)
                changed = True

    out: List[WriteSite] = []
    for key in order:
        owner, name = key
        fw = per_fn[key]
        for line, col, call, guarded in fw.writes:
            out.append(WriteSite(
                line=line, col=col, call=call, func=name, owner=owner,
                guarded=guarded or key in dominated))
    out.sort(key=lambda w: (w.line, w.col))
    return out


# --- the module walk -------------------------------------------------------

def _partition_spec_names(tree: ast.Module) -> Set[str]:
    """Local spellings of PartitionSpec ('P' via the import alias)."""
    names = {"PartitionSpec"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "PartitionSpec" and alias.asname:
                    names.add(alias.asname)
    return names


def _extract_partition_rules(tree: ast.Module) -> Optional[PartitionRulesDecl]:
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        else:
            continue
        if not (isinstance(target, ast.Name)
                and target.id == "PARTITION_RULES"):
            continue
        entries: List[RuleEntry] = []
        if isinstance(value, (ast.Tuple, ast.List)):
            for elt in value.elts:
                pattern = spec = None
                if isinstance(elt, (ast.Tuple, ast.List)) and \
                        len(elt.elts) == 2:
                    pat_node, spec_node = elt.elts
                    if isinstance(pat_node, ast.Constant) and \
                            isinstance(pat_node.value, str):
                        pattern = pat_node.value
                    if isinstance(spec_node, (ast.Tuple, ast.List)):
                        axes = []
                        ok = True
                        for a in spec_node.elts:
                            if isinstance(a, ast.Constant) and (
                                    a.value is None
                                    or isinstance(a.value, str)):
                                axes.append(a.value)
                            else:
                                ok = False
                        if ok:
                            spec = tuple(axes)
                entries.append(RuleEntry(elt.lineno, elt.col_offset,
                                         pattern, spec))
        return PartitionRulesDecl(line=node.lineno, entries=entries)
    return None


def build_module_shard_model(tree: ast.Module) -> ModuleShardModel:
    ps_names = _partition_spec_names(tree)
    axis_sites: List[AxisSite] = []
    fragile: List[FragileSpelling] = []
    stack_sites: List[StackSite] = []
    guards: List[ProcessGuard] = []
    batch_arith: List[BatchArithSite] = []
    placements: List[PlacementSite] = []

    # process_count-tainted local names, per function (for GS005).
    def count_tainted(fn) -> Set[str]:
        tainted: Set[str] = set()
        if not _contains_text(fn, "process_count"):
            return tainted  # cheap prefilter
        for _ in range(2):  # one propagation round is enough here
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign):
                    if _contains_text(n.value, "process_count") or any(
                            isinstance(x, ast.Name) and x.id in tainted
                            for x in ast.walk(n.value)):
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                tainted.add(t.id)
        return tainted

    def owner_of(path: List[ast.AST]) -> str:
        for node in reversed(path):
            if isinstance(node, ast.ClassDef):
                return node.name
        return ""

    # Walk with a parent path so stack sites / guards know their class.
    def walk(node, path):
        for child in ast.iter_child_nodes(node):
            visit(child, path + [node])

    def visit(node, path):
        if isinstance(node, ast.Call):
            callee = _tail(node.func)
            dotted = _dotted(node.func)
            if callee in ps_names or callee in AXIS_APIS or \
                    callee == "Mesh":
                api = ("PartitionSpec" if callee in ps_names else callee)
                # Keywords carry axis names too (`psum(x,
                # axis_name="data")` is the common jax spelling).
                args: List[ast.AST] = list(node.args) + [
                    kw.value for kw in node.keywords]
                if callee == "Mesh":
                    # Only the axis-names operand (2nd positional or the
                    # axis_names kwarg) carries axis strings.
                    args = list(node.args[1:2]) + [
                        kw.value for kw in node.keywords
                        if kw.arg == "axis_names"]
                for arg in args:
                    for line, col, s in _str_constants(arg):
                        axis_sites.append(AxisSite(line, col, s, api))
            if dotted.endswith("lax.axis_size"):
                fragile.append(FragileSpelling(
                    node.lineno, node.col_offset, dotted))
            if callee in PLACEMENT_APIS:
                placements.append(PlacementSite(
                    node.lineno, node.col_offset, callee))
            if callee in ("tree_map", "tree_multimap"):
                has_star = any(isinstance(a, ast.Starred)
                               for a in node.args)
                lam = next((a for a in node.args
                            if isinstance(a, ast.Lambda)), None)
                if has_star and lam is not None and any(
                        isinstance(n, ast.Call)
                        and _tail(n.func) in ("stack", "concatenate")
                        for n in ast.walk(lam.body)):
                    stack_sites.append(StackSite(
                        node.lineno, node.col_offset, owner_of(path)))
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Attribute) and \
                node.value.attr == "shape":
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                axis_sites.append(AxisSite(
                    node.lineno, node.col_offset, sl.value, "mesh.shape"))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and \
                isinstance(node.func.value, ast.Attribute) and \
                node.func.value.attr == "shape":
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                axis_sites.append(AxisSite(
                    node.lineno, node.col_offset, node.args[0].value,
                    "mesh.shape"))
        if isinstance(node, ast.ImportFrom) and node.module == "jax.lax":
            for alias in node.names:
                if alias.name == "axis_size":
                    fragile.append(FragileSpelling(
                        node.lineno, node.col_offset, "jax.lax.axis_size"))
        if isinstance(node, ast.If) and \
                _contains_text(node.test, "process_count") and \
                any(isinstance(n, ast.Compare)
                    for n in ast.walk(node.test)) and \
                any(isinstance(s, (ast.Raise, ast.Return, ast.Assign))
                    for s in ast.walk(node)):
            guards.append(ProcessGuard(node.lineno, owner_of(path)))
        walk(node, path)

    walk(tree, [])

    # GS005 batch arithmetic, per function scope.
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tainted = count_tainted(fn)

        def is_count_side(n) -> bool:
            if _contains_text(n, "process_count"):
                return True
            return any(isinstance(x, ast.Name) and x.id in tainted
                       for x in ast.walk(n))

        def is_batch_side(n) -> bool:
            for x in ast.walk(n):
                if isinstance(x, ast.Name) and "batch" in x.id.lower():
                    return True
                if isinstance(x, ast.Attribute) and \
                        "batch" in x.attr.lower():
                    return True
            return False

        for n in ast.walk(fn):
            if isinstance(n, ast.BinOp) and isinstance(
                    n.op, (ast.Mult, ast.Div, ast.FloorDiv, ast.Mod)):
                pairs = ((n.left, n.right), (n.right, n.left))
                for a, b in pairs:
                    if is_count_side(a) and is_batch_side(b) and \
                            not is_count_side(b):
                        batch_arith.append(BatchArithSite(
                            n.lineno, n.col_offset,
                            "batch dim combined with process_count"))
                        break

    return ModuleShardModel(
        axis_sites=sorted(axis_sites, key=lambda a: (a.line, a.col)),
        fragile=sorted(fragile, key=lambda a: (a.line, a.col)),
        stack_sites=sorted(stack_sites, key=lambda a: (a.line, a.col)),
        process_guards=guards,
        write_sites=_collect_write_sites(tree),
        batch_arith=sorted(set(batch_arith), key=lambda a: (a.line, a.col)),
        placements=sorted(placements, key=lambda a: (a.line, a.col)),
        partition_rules=_extract_partition_rules(tree),
    )
