"""shardcheck driver: files -> shard models -> GS rules -> diagnostics.

Mirrors ``concurrency/check.py``/``kernels/check.py`` deliberately: the
same ``Diagnostic`` type, the same ``# graftlint: disable=GSxxx --
reason`` suppression grammar (one parser — what ``lint --stats`` counts
is exactly what is honored here), the same stable ordering. Scope
defaults to the multi-process planes of the package (engine, obs,
parallel, programs, models, ops, data + the compat/config top-levels;
``serve/`` is the single-host plane, threadcheck's turf).

The declared context comes from the data planes, never hardcoded: the
mesh-axis vocabulary is parsed from ``parallel/mesh.py``'s
``*_AXIS = "..."`` declarations, the GS001 leaf inventory from the
committed ``artifacts/params_tree.json`` (whose own drift is pinned by
``programs params --check``).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence, Set, Tuple

from pvraft_tpu.analysis.engine import (
    Diagnostic,
    _expand_decorated_regions,
    _suppressed,
    _suppressions,
    iter_py_files,
)
from pvraft_tpu.analysis.sharding.model import build_module_shard_model
from pvraft_tpu.analysis.sharding.rules import (
    ShardContext,
    all_sharding_rules,
)

# Spelled as constants for docs/tests; resolved lazily by the CLI.
DEFAULT_SCOPE = (
    "pvraft_tpu/engine", "pvraft_tpu/obs", "pvraft_tpu/parallel",
    "pvraft_tpu/programs", "pvraft_tpu/models", "pvraft_tpu/ops",
    "pvraft_tpu/data", "pvraft_tpu/compat.py", "pvraft_tpu/config.py",
)


def _pkg_root() -> str:
    import pvraft_tpu

    return os.path.dirname(os.path.abspath(pvraft_tpu.__file__))


def default_scope() -> Tuple[str, ...]:
    """The gate's scan scope, as absolute paths of this checkout."""
    pkg = _pkg_root()
    return tuple(
        os.path.join(pkg, rel.split("/", 1)[1]) for rel in DEFAULT_SCOPE)


def declared_axes() -> Set[str]:
    """The mesh-axis vocabulary: every ``<NAME>_AXIS = "..."`` string
    constant declared at module level of ``parallel/mesh.py`` — the
    ``(data, seq)`` builder IS the declaration site (GS002)."""
    path = os.path.join(_pkg_root(), "parallel", "mesh.py")
    axes: Set[str] = set()
    try:
        with open(path, "r", encoding="utf-8-sig") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return axes
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id.endswith("_AXIS") and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            axes.add(node.value.value)
    return axes


def default_param_leaves() -> Optional[List[str]]:
    """Leaf paths of the committed ``artifacts/params_tree.json``
    (repo-root sibling of the package), or None when unreadable —
    GS001 reports that as a finding rather than skipping."""
    path = os.path.join(os.path.dirname(_pkg_root()),
                        "artifacts", "params_tree.json")
    try:
        from pvraft_tpu.programs.partitioning import load_params_tree

        doc = load_params_tree(path)
    except (OSError, ValueError):
        return None
    return [leaf["path"] for leaf in doc["leaves"]]


def check_source(source: str, path: str = "<string>",
                 rule_ids: Sequence[str] = (),
                 declared: Optional[Set[str]] = None,
                 param_leaves: Optional[Sequence[str]] = None,
                 ) -> List[Diagnostic]:
    """Run the GS rules over one source string (suppressions applied)."""
    source = source.lstrip("\ufeff")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Diagnostic(path, e.lineno or 1, e.offset or 0, "GS000",
                           f"syntax error: {e.msg}")]
    model = build_module_shard_model(tree)
    ctx = ShardContext(path, source, tree, model,
                       declared_axes=declared, param_leaves=param_leaves)
    per_line, file_ids = _suppressions(source)
    _expand_decorated_regions(tree, per_line)
    out: List[Diagnostic] = []
    for rule_cls in all_sharding_rules():
        if rule_ids and rule_cls.id not in rule_ids:
            continue
        for d in rule_cls().check(ctx):
            if not _suppressed(d, per_line, file_ids):
                out.append(d)
    out.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return out


def check_paths(paths: Sequence[str], rule_ids: Sequence[str] = (),
                declared: Optional[Set[str]] = None,
                param_leaves: Optional[Sequence[str]] = None,
                ) -> Tuple[List[Diagnostic], int]:
    """Check files/directories. Returns (findings, files_checked).

    ``declared``/``param_leaves`` default to the live declarations
    (mesh.py axes, the committed leaf inventory) so the clean-tree gate
    always arms GS001/GS002 with real data."""
    if declared is None:
        declared = declared_axes()
    if param_leaves is None:
        param_leaves = default_param_leaves()
    findings: List[Diagnostic] = []
    n = 0
    for f in iter_py_files(paths):
        n += 1
        with open(f, "r", encoding="utf-8-sig") as fh:
            findings.extend(check_source(
                fh.read(), path=f, rule_ids=rule_ids, declared=declared,
                param_leaves=param_leaves))
    findings.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return findings, n
