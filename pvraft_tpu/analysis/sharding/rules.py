"""shardcheck rules GS001-GS005 — the SPMD/multi-host failure classes.

ROADMAP item 2 turns the multi-process guards into implementations:
named-mesh sharding rules, per-host data loading, multihost
checkpointing, the ring kNN path promoted to how big scenes train.
These rules make the conventions that campaign depends on — partition
coverage, axis-name discipline, the no-eager-stack invariant, the
process-0 I/O contract, the batch-size contract — machine-checked
BEFORE the guards come down, the way kernelcheck de-risked the fused
kernel campaign. Suppress with ``# graftlint: disable=GSxxx -- reason``
(shared pragma grammar; reason-less suppressions fail ``lint --stats``).

Path scoping: inside the installed package each rule applies only where
its convention lives (GS004 to ``engine/``+``obs/``, GS005 to
``engine/``+``data/``+``obs/`` with ``parallel/mesh.py`` exempt as the
contract owner); outside the package (fixtures, inline test sources)
every rule applies unconditionally so red/green corpora stay honest.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Sequence, Set, Tuple, Type

from pvraft_tpu.analysis.engine import Diagnostic, LintContext, Rule
from pvraft_tpu.analysis.sharding.model import (
    ModuleShardModel,
    build_module_shard_model,
)


class ShardContext(LintContext):
    """LintContext + the extracted shard model + the declared-data
    context (mesh axes from ``parallel/mesh.py``, the committed param
    leaf inventory for GS001). ``param_leaves=None`` means the caller
    supplied no inventory: GS001 then reports the gap as a finding on
    any ``PARTITION_RULES`` file rather than silently skipping."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 model: Optional[ModuleShardModel] = None,
                 declared_axes: Optional[Set[str]] = None,
                 param_leaves: Optional[Sequence[str]] = None):
        super().__init__(path, source, tree)
        self.model = model if model is not None \
            else build_module_shard_model(tree)
        self.declared_axes = declared_axes
        self.param_leaves = param_leaves

    def package_suffix(self) -> Optional[str]:
        """'pvraft_tpu/...' relative suffix, or None for out-of-package
        sources (fixtures, inline strings) — those see every rule."""
        if "pvraft_tpu/" in self.norm_path:
            return "pvraft_tpu/" + self.norm_path.rsplit(
                "/pvraft_tpu/", 1)[-1]
        return None

    def diag_at(self, line: int, col: int, rule_id: str,
                message: str) -> Diagnostic:
        return Diagnostic(self.path, line, col, rule_id, message)


class ShardRule(Rule):
    def check(self, ctx: ShardContext) -> Iterable[Diagnostic]:
        raise NotImplementedError


_GS_REGISTRY: List[Type[ShardRule]] = []


def gs_register(cls: Type[ShardRule]) -> Type[ShardRule]:
    if not cls.id or not cls.title:
        raise ValueError(f"rule {cls.__name__} must set id and title")
    if any(r.id == cls.id for r in _GS_REGISTRY):
        raise ValueError(f"duplicate rule id {cls.id}")
    _GS_REGISTRY.append(cls)
    return cls


def all_sharding_rules() -> Tuple[Type[ShardRule], ...]:
    return tuple(sorted(_GS_REGISTRY, key=lambda r: r.id))


def _in_scope(ctx: ShardContext, prefixes: Tuple[str, ...],
              exempt: Tuple[str, ...] = ()) -> bool:
    suffix = ctx.package_suffix()
    if suffix is None:
        return True
    if any(suffix == e for e in exempt):
        return False
    return any(suffix.startswith(p) for p in prefixes)


# --- GS001 ----------------------------------------------------------------

@gs_register
class PartitionRuleCoverage(ShardRule):
    """Partition-rule ladder fails exactly-once leaf coverage.

    ``PARTITION_RULES`` must match every committed param-tree leaf
    (``artifacts/params_tree.json``) exactly once: an unmatched leaf
    would shard nothing silently, a multiply-matched leaf makes the
    ladder order-sensitive, a dead rule is a stale regex nobody notices.
    Runs on any file declaring ``PARTITION_RULES`` (the real
    ``programs/partitioning.py`` and the fixture corpus alike).
    """

    id = "GS001"
    title = "partition-rule-coverage"

    def check(self, ctx: ShardContext) -> Iterable[Diagnostic]:
        decl = ctx.model.partition_rules
        if decl is None:
            return
        rules = []
        for entry in decl.entries:
            if entry.pattern is None or entry.spec is None:
                yield ctx.diag_at(
                    entry.line, entry.col, self.id,
                    "PARTITION_RULES entry is not a literal "
                    "(regex, spec-tuple) pair — the ladder must stay "
                    "statically readable data")
                continue
            try:
                re.compile(entry.pattern)
            except re.error as e:
                yield ctx.diag_at(
                    entry.line, entry.col, self.id,
                    f"invalid partition-rule regex {entry.pattern!r}: {e}")
                continue
            if ctx.declared_axes is not None:
                bad = [a for a in entry.spec
                       if a is not None and a not in ctx.declared_axes]
                if bad:
                    yield ctx.diag_at(
                        entry.line, entry.col, self.id,
                        f"partition spec names undeclared mesh axes "
                        f"{bad} (declared: "
                        f"{sorted(ctx.declared_axes)})")
                    continue
            rules.append((entry, entry.pattern, entry.spec))
        if ctx.param_leaves is None:
            yield ctx.diag_at(
                decl.line, 0, self.id,
                "param-tree leaf inventory unavailable (regenerate "
                "artifacts/params_tree.json: python -m pvraft_tpu."
                "programs params --out artifacts/params_tree.json) — "
                "coverage cannot be checked")
            return
        from pvraft_tpu.programs.partitioning import match_report

        _mapping, unmatched, multi, unused = match_report(
            [(pat, spec) for _, pat, spec in rules], ctx.param_leaves)
        for path in unmatched:
            yield ctx.diag_at(
                decl.line, 0, self.id,
                f"param leaf {path!r} matches no partition rule "
                f"(exactly-once coverage)")
        for path, pats in multi:
            yield ctx.diag_at(
                decl.line, 0, self.id,
                f"param leaf {path!r} matches {len(pats)} rules "
                f"({pats}); rules must be disjoint")
        by_pattern = {pat: entry for entry, pat, _ in rules}
        for pat in unused:
            entry = by_pattern[pat]
            yield ctx.diag_at(
                entry.line, entry.col, self.id,
                f"dead partition rule {pat!r}: no param leaf matches it")


# --- GS002 ----------------------------------------------------------------

@gs_register
class MeshAxisDiscipline(ShardRule):
    """Undeclared mesh-axis name, or a version-fragile in-jit spelling.

    Every literal axis string at a ``PartitionSpec``/``Mesh``/
    collective call site (and ``mesh.shape["..."]`` lookups) must be an
    axis ``parallel/mesh.py`` declares — a typo'd axis name surfaces as
    an unbound-axis trace error only at the first multi-device run.
    Direct ``lax.axis_size`` use is flagged outside ``compat.py``: the
    spelling moved between jax versions (the GL004 precedent), and
    ``pvraft_tpu.compat.axis_size`` is the stable one.
    """

    id = "GS002"
    title = "mesh-axis-discipline"

    def check(self, ctx: ShardContext) -> Iterable[Diagnostic]:
        declared = ctx.declared_axes
        if declared is not None:
            for site in ctx.model.axis_sites:
                if site.axis not in declared:
                    yield ctx.diag_at(
                        site.line, site.col, self.id,
                        f"axis name {site.axis!r} at a {site.api} site "
                        f"is not declared by parallel/mesh.py (declared: "
                        f"{sorted(declared)})")
        if ctx.package_suffix() == "pvraft_tpu/compat.py":
            return
        for f in ctx.model.fragile:
            yield ctx.diag_at(
                f.line, f.col, self.id,
                f"direct {f.spelling} (moved between jax versions); "
                f"use pvraft_tpu.compat.axis_size")


# --- GS003 ----------------------------------------------------------------

@gs_register
class HostMaterializedShardedBatch(ShardRule):
    """Eager stack of device batches with no multi-process guard.

    ``tree_map(lambda *xs: jnp.stack(xs), *pending)`` materializes a
    stacked batch EAGERLY: on a multi-host mesh the pending batches are
    non-fully-addressable global arrays and the stack raises mid-epoch
    (or worse, silently gathers). Every such site must live in a class
    (or module) that also carries a ``process_count`` guard — the
    ``trainer.py`` constructor-raise / ``evaluator.py`` fallback shape —
    so the ROADMAP item-2 PR that deletes the guards cannot keep the
    eager stack by accident.
    """

    id = "GS003"
    title = "host-materialized-sharded-batch"

    def check(self, ctx: ShardContext) -> Iterable[Diagnostic]:
        guard_owners = {g.owner for g in ctx.model.process_guards}
        for site in ctx.model.stack_sites:
            if site.owner in guard_owners:
                continue
            where = (f"class {site.owner}" if site.owner
                     else "this module")
            yield ctx.diag_at(
                site.line, site.col, self.id,
                f"eager tree_map/jnp.stack of accumulated device "
                f"batches, but {where} has no process_count guard — "
                f"on a multi-host mesh the stacked batches are "
                f"non-addressable global arrays; guard the mode (raise "
                f"or fall back) or shard the stack through the mesh")


# --- GS004 ----------------------------------------------------------------

@gs_register
class UnguardedProcessZeroIO(ShardRule):
    """Filesystem write reachable without a process-0 dominator.

    ``engine/`` and ``obs/`` run on every host of a multi-process mesh;
    a write no ``jax.process_index() == 0`` test dominates runs once
    per host — concurrent truncations, interleaved JSONL, corrupt
    checkpoints. Recognized guard shapes: lexical rank-0 ``if`` bodies,
    terminating guard clauses (``if process_index() != 0: return``),
    process-0 flag fields (the ``EventLog.enabled`` pattern),
    single-process proofs (``if process_count() > 1: raise``), and
    module-local helpers whose every call site is guarded (the
    ``checkpoint.py`` ``_write``/``_swap_in`` shape).
    ``os.makedirs(..., exist_ok=True)`` is exempt (idempotent ensure).
    """

    id = "GS004"
    title = "unguarded-process0-io"

    _SCOPE = ("pvraft_tpu/engine/", "pvraft_tpu/obs/")

    def check(self, ctx: ShardContext) -> Iterable[Diagnostic]:
        if not _in_scope(ctx, self._SCOPE):
            return
        for site in ctx.model.write_sites:
            if site.guarded:
                continue
            where = (f"{site.owner}.{site.func}" if site.owner
                     else site.func or "<module>")
            yield ctx.diag_at(
                site.line, site.col, self.id,
                f"{site.call}(...) in {where} is reachable without a "
                f"dominating jax.process_index() == 0 test — on a "
                f"multi-process mesh every host runs it; guard the "
                f"write (early return, rank-0 if, or a process-0 flag "
                f"field)")


# --- GS005 ----------------------------------------------------------------

@gs_register
class BatchContractConfusion(ShardRule):
    """Per-host vs global batch arithmetic outside the mesh contract.

    The global/local batch relationship (``global = per_device x
    mesh_data``, ``local = global / process_count``) lives in
    ``parallel/mesh.py`` (``batch_contract``/``shard_batch``/
    ``device_batch``); a literal batch dim scaled by ``process_count``
    anywhere else re-derives the contract and drifts from it (the
    historical trainer shape). Direct ``jax.device_put`` /
    ``make_array_from_process_local_data`` calls in the engine/data/obs
    planes bypass the one placement path that is multi-host-correct.
    """

    id = "GS005"
    title = "batch-contract-confusion"

    _SCOPE = ("pvraft_tpu/engine/", "pvraft_tpu/data/", "pvraft_tpu/obs/")
    _OWNER = ("pvraft_tpu/parallel/mesh.py",)

    def check(self, ctx: ShardContext) -> Iterable[Diagnostic]:
        if not _in_scope(ctx, self._SCOPE, exempt=self._OWNER):
            return
        for site in ctx.model.batch_arith:
            yield ctx.diag_at(
                site.line, site.col, self.id,
                f"{site.detail} — the per-host/global batch contract "
                f"lives in parallel/mesh.py (batch_contract); derive "
                f"the size there instead of re-scaling by "
                f"process_count here")
        for site in ctx.model.placements:
            yield ctx.diag_at(
                site.line, site.col, self.id,
                f"direct jax.{site.api}(...) outside parallel/mesh.py "
                f"— batch placement must route through mesh."
                f"shard_batch/device_batch (the multi-host-correct "
                f"path)")
