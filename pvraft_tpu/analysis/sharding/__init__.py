"""shardcheck: SPMD/multi-host static analysis (GS rules) + pod planner.

The FIFTH analysis engine (graftlint AST / deepcheck jaxpr / threadcheck
concurrency / kernelcheck Pallas / shardcheck SPMD), built for ROADMAP
item 2 — true pod-scale training. Pure stdlib ``ast`` plus the jax-free
``programs/partitioning.py`` + ``programs/geometries.py`` data planes;
no jax import anywhere in the engine, so the gate runs on hosts with no
accelerator stack (the graftlint/threadcheck/kernelcheck contract).

Rules (``# graftlint: disable=GSxxx -- reason`` to suppress, shared
pragma grammar — ``lint --stats`` counts GS debt):

* **GS001** partition-rule coverage: ``PARTITION_RULES`` must match
  every committed param-tree leaf exactly once;
* **GS002** mesh-axis discipline: literal axis names at
  ``PartitionSpec``/collective call sites must be the declared
  ``(data, seq)`` axes, and version-fragile in-jit spellings route
  through ``compat.py``;
* **GS003** host-materialization of sharded batches (the eager
  ``jnp.stack`` idiom behind the multi-process guards);
* **GS004** unguarded process-0 I/O in ``engine/``/``obs/``;
* **GS005** per-host vs global batch-contract confusion outside
  ``parallel/mesh.py``.

The planner (``planner.py`` / ``analysis sharding --plan``) joins the
rules, the committed ``artifacts/params_tree.json`` leaf inventory and
``artifacts/programs_costs.json`` into ``artifacts/pod_plan.json``
(``pvraft_pod_plan/v1``): per-device param/optimizer/activation bytes
and fits-16GiB verdicts per candidate ``(dp, sp)`` mesh at
2048/8192/16k/100k-point scenes, plus ring comms-vs-compute accounting.
"""

from pvraft_tpu.analysis.sharding.check import (  # noqa: F401
    check_paths,
    check_source,
    default_scope,
)
