"""Trace-compat audit: abstractly trace every registered op, zero FLOPs.

``jax.eval_shape`` runs the full trace machinery — shape propagation,
Python control flow, ``lax.scan``/``top_k`` shape rules, the
``@shapecheck`` contracts when enabled — without executing anything. So
every failure mode the linter hunts *dynamically manifests here*:
tracer concretization, shape drift between the point and voxel
branches, version-fragile lowering, all caught on a CPU host in
milliseconds per op.

Each entry is a thunk returning ``(fn, args)`` where array args are
``jax.ShapeDtypeStruct``s; the audit calls ``jax.eval_shape(fn, *args)``
and reports per-op pass/fail. Run it:

    python -m pvraft_tpu.analysis trace

Dims are deliberately small and pairwise-distinct (B=2, N=24, M=40,
D=16, K=8) so a transposed axis can never accidentally type-check.
"""

from __future__ import annotations

import dataclasses
import traceback
from typing import Callable, Dict, List, Optional, Tuple

from pvraft_tpu.rng import DEFAULT_SEED, derive

# Symbolic dims: distinct so axis mixups fail loudly.
B, N, M, D, K = 2, 24, 40, 16, 8


@dataclasses.dataclass
class AuditResult:
    name: str
    ok: bool
    detail: str  # out shapes on success, error summary on failure


@dataclasses.dataclass(frozen=True)
class AuditEntry:
    """One registered op: the thunk plus the metadata deepcheck reads.

    Since the program-registry refactor this is a *view* over a
    :class:`pvraft_tpu.programs.spec.ProgramSpec` tagged ``"audit"`` —
    ``audit_entry`` registers a spec, and :func:`entries` projects the
    audit-tagged slice of the registry back into these records, so the
    deepcheck corpus and the program inventory can never diverge.

    ``precision`` declares the entry's dtype intent for rule GJ006
    (``"f32"``: no 16-bit floats anywhere; ``"bf16_grads"``: the
    grad-cast lever must actually appear and not leak; ``"any"``: opt
    out). ``spmd_group`` names a set of step variants whose collective
    fingerprints must match (GJ003). ``path``/``line`` anchor
    entry-level findings for suppression and reporting."""

    name: str
    thunk: Callable[[], Tuple[Callable, tuple]]
    precision: str = "f32"
    spmd_group: Optional[str] = None
    path: str = ""
    line: int = 0


AUDIT_TAG = "audit"


def audit_entry(name: str, precision: str = "f32",
                spmd_group: Optional[str] = None,
                tags: Tuple[str, ...] = (),
                determinism: str = ""):
    """Register one audit entry as an ``"audit"``-tagged ProgramSpec.

    Extra ``tags`` classify the entry in the program inventory
    (``python -m pvraft_tpu.programs list``): "op", "model", "train",
    "eval", "serve", "parallel", ... ``determinism`` is the detcheck
    GD003 stance for entries whose import closure reaches a
    nondeterminism-hazard op. Duplicate names raise (the registry
    enforces declare-exactly-once)."""
    from pvraft_tpu.programs.spec import ProgramSpec, register_spec

    def deco(thunk):
        code = getattr(thunk, "__code__", None)
        register_spec(ProgramSpec(
            name=name,
            thunk=thunk,
            tags=(AUDIT_TAG,) + tuple(tags),
            precision=precision,
            spmd_group=spmd_group,
            determinism=determinism,
            path=getattr(code, "co_filename", "") or "",
            line=getattr(code, "co_firstlineno", 0) or 0,
        ))
        return thunk

    return deco


def entries() -> Dict[str, AuditEntry]:
    """Deepcheck's corpus: the ``"audit"`` slice of the program
    registry, projected into AuditEntry views (copy; mutation-safe)."""
    from pvraft_tpu.programs.spec import by_tag

    return {
        s.name: AuditEntry(name=s.name, thunk=s.thunk,
                           precision=s.precision, spmd_group=s.spmd_group,
                           path=s.path, line=s.line)
        for s in by_tag(AUDIT_TAG)
    }


def _f32(*shape):
    import jax

    return jax.ShapeDtypeStruct(shape, "float32")


def _i32(*shape):
    import jax

    return jax.ShapeDtypeStruct(shape, "int32")


def _bool(*shape):
    import jax

    return jax.ShapeDtypeStruct(shape, "bool")


# --- ops/geometry ---------------------------------------------------------

@audit_entry("geometry.pairwise_sqdist", tags=("op",))
def _e_pairwise():
    from pvraft_tpu.ops.geometry import pairwise_sqdist

    return pairwise_sqdist, (_f32(B, N, 3), _f32(B, M, 3))


@audit_entry("geometry.knn_indices", tags=("op",))
def _e_knn():
    from pvraft_tpu.ops.geometry import knn_indices

    return lambda q, p: knn_indices(q, p, K), (_f32(B, N, 3), _f32(B, M, 3))


@audit_entry("geometry.knn_indices[chunked]", tags=("op",))
def _e_knn_chunked():
    from pvraft_tpu.ops.geometry import knn_indices

    return (
        lambda q, p: knn_indices(q, p, K, chunk=M // 2),
        (_f32(B, N, 3), _f32(B, M, 3)),
    )


@audit_entry("geometry.gather_neighbors", tags=("op",))
def _e_gather():
    from pvraft_tpu.ops.geometry import gather_neighbors

    return gather_neighbors, (_f32(B, M, D), _i32(B, N, K))


@audit_entry("geometry.build_graph", tags=("op",))
def _e_graph():
    from pvraft_tpu.ops.geometry import build_graph

    return lambda pc: build_graph(pc, K), (_f32(B, N, 3),)


# --- ops/corr -------------------------------------------------------------

@audit_entry("corr.corr_volume", tags=("op",))
def _e_corr_volume():
    from pvraft_tpu.ops.corr import corr_volume

    return corr_volume, (_f32(B, N, D), _f32(B, M, D))


@audit_entry("corr.corr_init", tags=("op",))
def _e_corr_init():
    from pvraft_tpu.ops.corr import corr_init

    return (
        lambda f1, f2, x2: corr_init(f1, f2, x2, K),
        (_f32(B, N, D), _f32(B, M, D), _f32(B, M, 3)),
    )


@audit_entry("corr.corr_init[chunked]", tags=("op",))
def _e_corr_init_chunked():
    from pvraft_tpu.ops.corr import corr_init

    return (
        lambda f1, f2, x2: corr_init(f1, f2, x2, K, chunk=M // 2),
        (_f32(B, N, D), _f32(B, M, D), _f32(B, M, 3)),
    )


@audit_entry("corr.knn_lookup", tags=("op",))
def _e_knn_lookup():
    from pvraft_tpu.ops.corr import CorrState, knn_lookup

    state = CorrState(corr=_f32(B, N, K), xyz=_f32(B, N, K, 3))
    return (
        lambda s, rel: knn_lookup(s, rel, K // 2),
        (state, _f32(B, N, K, 3)),
    )


# --- ops/scatter_free (the custom VJPs must TRACE through grad) -----------

@audit_entry("scatter_free.gather_neighbors_onehot[grad]", tags=("op", "grad"))
def _e_sf_gather():
    import jax

    from pvraft_tpu.ops.scatter_free import gather_neighbors_onehot

    def fn(f, i):
        return jax.grad(lambda f_: gather_neighbors_onehot(f_, i).sum())(f)

    return fn, (_f32(B, M, D), _i32(B, N, K))


@audit_entry("scatter_free.take_pair_onehot[grad]", tags=("op", "grad"))
def _e_sf_take_pair():
    import jax

    from pvraft_tpu.ops.scatter_free import take_pair_onehot

    def fn(c, r, nbr):
        def loss(c_, r_):
            kc, rx = take_pair_onehot(c_, r_, nbr)
            return kc.sum() + rx.sum()

        return jax.grad(loss, argnums=(0, 1))(c, r)

    return fn, (_f32(B, N, K), _f32(B, N, K, 3), _i32(B, N, K // 2))


@audit_entry("scatter_free.max_pool_argmax[grad]", tags=("op", "grad"))
def _e_sf_max_pool():
    import jax

    from pvraft_tpu.ops.scatter_free import max_pool_argmax

    def fn(h):
        return jax.grad(lambda h_: max_pool_argmax(h_).sum())(h)

    return fn, (_f32(B, N, K, D),)


# --- ops/voxel + Pallas kernels ------------------------------------------

@audit_entry("voxel.voxel_bin_means", tags=("op",))
def _e_voxel():
    from pvraft_tpu.ops.voxel import voxel_bin_means

    return (
        lambda c, rel: voxel_bin_means(c, rel, 3, 0.25),
        (_f32(B, N, K), _f32(B, N, K, 3)),
    )


@audit_entry("pallas.voxel_bin_means_pallas", tags=("op", "pallas"))
def _e_voxel_pallas():
    from pvraft_tpu.ops.pallas.voxel_corr import voxel_bin_means_pallas

    return (
        lambda c, rel: voxel_bin_means_pallas(c, rel, 3, 0.25),
        (_f32(B, N, K), _f32(B, N, K, 3)),
    )


@audit_entry("pallas.fused_corr_lookup", tags=("op", "pallas"),
             determinism="unique-index-scatter; replay-certified")
def _e_fused():
    from pvraft_tpu.ops.pallas.corr_lookup import fused_corr_lookup

    return (
        lambda c, xyz, co: fused_corr_lookup(c, xyz, co, 3, 0.25, 3, K // 2),
        (_f32(B, N, K), _f32(B, N, K, 3), _f32(B, N, 3)),
    )


# --- parallel/ring (under shard_map; 2 seq shards when the host has the
# devices, so the traced programs CONTAIN the ring ppermutes and the
# deepcheck collective rules check real communication, not a degenerate
# p=1 loop — lint.sh forces an 8-device virtual CPU mesh for this) ------

def _ring_seq() -> int:
    import jax

    return 2 if jax.device_count() >= 2 else 1


@audit_entry("ring.ring_corr_init", tags=("parallel",),
             determinism="ring-fold order fixed by mesh topology")
def _e_ring():
    from jax.sharding import PartitionSpec as P

    from pvraft_tpu.compat import shard_map
    from pvraft_tpu.ops.corr import CorrState
    from pvraft_tpu.parallel.mesh import make_mesh
    from pvraft_tpu.parallel.ring import ring_corr_init

    mesh = make_mesh(n_data=1, n_seq=_ring_seq())

    def fn(f1, f2, x2):
        return shard_map(
            lambda a, b, c: ring_corr_init(a, b, c, K, "seq"),
            mesh=mesh,
            in_specs=(P(None, "seq", None),) * 2 + (P(None, "seq", None),),
            out_specs=CorrState(
                corr=P(None, "seq", None), xyz=P(None, "seq", None, None)
            ),
            check_vma=False,
        )(f1, f2, x2)

    return fn, (_f32(B, N, D), _f32(B, M, D), _f32(B, M, 3))


@audit_entry("ring.ring_knn_indices", tags=("parallel",),
             determinism="ring-fold order fixed by mesh topology")
def _e_ring_knn():
    from jax.sharding import PartitionSpec as P

    from pvraft_tpu.compat import shard_map
    from pvraft_tpu.parallel.mesh import make_mesh
    from pvraft_tpu.parallel.ring import ring_knn_indices

    mesh = make_mesh(n_data=1, n_seq=_ring_seq())

    def fn(query, db):
        return shard_map(
            lambda q, d: ring_knn_indices(q, d, K, "seq"),
            mesh=mesh,
            in_specs=(P(None, "seq", None), P(None, "seq", None)),
            out_specs=P(None, "seq", None),
            check_vma=False,
        )(query, db)

    return fn, (_f32(B, N, 3), _f32(B, M, 3))


# --- models (full forward passes, abstract params included) ---------------

def _model_entry(refine: bool, **cfg_kwargs):
    import jax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.models.raft import PVRaft, PVRaftRefine

    cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2,
                      **cfg_kwargs)
    model = (PVRaftRefine if refine else PVRaft)(cfg)

    # pc2 gets M points and num_iters (T) differs from B: an axis mixup
    # inside the model cannot accidentally type-check (same discipline as
    # the op-level entries).
    def fn(pc1, pc2):
        params = model.init(derive(DEFAULT_SEED, "model.init"), pc1, pc2, 3)
        return model.apply(params, pc1, pc2, 3)

    return fn, (_f32(B, N, 3), _f32(B, M, 3))


@audit_entry("models.PVRaft", tags=("model",),
             determinism="unique-index-scatter; replay-certified")
def _e_pvraft():
    return _model_entry(refine=False)


@audit_entry("models.PVRaftRefine", tags=("model",),
             determinism="unique-index-scatter; replay-certified")
def _e_refine():
    return _model_entry(refine=True)


@audit_entry("models.PVRaft[scatter_free+save_corr]", tags=("model",),
             determinism="unique-index-scatter; replay-certified")
def _e_pvraft_opt():
    # The optimized backward path end to end: scatter-free VJPs +
    # checkpoint_name-tagged corr under the save_corr remat policy.
    return _model_entry(refine=False, scatter_free_vjp=True,
                        remat_policy="save_corr")


# --- engine (the jitted train step, end to end) ---------------------------

@audit_entry("engine.train_step", spmd_group="train-step",
             tags=("train",),
             determinism="unique-index-scatter; replay-certified")
def _e_train_step():
    import jax
    import optax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.steps import make_train_step
    from pvraft_tpu.models.raft import PVRaft

    cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2)
    model = PVRaft(cfg)
    tx = optax.sgd(1e-2)

    def fn(pc1, pc2, mask, gt):
        params = model.init(derive(DEFAULT_SEED, "model.init"), pc1, pc2, 3)
        opt_state = tx.init(params)
        step = make_train_step(model, tx, 0.8, 3)
        batch = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": gt}
        return step(params, opt_state, batch)

    return fn, (_f32(B, N, 3), _f32(B, M, 3), _f32(B, N), _f32(B, N, 3))


@audit_entry("engine.train_step[optimized_backward]",
             precision="bf16_grads", spmd_group="train-step",
             tags=("train", "ab"),
             determinism="unique-index-scatter; replay-certified")
def _e_train_step_opt():
    # Full optimized train step: scatter-free VJPs, dots remat policy,
    # bf16 gradient cast, fused GRU kernel — the bench A/B
    # configuration, traced end to end. The lever values come from the
    # registry's single declaration
    # (programs/geometries.AB_PRIMARY), so the variant bench.py measures
    # and the variant deepcheck walks cannot drift apart.
    import jax
    import optax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.steps import make_train_step
    from pvraft_tpu.models.raft import PVRaft
    from pvraft_tpu.programs.geometries import AB_PRIMARY

    ab = dict(AB_PRIMARY)
    grad_dtype = ab.pop("grad_dtype")
    cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2, **ab)
    model = PVRaft(cfg)
    tx = optax.sgd(1e-2)

    def fn(pc1, pc2, mask, gt):
        params = model.init(derive(DEFAULT_SEED, "model.init"), pc1, pc2, 3)
        opt_state = tx.init(params)
        step = make_train_step(model, tx, 0.8, 3, grad_dtype=grad_dtype)
        batch = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": gt}
        return step(params, opt_state, batch)

    return fn, (_f32(B, N, 3), _f32(B, M, 3), _f32(B, N), _f32(B, N, 3))


@audit_entry("engine.train_step[telemetry]", spmd_group="train-step",
             tags=("train",),
             determinism="unique-index-scatter; replay-certified")
def _e_train_step_telemetry():
    # The telemetry-armed step traces end to end: the in-jit monitors
    # (obs/monitors.py) ride back as an extra metrics leaf.
    import jax
    import optax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.steps import make_train_step
    from pvraft_tpu.models.raft import PVRaft

    cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2)
    model = PVRaft(cfg)
    tx = optax.sgd(1e-2)

    def fn(pc1, pc2, mask, gt):
        params = model.init(derive(DEFAULT_SEED, "model.init"), pc1, pc2, 3)
        opt_state = tx.init(params)
        step = make_train_step(model, tx, 0.8, 3, telemetry=True)
        batch = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": gt}
        return step(params, opt_state, batch)

    return fn, (_f32(B, N, 3), _f32(B, M, 3), _f32(B, N), _f32(B, N, 3))


@audit_entry("engine.refine_train_step", tags=("train",),
             determinism="unique-index-scatter; replay-certified")
def _e_refine_train_step():
    # Stage-2 step variant: frozen backbone, masked-L1 on the single
    # refined flow. In the corpus so deepcheck's donation and precision
    # walks cover the refine path, not just stage 1.
    import jax
    import optax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.steps import make_refine_train_step
    from pvraft_tpu.models.raft import PVRaftRefine

    cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2)
    model = PVRaftRefine(cfg)
    tx = optax.sgd(1e-2)

    def fn(pc1, pc2, mask, gt):
        params = model.init(derive(DEFAULT_SEED, "model.init"), pc1, pc2, 3)
        opt_state = tx.init(params)
        step = make_refine_train_step(model, tx, 3)
        batch = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": gt}
        return step(params, opt_state, batch)

    return fn, (_f32(B, N, 3), _f32(B, M, 3), _f32(B, N), _f32(B, N, 3))


@audit_entry("engine.eval_step", tags=("eval",),
             determinism="unique-index-scatter; replay-certified")
def _e_eval_step():
    # The jitted eval step (no donation by design: params are reused
    # across every val batch) — deepcheck verifies exactly that.
    import jax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.steps import make_eval_step
    from pvraft_tpu.models.raft import PVRaft

    cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2)
    model = PVRaft(cfg)

    def fn(pc1, pc2, mask, gt):
        params = model.init(derive(DEFAULT_SEED, "model.init"), pc1, pc2, 3)
        step = make_eval_step(model, 3, 0.8)
        batch = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": gt}
        return step(params, batch)

    return fn, (_f32(B, N, 3), _f32(B, M, 3), _f32(B, N), _f32(B, N, 3))


@audit_entry("engine.eval_step[refine]", tags=("eval",),
             determinism="unique-index-scatter; replay-certified")
def _e_eval_step_refine():
    import jax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.steps import make_eval_step
    from pvraft_tpu.models.raft import PVRaftRefine

    cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2)
    model = PVRaftRefine(cfg)

    def fn(pc1, pc2, mask, gt):
        params = model.init(derive(DEFAULT_SEED, "model.init"), pc1, pc2, 3)
        step = make_eval_step(model, 3, 0.8, refine=True)
        batch = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": gt}
        return step(params, batch)

    return fn, (_f32(B, N, 3), _f32(B, M, 3), _f32(B, N), _f32(B, N, 3))


# --- serve (the AOT-bucketed predict programs) -----------------------------

def _serve_predict_entry(**model_kwargs):
    """The serve program exactly as the engine compiles it: masked
    forward (padding excluded from GroupNorm stats and the correlation
    truncation), pc1 donated — the one input aliasing the flow output,
    which GJ004/GJ005 verify is a real and sufficient donation."""
    import jax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.models.raft import PVRaft
    from pvraft_tpu.serve.engine import build_predict_fn

    cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2,
                      **model_kwargs)
    model = PVRaft(cfg)
    predict = jax.jit(build_predict_fn(model, 3), donate_argnums=(1,))

    def fn(pc1, pc2, v1, v2):
        params = model.init(derive(DEFAULT_SEED, "model.init"), pc1, pc2, 3)
        return predict(params, pc1, pc2, v1, v2)

    # pc1 and pc2 share one bucket (the serve layout), so both are
    # (B, N, 3) here — unlike the training entries' distinct N/M.
    return fn, (_f32(B, N, 3), _f32(B, N, 3), _bool(B, N), _bool(B, N))


@audit_entry("serve.predict", tags=("serve",),
             determinism="unique-index-scatter; replay-certified")
def _e_serve_predict():
    return _serve_predict_entry()


@audit_entry("serve.predict[bf16]", precision="any", tags=("serve",),
             determinism="unique-index-scatter; replay-certified")
def _e_serve_predict_bf16():
    # bf16 matmul compute is the serve fast path's POINT, not drift, and
    # there is no gradient cast to declare (inference-only program) —
    # "any" is the honest GJ006 intent.
    return _serve_predict_entry(compute_dtype="bfloat16")


@audit_entry("engine.train_step[telemetry_off_jaxpr]",
             tags=("train", "guarantee"),
             determinism="unique-index-scatter; replay-certified")
def _e_train_step_telemetry_off_jaxpr():
    # Guarantee audit (GL009's dynamic twin): with telemetry OFF the
    # train-step jaxpr is byte-identical to the pre-telemetry step body,
    # replicated here verbatim as the golden. The comparison runs at
    # entry-build time (abstract trace only, zero FLOPs); a mismatch
    # raises and the audit reports this entry FAIL.
    import jax
    import optax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.loss import sequence_loss
    from pvraft_tpu.engine.metrics import epe_train
    from pvraft_tpu.engine.steps import make_train_step, maybe_cast_grads
    from pvraft_tpu.models.raft import PVRaft

    cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2)
    model = PVRaft(cfg)
    tx = optax.sgd(1e-2)
    pc1, pc2, mask, gt = (
        _f32(B, N, 3), _f32(B, M, 3), _f32(B, N), _f32(B, N, 3))
    params = jax.eval_shape(
        lambda a, b: model.init(derive(DEFAULT_SEED, "model.init"), a, b, 3),
        pc1, pc2)
    opt_state = jax.eval_shape(tx.init, params)
    batch = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": gt}

    def train_step(params, opt_state, batch):  # name matches: pjit keeps it
        def loss_fn(p):
            flows, _ = model.apply(p, batch["pc1"], batch["pc2"], 3)
            loss = sequence_loss(flows, batch["mask"], batch["flow"], 0.8)
            return loss, flows

        (loss, flows), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = maybe_cast_grads(grads, None)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        epe = epe_train(flows[-1], batch["mask"], batch["flow"])
        return params, opt_state, {"loss": loss, "epe": epe}

    # Both sides identically jitted (donation marks live in the pjit
    # params), so the strings compare the step bodies alone. Embedded
    # object reprs (custom_jvp thunks) carry memory addresses; normalize
    # those — everything else must match byte for byte.
    from pvraft_tpu.analysis.jaxpr.rules import normalize_jaxpr_str

    def jaxpr_str(fn):
        return normalize_jaxpr_str(
            str(jax.make_jaxpr(fn)(params, opt_state, batch)))

    factory_step = make_train_step(model, tx, 0.8, 3, telemetry=False)
    got = jaxpr_str(factory_step)
    want = jaxpr_str(jax.jit(train_step, donate_argnums=(0, 1)))
    if got != want:
        raise AssertionError(
            "telemetry=False train-step jaxpr differs from the "
            "pre-telemetry golden (the default path must be untouched)")

    return lambda p: p["loss"], ({"loss": _f32()},)


def run_audit(verbose: bool = False) -> List[AuditResult]:
    """eval_shape every registered entry. Never raises; failures become
    ``AuditResult(ok=False)`` so one broken op can't hide the rest."""
    import jax

    corpus = entries()
    results: List[AuditResult] = []
    for name in sorted(corpus):
        try:
            fn, args = corpus[name].thunk()
            out = jax.eval_shape(fn, *args)
            shapes = jax.tree_util.tree_map(
                lambda s: tuple(s.shape), out
            )
            detail = f"{shapes}"
            if len(detail) > 160:  # param pytrees dump pages otherwise
                leaves = jax.tree_util.tree_leaves(shapes)
                detail = f"<pytree of {len(leaves)} arrays>"
            results.append(AuditResult(name, True, detail))
        except Exception as e:  # noqa: BLE001 — report, don't crash
            last = traceback.format_exception_only(type(e), e)[-1].strip()
            results.append(AuditResult(name, False, last[:500]))
    if verbose:
        for r in results:
            mark = "PASS" if r.ok else "FAIL"
            print(f"[{mark}] {r.name}: {r.detail}")
    return results
