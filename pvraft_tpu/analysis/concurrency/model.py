"""AST concurrency model: what the GC rules reason over.

Pure stdlib ``ast`` — like the lint engine, this must run in
milliseconds on hosts with no accelerator stack. Per class the model
extracts:

  * **lock fields** — ``self.X = threading.Lock()/RLock()/Condition()``
    (and the sanitizer's ``ordered_lock(...)`` factory), plus
    ``threading.Event()`` and ``queue.Queue()`` fields (thread-safe
    objects the TOCTOU rule cares about);
  * **guarded-by declarations** — a ``# guarded-by: <lock>`` comment on
    (or directly above) a field's assignment line declares which lock
    must be held at every access of that field outside ``__init__``;
  * **inferred guards** — a field written under ``with self.L:`` at two
    or more sites (and never annotated) is inferred guarded-by ``L``;
  * **attribute accesses** — every ``self.X`` read/write with the set
    of class/module locks lexically held at that point (``with``
    nesting; nested ``def``s start with an empty held set, because a
    closure body runs after the enclosing ``with`` exits);
  * **thread spawns** — ``threading.Thread(...)`` calls with their
    ``daemon=`` flag and ``target=``, so reachability ("does this class
    run code on more than one thread") and the un-joined-thread rule
    need no runtime;
  * **lock-order edges** — lock B acquired while A is held, both from
    lexically nested ``with`` blocks and through intra-class
    ``self.method()`` calls under a lock (transitive, depth-bounded),
    plus cross-class edges where a field's class is known from a
    constructor call in the same scanned set.

Everything here is deliberately under-approximate (no cross-module call
graph, no alias analysis): like the AST lint, a gate that only flags
certainties gets kept.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from pvraft_tpu.analysis.engine import _comment_tokens

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# Constructor spellings that make a field a lock / event / queue. Names
# are matched on the callee's dotted tail so `threading.Lock`, a bare
# `Lock` import, and the sanitizer factory all count.
_LOCK_CTORS = {"Lock", "RLock", "Condition", "ordered_lock", "OrderedLock"}
_EVENT_CTORS = {"Event"}
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")


def _dotted_tail(expr: ast.AST) -> str:
    """Last component of a dotted callee (``threading.Lock`` -> "Lock")."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _self_attr(expr: ast.AST) -> Optional[str]:
    """``X`` when ``expr`` is exactly ``self.X``, else None."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


def _is_thread_join(node: ast.Call) -> bool:
    """Does this call look like ``thread.join([timeout])``? String and
    path joins (``", ".join(parts)``, ``os.path.join(a, b)``) must NOT
    count — one of those anywhere in a class would silence GC004 for
    every spawn in it. Thread joins take no argument, a single numeric
    timeout, or ``timeout=``: anything else (an iterable positional, a
    ``.path.`` receiver, a string-literal receiver) is treated as a
    non-thread join. Deliberately under-approximate in the direction
    that keeps GC004 ARMED."""
    func = node.func
    if not (isinstance(func, ast.Attribute) and func.attr == "join"):
        return False
    recv = func.value
    if isinstance(recv, ast.Constant):
        return False  # "sep".join(...)
    if isinstance(recv, ast.Attribute) and recv.attr == "path":
        return False  # os.path.join(...)
    if len(node.args) > 1:
        return False
    if node.args:
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, (int, float))):
            return False
    if any(kw.arg != "timeout" for kw in node.keywords):
        return False
    return True


@dataclasses.dataclass(frozen=True)
class Access:
    """One ``self.X`` touch: where, from which method, read or write,
    and which locks were lexically held."""

    attr: str
    line: int
    col: int
    method: str
    write: bool
    held: FrozenSet[str]


@dataclasses.dataclass(frozen=True)
class ThreadSpawn:
    """One ``threading.Thread(...)`` call site."""

    line: int
    col: int
    method: str            # "" for module level
    daemon: Optional[bool]  # None = keyword absent
    target: Optional[str]   # "X" for target=self.X, bare name otherwise


@dataclasses.dataclass(frozen=True)
class OrderEdge:
    """Lock ``a`` held while lock ``b`` is acquired (names are
    class-qualified: ``MicroBatcher._count_lock``)."""

    a: str
    b: str
    line: int
    col: int
    via: str  # "nested-with" | "call:<method chain>"


@dataclasses.dataclass
class ClassModel:
    name: str
    node: ast.ClassDef
    locks: Dict[str, int] = dataclasses.field(default_factory=dict)
    events: Dict[str, int] = dataclasses.field(default_factory=dict)
    queues: Dict[str, int] = dataclasses.field(default_factory=dict)
    # attr -> (lock attr, declaration line) from `# guarded-by:` comments.
    guards: Dict[str, Tuple[str, int]] = dataclasses.field(
        default_factory=dict)
    accesses: List[Access] = dataclasses.field(default_factory=list)
    spawns: List[ThreadSpawn] = dataclasses.field(default_factory=list)
    joins: int = 0  # thread-join call sites (see _is_thread_join)
    methods: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)
    # method -> locks it acquires anywhere in its own body (not callees).
    method_locks: Dict[str, Set[str]] = dataclasses.field(
        default_factory=dict)
    # method -> self-methods it calls (intra-class call graph).
    calls: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    # (held lock, called self-method, line, col) — call made under a lock.
    calls_under: List[Tuple[str, str, int, int]] = dataclasses.field(
        default_factory=list)
    # Lexically nested with-acquisitions: (outer, inner, line, col).
    nested_withs: List[Tuple[str, str, int, int]] = dataclasses.field(
        default_factory=list)
    # field -> class name, from `self.Y = ClassName(...)`.
    field_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    # (held lock, field, method-called-on-field, line, col).
    field_calls_under: List[Tuple[str, str, str, int, int]] = \
        dataclasses.field(default_factory=list)

    @property
    def concurrent(self) -> bool:
        """Does this class intend concurrency? Owning a lock or spawning
        a thread is the evidence; classes with neither are skipped by
        every GC rule (a single-threaded class cannot race)."""
        return bool(self.locks) or bool(self.spawns)

    def guard_of(self, attr: str) -> Optional[str]:
        """Declared guard lock of ``attr`` (annotations only)."""
        entry = self.guards.get(attr)
        return entry[0] if entry else None

    def inferred_guards(self) -> Dict[str, str]:
        """attr -> lock for UNANNOTATED fields the class itself treats
        as lock-guarded: >= 2 non-``__init__`` access sites hold the
        same class lock and at least one of them is a write. The rule
        layer flags the *outlier* unlocked writes of such fields (an
        unlocked read of a flag is a benign-racy idiom; an unlocked
        write to a field that is elsewhere lock-disciplined is almost
        always the bug). Fields disciplined under two different locks
        are ambiguous and skipped — annotate those explicitly."""
        per_attr: Dict[str, List[Access]] = {}
        for acc in self.accesses:
            if acc.method.split(".")[0] == "__init__":
                continue
            if acc.attr in self.guards or acc.attr in self.locks \
                    or acc.attr in self.events or acc.attr in self.queues:
                continue
            per_attr.setdefault(acc.attr, []).append(acc)
        out: Dict[str, str] = {}
        for attr, accs in per_attr.items():
            by_lock: Dict[str, List[Access]] = {}
            for a in accs:
                for lock in a.held & set(self.locks):
                    by_lock.setdefault(lock, []).append(a)
            candidates = {
                lock: under for lock, under in by_lock.items()
                if len(under) >= 2 and any(a.write for a in under)
            }
            if len(candidates) == 1:
                out[attr] = next(iter(candidates))
        return out

    def transitive_locks(self, method: str, depth: int = 4) -> Set[str]:
        """Locks ``method`` may acquire through intra-class calls."""
        seen: Set[str] = set()
        frontier = {method}
        for _ in range(depth):
            nxt: Set[str] = set()
            for m in frontier:
                if m in seen:
                    continue
                seen.add(m)
                nxt |= self.calls.get(m, set())
            frontier = nxt - seen
            if not frontier:
                break
        locks: Set[str] = set()
        for m in seen:
            locks |= self.method_locks.get(m, set())
        return locks

    def thread_entry_methods(self) -> Set[str]:
        """Methods that run on a spawned thread (``target=self.X``),
        expanded transitively through intra-class calls."""
        entries = {s.target for s in self.spawns
                   if s.target and s.target in self.methods}
        seen: Set[str] = set()
        frontier = set(entries)
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            frontier |= self.calls.get(m, set()) - seen
        return seen


@dataclasses.dataclass
class ModuleModel:
    path: str
    classes: List[ClassModel] = dataclasses.field(default_factory=list)
    module_locks: Set[str] = dataclasses.field(default_factory=set)
    module_spawns: List[ThreadSpawn] = dataclasses.field(
        default_factory=list)
    module_joins: int = 0

    def class_named(self, name: str) -> Optional[ClassModel]:
        for c in self.classes:
            if c.name == name:
                return c
        return None


def _guard_comments(source: str) -> Dict[int, Tuple[str, bool]]:
    """line -> (lock name, own_line) for every real ``# guarded-by:``
    comment token (docstring examples must not declare anything — same
    discipline as the suppression pragmas). ``own_line`` is True for a
    comment-only line: only those may annotate the assignment BELOW
    them — a trailing comment binds to its own line exclusively, so it
    cannot leak onto the next field."""
    lines = source.splitlines()
    out: Dict[int, Tuple[str, bool]] = {}
    for lineno, text in _comment_tokens(source):
        m = _GUARD_RE.search(text)
        if m:
            own = (0 < lineno <= len(lines)
                   and lines[lineno - 1].lstrip().startswith("#"))
            out[lineno] = (m.group(1), own)
    return out


class _MethodWalker:
    """Walks one method body tracking the lexically held lock set."""

    def __init__(self, cls: ClassModel, module: "ModuleModel",
                 method: str):
        self.cls = cls
        self.module = module
        self.method = method
        self.own_locks: Set[str] = set()

    # -- classification helpers --------------------------------------------

    def _lock_name(self, expr: ast.AST) -> Optional[str]:
        """Held-set name for a with-item context expr: a class lock
        field (``self.L``) or a module-level lock (bare name)."""
        attr = _self_attr(expr)
        if attr is not None and attr in self.cls.locks:
            return attr
        if isinstance(expr, ast.Name) and expr.id in self.module.module_locks:
            return expr.id
        return None

    def _is_write(self, node: ast.Attribute) -> bool:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        parent = getattr(node, "_gc_parent", None)
        # `self.X[i] = v` / `self.X[i] += v`: the attribute loads but the
        # object mutates — counts as a write for guard purposes.
        if isinstance(parent, ast.Subscript) and isinstance(
                parent.ctx, (ast.Store, ast.Del)):
            return True
        if isinstance(parent, ast.AugAssign) and parent.target is node:
            return True
        return False

    # -- the walk -----------------------------------------------------------

    def walk(self, stmts, held: FrozenSet[str]) -> None:
        for stmt in stmts:
            self._stmt(stmt, held)

    def _stmt(self, node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, _FUNC_NODES):
            # A nested def's body runs AFTER the enclosing with exits:
            # it starts with nothing held, under a qualified name.
            sub = _MethodWalker(self.cls, self.module,
                                f"{self.method}.{node.name}")
            sub.walk(node.body, frozenset())
            self.own_locks |= sub.own_locks
            # Nested closures fold into the enclosing method's call/lock
            # book-keeping (they are reachable from it).
            self.cls.method_locks.setdefault(self.method, set()).update(
                sub.own_locks)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                self._expr(item.context_expr, held)
                name = self._lock_name(item.context_expr)
                if name is not None:
                    acquired.append(name)
            if acquired:
                self.own_locks.update(acquired)
                self.cls.method_locks.setdefault(
                    self.method, set()).update(acquired)
                for h in held:
                    for a in acquired:
                        if h != a:
                            self.cls.nested_withs.append(
                                (h, a, node.lineno, node.col_offset))
                # `with self.a, self.b:` acquires left-to-right — a real
                # a-before-b constraint, same as lexical nesting.
                for i, a in enumerate(acquired):
                    for b in acquired[i + 1:]:
                        if a != b:
                            self.cls.nested_withs.append(
                                (a, b, node.lineno, node.col_offset))
            self.walk(node.body, held | frozenset(acquired))
            return
        # Generic statement: record expressions at this held set, then
        # recurse into child statements with the same held set.
        for field_name, value in ast.iter_fields(node):
            if field_name in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.AST):
                self._expr(value, held)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.AST) and not isinstance(
                            v, ast.stmt):
                        self._expr(v, held)
        for child_field in ("body", "orelse", "finalbody"):
            self.walk(getattr(node, child_field, []) or [], held)
        for handler in getattr(node, "handlers", []) or []:
            self.walk(handler.body, held)

    def _expr(self, expr: ast.AST, held: FrozenSet[str]) -> None:
        for node in ast.walk(expr):
            for child in ast.iter_child_nodes(node):
                child._gc_parent = node  # type: ignore[attr-defined]
        # ast.walk descends into lambda bodies with the current held set
        # — over-approximate for code that runs later, which can only
        # hide a finding, never invent one. Real nested defs are handled
        # statement-side with a fresh empty held set.
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is not None:
                    self.cls.accesses.append(Access(
                        attr=attr, line=node.lineno, col=node.col_offset,
                        method=self.method, write=self._is_write(node),
                        held=held))
            if isinstance(node, ast.Call):
                if _is_thread_join(node):
                    self.cls.joins += 1
                self._call(node, held)

    def _call(self, node: ast.Call, held: FrozenSet[str]) -> None:
        tail = _dotted_tail(node.func)
        if tail == "Thread":
            daemon: Optional[bool] = None
            target: Optional[str] = None
            for kw in node.keywords:
                if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                    daemon = bool(kw.value.value)
                elif kw.arg == "target":
                    t = _self_attr(kw.value)
                    if t is None and isinstance(kw.value, ast.Name):
                        t = kw.value.id
                    elif t is None and isinstance(kw.value, ast.Attribute):
                        # self.httpd.serve_forever -> outermost attr name
                        t = kw.value.attr
                    target = t
            self.cls.spawns.append(ThreadSpawn(
                line=node.lineno, col=node.col_offset, method=self.method,
                daemon=daemon, target=target))
            return
        func = node.func
        if isinstance(func, ast.Attribute):
            # Call-graph/lock bookkeeping keys on the ROOT method name:
            # a closure defined inside `seal` is reachable from `seal`.
            root = self.method.split(".", 1)[0]
            owner = _self_attr(func.value)
            if owner is not None:
                # self.<owner>.<method>(...) — a call on a field.
                self.cls.field_calls_under.extend(
                    (h, owner, func.attr, node.lineno, node.col_offset)
                    for h in held)
                return
            callee = _self_attr(func)
            if callee is not None:
                # self.<callee>(...) — intra-class call.
                self.cls.calls.setdefault(root, set()).add(callee)
                for h in held:
                    self.cls.calls_under.append(
                        (h, callee, node.lineno, node.col_offset))


def build_module_model(tree: ast.Module, source: str,
                       path: str) -> ModuleModel:
    """Extract the concurrency model of one parsed module."""
    module = ModuleModel(path=path)
    guards_by_line = _guard_comments(source)

    # Module-level locks/spawns/joins (outside any class body).
    class_node_ids: Set[int] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef):
            for n in ast.walk(stmt):
                class_node_ids.add(id(n))
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call):
            tail = _dotted_tail(stmt.value.func)
            if tail in _LOCK_CTORS:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        module.module_locks.add(t.id)
    for node in ast.walk(tree):
        if id(node) in class_node_ids:
            continue
        if isinstance(node, ast.Call) and _dotted_tail(node.func) == "Thread":
            daemon = None
            target = None
            for kw in node.keywords:
                if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                    daemon = bool(kw.value.value)
                elif kw.arg == "target" and isinstance(kw.value, ast.Name):
                    target = kw.value.id
            module.module_spawns.append(ThreadSpawn(
                line=node.lineno, col=node.col_offset, method="",
                daemon=daemon, target=target))
        if isinstance(node, ast.Call) and _is_thread_join(node):
            module.module_joins += 1

    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef):
            module.classes.append(
                _build_class(stmt, module, guards_by_line))
    return module


def _build_class(node: ast.ClassDef, module: ModuleModel,
                 guards_by_line: Dict[int, Tuple[str, bool]]) -> ClassModel:
    cls = ClassModel(name=node.name, node=node)

    # Pass 1: field classification + guarded-by declarations, from every
    # `self.X = <ctor>()` in every method (locks are almost always born
    # in __init__, but lazily created fields count too). AnnAssign covers
    # the `self.rejected: Dict[str, int] = {}` spelling.
    for fn in ast.walk(node):
        if isinstance(fn, ast.Assign):
            targets = fn.targets
            value = fn.value
        elif isinstance(fn, ast.AnnAssign) and fn.value is not None:
            targets = [fn.target]
            value = fn.value
        else:
            continue
        for target in targets:
            attr = _self_attr(target)
            if attr is None:
                continue
            if isinstance(value, ast.Call):
                tail = _dotted_tail(value.func)
                if tail in _LOCK_CTORS:
                    cls.locks.setdefault(attr, fn.lineno)
                elif tail in _EVENT_CTORS:
                    cls.events.setdefault(attr, fn.lineno)
                elif tail in _QUEUE_CTORS:
                    cls.queues.setdefault(attr, fn.lineno)
                elif tail and tail[0].isupper():
                    cls.field_types.setdefault(attr, tail)
            entry = guards_by_line.get(fn.lineno)
            if entry is None:
                above = guards_by_line.get(fn.lineno - 1)
                if above is not None and above[1]:
                    entry = above  # comment-only line annotating below
            if entry is not None:
                cls.guards.setdefault(attr, (entry[0], fn.lineno))

    # Pass 2: per-method held-lock walk.
    for stmt in node.body:
        if isinstance(stmt, _FUNC_NODES):
            cls.methods[stmt.name] = stmt
            walker = _MethodWalker(cls, module, stmt.name)
            walker.walk(stmt.body, frozenset())
            cls.method_locks.setdefault(stmt.name, set()).update(
                walker.own_locks)
    return cls
