"""Runtime lock-order sanitizer: the dynamic half of threadcheck.

:class:`OrderedLock` wraps ``threading.Lock`` with a per-thread
acquisition stack and a process-wide order graph: the first time lock B
is acquired while A is held, the edge A->B is recorded with its call
site; a later attempt to acquire A while B is held is an order
inversion — the exact shape that deadlocks under the right interleaving
— and raises :class:`LockOrderError` naming both sites *before*
blocking on the lock (a sanitizer that deadlocks while reporting a
deadlock would be satire). Recursive acquisition of the same
non-reentrant lock by one thread (guaranteed self-deadlock) raises too.

Opt-in mirrors ``@shapecheck`` (``analysis/contracts.py``): the
:func:`ordered_lock` factory returns a plain ``threading.Lock`` unless
``PVRAFT_CHECKS=1``, so production/serving pays zero overhead — no
wrapper object, no indirection — while any test run with checks on
turns every adopted serve/obs lock into a sanitizer probe. The threaded
tier-1 tests (batcher no-HOL, pool, retrace, drain races) thereby
double as a lock-order sanitizer pass:

    PVRAFT_CHECKS=1 python -m pytest tests/test_serve.py tests/test_serve_pool.py

Non-blocking acquires (``blocking=False``) neither raise on inversion
nor record an order edge for the lock being try-acquired: a trylock
cannot wait, so it cannot complete a deadlock cycle — constraining the
opposite (blocking) order on its account would flag deadlock-free code.
A trylock-HELD lock still constrains later blocking acquires normally:
the held stack does not care how a lock was won.
"""

from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Tuple

from pvraft_tpu.analysis.contracts import checks_enabled


class LockOrderError(RuntimeError):
    """Two locks acquired in opposite orders by different code paths
    (deadlock-prone), or one non-reentrant lock acquired recursively
    (deadlock-certain)."""


# Process-wide order graph: (held_name, acquired_name) -> first-seen
# call site. One plain lock guards it — the graph lock is leaf-only
# (nothing is acquired under it), so it cannot itself invert.
_GRAPH_LOCK = threading.Lock()
_EDGES: Dict[Tuple[str, str], str] = {}

_HELD = threading.local()


def _held_stack() -> List["OrderedLock"]:
    stack = getattr(_HELD, "stack", None)
    if stack is None:
        stack = _HELD.stack = []
    return stack


def _call_site() -> str:
    """The acquiring frame outside this module — what the error report
    and the order graph anchor to."""
    for frame in reversed(traceback.extract_stack(limit=16)):
        if not frame.filename.endswith("sanitizer.py"):
            return f"{frame.filename}:{frame.lineno} in {frame.name}"
    return "<unknown>"


def order_edges() -> Dict[Tuple[str, str], str]:
    """Snapshot of the observed acquisition-order graph (tests assert
    on it; the keys read "held -> acquired")."""
    with _GRAPH_LOCK:
        return dict(_EDGES)


def reset_order_graph() -> None:
    """Forget every recorded edge (test isolation only — a live process
    must keep its history, or an inversion across test phases hides)."""
    with _GRAPH_LOCK:
        _EDGES.clear()


class OrderedLock:
    """``threading.Lock`` with acquisition-order recording.

    Drop-in for the subset of the Lock API this codebase uses:
    ``with``-statement, ``acquire(blocking=, timeout=)``, ``release()``,
    ``locked()``. ``name`` should be globally descriptive
    (``"MicroBatcher._count_lock"``) — the order graph and error
    messages are keyed on it, and two instances sharing a name share an
    order node (what you want for per-instance locks of the same class).
    """

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def _check_order(self, blocking: bool) -> None:
        stack = _held_stack()
        if not stack:
            return
        if any(h is self for h in stack):
            raise LockOrderError(
                f"recursive acquisition of non-reentrant lock "
                f"{self.name!r} at {_call_site()} — this thread already "
                f"holds it (guaranteed self-deadlock)")
        if not blocking:
            # A trylock never waits: it can neither complete a deadlock
            # cycle itself nor justify failing the opposite blocking
            # order — no raise, no recorded edge. (Locks it WON stay on
            # the held stack and constrain later blocking acquires.)
            return
        site = _call_site()
        with _GRAPH_LOCK:
            for held in stack:
                if held.name == self.name:
                    # Same-name, different-object nesting (two instances
                    # of one class): a real order exists but the name
                    # graph cannot express it without a self-loop; skip
                    # rather than lie.
                    continue
                inverse = _EDGES.get((self.name, held.name))
                if inverse is not None:
                    raise LockOrderError(
                        f"lock-order inversion: acquiring {self.name!r} "
                        f"while holding {held.name!r} at {site}, but the "
                        f"opposite order ({self.name!r} -> {held.name!r}) "
                        f"was taken at {inverse} — two threads running "
                        f"these paths concurrently deadlock")
                _EDGES.setdefault((held.name, self.name), site)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check_order(blocking)
        got = self._lock.acquire(blocking, timeout)
        if got:
            _held_stack().append(self)
        return got

    def release(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> Optional[bool]:
        self.release()
        return None

    def __repr__(self) -> str:
        return f"OrderedLock({self.name!r})"


def ordered_lock(name: str):
    """The adoption point: a plain ``threading.Lock`` when checks are
    off (zero overhead — the production path), an :class:`OrderedLock`
    under ``PVRAFT_CHECKS=1``. Evaluated per call, so a lock built
    inside a test that sets the env var is instrumented even though the
    module imported earlier."""
    if checks_enabled():
        return OrderedLock(name)
    return threading.Lock()
