"""threadcheck — concurrency analysis for the hand-threaded planes.

The serving/observability planes are ~5k LoC of hand-threaded Python
(collector/executor/monitor threads, 10+ locks, bounded queues) where
every known race so far was found by ad-hoc manual review (CHANGES.md
PRs 5/8/9). This package encodes that review checklist as the repo's
THIRD analysis engine, beside the AST lint (``analysis.rules``) and the
jaxpr deepcheck (``analysis.jaxpr``):

  * a **static half** (``model.py`` + ``rules.py`` + ``check.py``):
    a guarded-by model — ``# guarded-by: <lock>`` field annotations
    plus AST inference from ``with self._lock:`` bodies — feeding rules
    GC001+ (guarded attributes accessed outside their lock, lock-order
    cycles, check-then-act/TOCTOU shapes, un-joined non-daemon
    threads), run as ``python -m pvraft_tpu.analysis concurrency`` over
    ``serve/``, ``obs/`` and ``data/loader.py``;

  * a **dynamic half** (``sanitizer.py``): an instrumented
    :class:`OrderedLock` that records each thread's acquisition stack
    and raises on lock-order inversions. Opt-in via ``PVRAFT_CHECKS=1``
    exactly like ``@shapecheck`` — the serve/obs locks are built
    through :func:`ordered_lock`, so the existing threaded tier-1 tests
    double as a runtime lock-order sanitizer run when checks are on,
    and cost a plain ``threading.Lock`` when they are off.

Diagnostics reuse :class:`pvraft_tpu.analysis.engine.Diagnostic` and
the one ``# graftlint: disable=GCxxx -- reason`` pragma grammar, so the
suppression-debt report (``lint --stats``) counts GC blind spots with
no second parser. Like the AST lint, the static half never imports jax.
"""

from pvraft_tpu.analysis.concurrency.check import (  # noqa: F401
    DEFAULT_SCOPE,
    check_paths,
    check_source,
)
from pvraft_tpu.analysis.concurrency.sanitizer import (  # noqa: F401
    LockOrderError,
    OrderedLock,
    ordered_lock,
)
