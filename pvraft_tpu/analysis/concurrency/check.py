"""Concurrency check driver: files -> model -> GC rules -> diagnostics.

Mirrors ``engine.lint_paths`` deliberately: same ``Diagnostic`` type,
same ``# graftlint: disable=GCxxx -- reason`` suppression grammar (one
parser — what ``lint --stats`` counts is exactly what is honored here),
same stable ordering. Scope defaults to the hand-threaded planes the
rules were written for: ``serve/``, ``obs/`` and ``data/loader.py``
(``DEFAULT_SCOPE``), resolved relative to the installed package so
``python -m pvraft_tpu.analysis concurrency`` works from any cwd.
"""

from __future__ import annotations

import ast
import os
from typing import List, Sequence, Tuple

from pvraft_tpu.analysis.engine import (
    Diagnostic,
    _expand_decorated_regions,
    _suppressed,
    _suppressions,
    iter_py_files,
)
from pvraft_tpu.analysis.concurrency.model import build_module_model
from pvraft_tpu.analysis.concurrency.rules import (
    ConcurrencyContext,
    all_concurrency_rules,
)


def default_scope() -> Tuple[str, ...]:
    """The gate's scan scope, as absolute paths of this checkout."""
    import pvraft_tpu

    pkg = os.path.dirname(os.path.abspath(pvraft_tpu.__file__))
    return (
        os.path.join(pkg, "serve"),
        os.path.join(pkg, "fleet"),
        os.path.join(pkg, "obs"),
        os.path.join(pkg, "data", "loader.py"),
    )


# Spelled as a constant for docs/tests; resolved lazily by the CLI so
# importing this module never imports the full package tree.
DEFAULT_SCOPE = ("pvraft_tpu/serve", "pvraft_tpu/fleet", "pvraft_tpu/obs",
                 "pvraft_tpu/data/loader.py")


def check_source(source: str, path: str = "<string>",
                 rule_ids: Sequence[str] = ()) -> List[Diagnostic]:
    """Run the GC rules over one source string (suppressions applied)."""
    source = source.lstrip("\ufeff")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Diagnostic(path, e.lineno or 1, e.offset or 0, "GC000",
                           f"syntax error: {e.msg}")]
    model = build_module_model(tree, source, path)
    ctx = ConcurrencyContext(path, source, tree, model)
    per_line, file_ids = _suppressions(source)
    _expand_decorated_regions(tree, per_line)
    out: List[Diagnostic] = []
    for rule_cls in all_concurrency_rules():
        if rule_ids and rule_cls.id not in rule_ids:
            continue
        for d in rule_cls().check(ctx):
            if not _suppressed(d, per_line, file_ids):
                out.append(d)
    out.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return out


def check_paths(paths: Sequence[str], rule_ids: Sequence[str] = ()
                ) -> Tuple[List[Diagnostic], int]:
    """Check files/directories. Returns (diagnostics, files_checked)."""
    out: List[Diagnostic] = []
    n = 0
    for f in iter_py_files(paths):
        n += 1
        with open(f, "r", encoding="utf-8-sig") as fh:
            out.extend(check_source(fh.read(), path=f, rule_ids=rule_ids))
    out.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return out, n
