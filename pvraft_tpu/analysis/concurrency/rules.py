"""threadcheck rules GC001-GC004 — the review-found race shapes, encoded.

Every one of these patterns was found (and fixed) by hand at least once
in CHANGES.md PRs 5/8/9 before this engine existed; the red fixture
corpus under ``tests/fixtures/threadcheck/`` pins each historical race
to the rule that now detects it. Suppress with
``# graftlint: disable=GCxxx -- reason`` (shared pragma grammar;
reason-less suppressions fail ``lint --stats``).

Scope discipline (mirrors the AST lint): rules only fire inside classes
the model can PROVE intend concurrency — owning a lock or spawning a
thread — and only on ``self.X`` fields it can resolve. No cross-module
call graph, no alias analysis: flag certainties, keep the gate.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple, Type

from pvraft_tpu.analysis.engine import Diagnostic, LintContext, Rule
from pvraft_tpu.analysis.concurrency.model import (
    ClassModel,
    ModuleModel,
    _self_attr,
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class ConcurrencyContext(LintContext):
    """LintContext + the extracted concurrency model."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 model: ModuleModel):
        super().__init__(path, source, tree)
        self.model = model


class ConcurrencyRule(Rule):
    """Base for GC rules: sees one file's :class:`ConcurrencyContext`."""

    def check(self, ctx: ConcurrencyContext) -> Iterable[Diagnostic]:
        raise NotImplementedError


_GC_REGISTRY: List[Type[ConcurrencyRule]] = []


def gc_register(cls: Type[ConcurrencyRule]) -> Type[ConcurrencyRule]:
    if not cls.id or not cls.title:
        raise ValueError(f"rule {cls.__name__} must set id and title")
    if any(r.id == cls.id for r in _GC_REGISTRY):
        raise ValueError(f"duplicate rule id {cls.id}")
    _GC_REGISTRY.append(cls)
    return cls


def all_concurrency_rules() -> Tuple[Type[ConcurrencyRule], ...]:
    return tuple(sorted(_GC_REGISTRY, key=lambda r: r.id))


# --- GC001 ----------------------------------------------------------------

@gc_register
class GuardedFieldOutsideLock(ConcurrencyRule):
    """Guarded field accessed without its lock.

    A field declared ``# guarded-by: <lock>`` on its assignment line must
    be read AND written with that lock held everywhere outside
    ``__init__`` (construction happens-before thread start). Fields never
    annotated but written under exactly one ``with self.L:`` at 2+ sites
    get the guard INFERRED — for those, only unlocked *writes* are
    flagged (an unlocked read of a flag is a benign-racy idiom; an
    unlocked write to a field that is elsewhere lock-disciplined is
    almost always the bug — the ``in_flight`` identity and
    ``record_submit`` races were exactly this shape, CHANGES.md PR 5/8).
    """

    id = "GC001"
    title = "guarded-field-outside-lock"

    def check(self, ctx: ConcurrencyContext) -> Iterable[Diagnostic]:
        for cls in ctx.model.classes:
            if not cls.concurrent:
                continue
            inferred = cls.inferred_guards()
            for acc in cls.accesses:
                if acc.method.split(".")[0] == "__init__":
                    continue
                declared = cls.guard_of(acc.attr)
                if declared is not None:
                    if declared not in acc.held:
                        yield Diagnostic(
                            ctx.path, acc.line, acc.col, self.id,
                            f"`self.{acc.attr}` is declared guarded-by "
                            f"`{declared}` but accessed in "
                            f"`{cls.name}.{acc.method}` without holding "
                            f"it; wrap the access in `with self."
                            f"{declared}:` (or fix the annotation)")
                    continue
                lock = inferred.get(acc.attr)
                if lock is not None and acc.write and lock not in acc.held:
                    yield Diagnostic(
                        ctx.path, acc.line, acc.col, self.id,
                        f"`self.{acc.attr}` is written under `with self."
                        f"{lock}:` everywhere else in `{cls.name}` but "
                        f"written here ({acc.method}) without it — either "
                        f"take the lock or annotate the field's intent "
                        f"with `# guarded-by:`")


# --- GC002 ----------------------------------------------------------------

def _lock_order_edges(model: ModuleModel,
                      classes_by_name: Dict[str, ClassModel],
                      ) -> List[Tuple[str, str, int, int, str]]:
    """(a, b, line, col, via) edges of the acquisition-order graph for
    one module, lock names class-qualified."""
    edges: List[Tuple[str, str, int, int, str]] = []
    for cls in model.classes:
        q = f"{cls.name}."
        for a, b, line, col in cls.nested_withs:
            edges.append((q + a, q + b, line, col, "nested with"))
        for held, callee, line, col in cls.calls_under:
            for lock in cls.transitive_locks(callee):
                if lock != held:
                    edges.append((q + held, q + lock, line, col,
                                  f"call self.{callee}()"))
        for held, field, meth, line, col in cls.field_calls_under:
            target_cls = classes_by_name.get(cls.field_types.get(field, ""))
            if target_cls is None:
                continue
            locks = target_cls.method_locks.get(meth, set()) | \
                target_cls.transitive_locks(meth)
            for lock in locks:
                edges.append((q + held, f"{target_cls.name}.{lock}",
                              line, col,
                              f"call self.{field}.{meth}()"))
    return edges


def _find_cycle(graph: Dict[str, Set[str]]) -> Optional[List[str]]:
    """One cycle as a node list [a, b, ..., a], or None."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GREY
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, WHITE) == GREY:
                return stack[stack.index(m):] + [m]
            if color.get(m, WHITE) == WHITE:
                found = dfs(m)
                if found:
                    return found
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            found = dfs(n)
            if found:
                return found
    return None


@gc_register
class LockOrderCycle(ConcurrencyRule):
    """Cycle in the lock-acquisition-order graph.

    Two code paths taking the same pair of locks in opposite orders
    deadlock under the right interleaving. The graph covers lexically
    nested ``with`` blocks, intra-class ``self.method()`` calls made
    under a lock (transitive), and calls on fields whose class is known
    from a constructor in the scanned set. The runtime complement is the
    ``OrderedLock`` sanitizer (``analysis/concurrency/sanitizer.py``),
    which sees the orders the AST cannot (cross-object, cross-module).
    """

    id = "GC002"
    title = "lock-order-cycle"

    def check(self, ctx: ConcurrencyContext) -> Iterable[Diagnostic]:
        classes_by_name = {c.name: c for c in ctx.model.classes}
        edges = _lock_order_edges(ctx.model, classes_by_name)
        graph: Dict[str, Set[str]] = {}
        sites: Dict[Tuple[str, str], Tuple[int, int, str]] = {}
        for a, b, line, col, via in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
            sites.setdefault((a, b), (line, col, via))
        cycle = _find_cycle(graph)
        if cycle is None:
            return
        line, col, via = sites[(cycle[0], cycle[1])]
        yield Diagnostic(
            ctx.path, line, col, self.id,
            "lock-order cycle: " + " -> ".join(cycle) +
            f" (this edge via {via}); two threads walking opposite arcs "
            "of this cycle deadlock — pick one global order and take "
            "the locks in it everywhere")


# --- GC003 ----------------------------------------------------------------

_QUEUE_CHECKS = {"full", "empty", "qsize"}
_QUEUE_ACTS = {"put", "put_nowait", "get", "get_nowait"}
# After an event check only the PRODUCER side is a race: `if not
# stopped: q.put(...)` accepts work a concurrent shutdown never drains.
# `while not stopped: q.get(timeout=...)` is the benign consumer idiom —
# the get is atomic and an extra consumed item is the drain sweep's job.
_EVENT_GATED_ACTS = {"put", "put_nowait"}
_EVENT_CHECKS = {"is_set"}
_EVENT_ACTS = {"set", "clear"}


def _method_attr_call(expr: ast.AST, attrs: Dict[str, int],
                      names: Iterable[str]) -> Optional[Tuple[str, str]]:
    """(field, method) when ``expr`` contains ``self.<field>.<m>()`` with
    field in ``attrs`` and m in ``names``."""
    for node in ast.walk(expr):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in names):
            field = _self_attr(node.func.value)
            if field is not None and field in attrs:
                return field, node.func.attr
    return None


def _plain_attr_test(expr: ast.AST) -> Optional[str]:
    """Field X when the test is (or contains, via and/or/not) a
    None-compare or truth-test of a bare ``self.X`` (``self.X is
    None``, ``not self.X``, ``if self.X``, ``if a or self.X is not
    None``)."""
    node = expr
    while isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
        node = node.operand
    if isinstance(node, ast.BoolOp):
        for value in node.values:
            attr = _plain_attr_test(value)
            if attr is not None:
                return attr
        return None
    if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        for cand in [node.left, *node.comparators]:
            attr = _self_attr(cand)
            if attr is not None:
                return attr
        return None
    return _self_attr(node)


@gc_register
class CheckThenAct(ConcurrencyRule):
    """Check-then-act (TOCTOU) on shared state without a lock.

    Between an unlocked check and the action it gates, another thread
    can invalidate the check: ``if not stopping.is_set(): q.put(...)``
    accepts work a concurrent shutdown will never drain (the PR-5
    submit/shutdown race); ``if self._thread is None: self._thread =
    Thread(...)`` double-starts under concurrent callers (the PR-9
    monitor-restart cousin); ``if q.full()`` followed by ``put`` sheds
    the wrong request. Make the check and the act one critical section
    (the ``full()`` admission check under ``_intake_lock`` is the
    in-tree exemplar), or use the atomic form
    (``try: put_nowait/except Full``, ``acquire(blocking=False)``).
    """

    id = "GC003"
    title = "check-then-act"

    def check(self, ctx: ConcurrencyContext) -> Iterable[Diagnostic]:
        for cls in ctx.model.classes:
            if not cls.concurrent:
                continue
            guarded = set(cls.guards) | set(cls.inferred_guards())
            for mname, fn in cls.methods.items():
                if mname == "__init__":
                    continue  # construction happens-before thread start
                yield from self._method(ctx, cls, mname, fn, guarded)

    def _method(self, ctx: ConcurrencyContext, cls: ClassModel,
                mname: str, fn: ast.AST,
                guarded: Set[str]) -> Iterable[Diagnostic]:
        held_by_line = self._held_lines(cls, mname)
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            if held_by_line.get(node.lineno):
                continue  # the check runs under a class lock
            test = node.test
            # (a) lifecycle/lazy-init: test self.X, assign self.X later.
            attr = _plain_attr_test(test)
            if attr is not None and attr not in cls.locks \
                    and attr not in cls.events and attr not in cls.queues:
                assign = self._later_assign(fn, attr, node.lineno)
                if assign is not None:
                    yield Diagnostic(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"`{cls.name}.{mname}` tests `self.{attr}` here "
                        f"and assigns it at line {assign} with no lock "
                        f"held — concurrent callers both pass the check "
                        f"(lazy-init/lifecycle race); guard both with "
                        f"one lock")
                continue
            # (b) queue TOCTOU: full()/empty()/qsize() then put/get.
            q = _method_attr_call(test, cls.queues, _QUEUE_CHECKS)
            if q is not None:
                act = self._later_queue_act(fn, cls, q[0], node.lineno)
                if act is not None:
                    yield Diagnostic(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"`self.{q[0]}.{q[1]}()` checked here, then "
                        f"`{act[1]}` at line {act[0]} with no lock held "
                        f"— the queue state can change between them; "
                        f"serialize check+act under one lock (the "
                        f"admission-check pattern) or use the atomic "
                        f"try/except form")
                continue
            # (c) event TOCTOU: is_set() then a shared-state mutation.
            e = _method_attr_call(test, cls.events, _EVENT_CHECKS)
            if e is not None:
                act = self._later_mutation(fn, cls, guarded, node.lineno,
                                           held_by_line)
                if act is not None:
                    yield Diagnostic(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"`self.{e[0]}.is_set()` checked here, then "
                        f"shared state mutated at line {act} with no "
                        f"lock held — the flag can flip between check "
                        f"and act (the submit/shutdown TOCTOU shape); "
                        f"make the check and the mutation one critical "
                        f"section")

    def _held_lines(self, cls: ClassModel, mname: str) -> Dict[int, bool]:
        """line -> "some class lock held" from the access model (an
        approximation good enough to ask 'was anything held at the
        test line')."""
        out: Dict[int, bool] = {}
        root = mname
        for acc in cls.accesses:
            if acc.method.split(".")[0] != root:
                continue
            if acc.held & set(cls.locks):
                out[acc.line] = True
        # With-blocks with no self-attr access inside still hold: derive
        # from nested_withs? The access map covers every flagged pattern
        # (the test itself reads a self attr, so its line is in the map).
        return out

    def _later_assign(self, fn: ast.AST, attr: str,
                      after_line: int) -> Optional[int]:
        for node in ast.walk(fn):
            if node is fn or getattr(node, "lineno", 0) < after_line:
                continue
            targets: Sequence[ast.AST] = ()
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = (node.target,)
            for t in targets:
                if _self_attr(t) == attr:
                    return node.lineno
        return None

    def _later_queue_act(self, fn: ast.AST, cls: ClassModel, queue_attr: str,
                         after_line: int) -> Optional[Tuple[int, str]]:
        for node in ast.walk(fn):
            if getattr(node, "lineno", 0) < after_line:
                continue
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _QUEUE_ACTS
                    and _self_attr(node.func.value) == queue_attr):
                return node.lineno, f"self.{queue_attr}.{node.func.attr}()"
        return None

    def _later_mutation(self, fn: ast.AST, cls: ClassModel,
                        guarded: Set[str], after_line: int,
                        held_by_line: Dict[int, bool]) -> Optional[int]:
        for node in ast.walk(fn):
            line = getattr(node, "lineno", 0)
            if line < after_line or held_by_line.get(line):
                continue
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                field = _self_attr(node.func.value)
                if field in cls.queues \
                        and node.func.attr in _EVENT_GATED_ACTS:
                    return line
                if field in cls.events and node.func.attr in _EVENT_ACTS:
                    return line
            targets: Sequence[ast.AST] = ()
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = (node.target,)
            for t in targets:
                if _self_attr(t) in guarded:
                    return line
        return None


# --- GC004 ----------------------------------------------------------------

@gc_register
class UnjoinedThread(ConcurrencyRule):
    """Non-daemon thread spawned with no ``join`` in sight.

    A non-daemon thread keeps the interpreter alive until it exits: with
    no ``join()`` anywhere in its owning class (or module, for
    module-level spawns), shutdown depends on the thread deciding to
    stop — the process hangs instead of exiting on the first missed
    sentinel. Either pass ``daemon=True`` (and provide an explicit
    drain/stop, like the batcher's ``shutdown``) or join the thread on
    the shutdown path.
    """

    id = "GC004"
    title = "unjoined-nondaemon-thread"

    def check(self, ctx: ConcurrencyContext) -> Iterable[Diagnostic]:
        for cls in ctx.model.classes:
            for spawn in cls.spawns:
                if spawn.daemon is not True and cls.joins == 0:
                    yield Diagnostic(
                        ctx.path, spawn.line, spawn.col, self.id,
                        f"`{cls.name}` spawns a non-daemon thread and "
                        f"never joins any thread — the process cannot "
                        f"exit until it stops on its own; pass "
                        f"daemon=True with an explicit drain, or join "
                        f"it on shutdown")
        for spawn in ctx.model.module_spawns:
            if spawn.daemon is not True and ctx.model.module_joins == 0:
                yield Diagnostic(
                    ctx.path, spawn.line, spawn.col, self.id,
                    "module-level non-daemon thread with no join in the "
                    "module — the process cannot exit until it stops on "
                    "its own; pass daemon=True or join it")
