"""graftlint rules GL001-GL009 — the TPU failure modes worth automating.

Each rule's class docstring is its user-facing documentation (printed by
``python -m pvraft_tpu.analysis lint --list-rules``). Suppress any rule
on a line with ``# graftlint: disable=GLxxx -- reason``.

Scope discipline: the expensive rules (host sync, tracer control flow,
tracer asserts) only fire inside functions this module can PROVE are
jit-traced — functions decorated with ``jax.jit``/``partial(jax.jit)``,
functions passed to a ``jax.jit(...)`` call in the same module, and
everything lexically nested inside those. That is deliberately
under-approximate (no cross-module call graph): a lint gate that cries
wolf gets disabled; one that only flags certainties gets kept.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from pvraft_tpu.analysis.engine import Diagnostic, LintContext, Rule, register

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# Attribute reads that concretize nothing: static metadata available on
# tracers at trace time.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}


def _attach_parents(tree: ast.Module) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._gl_parent = node  # type: ignore[attr-defined]


def _ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "_gl_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_gl_parent", None)


def _mentions_jit(expr: ast.AST) -> bool:
    """Does this decorator/callee expression reference a ``jit`` symbol
    (``jax.jit``, bare ``jit``, ``partial(jax.jit, ...)``)?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id == "jit":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "jit":
            return True
    return False


def jit_context_functions(tree: ast.Module) -> Set[ast.AST]:
    """Function nodes that are provably traced under ``jax.jit``.

    Roots: a) decorated with something mentioning ``jit``; b) named as the
    first argument of a call whose callee mentions ``jit`` anywhere in the
    module. Every function lexically nested inside a root is included.
    """
    _attach_parents(tree)
    jitted_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _mentions_jit(node.func):
            if node.args and isinstance(node.args[0], ast.Name):
                jitted_names.add(node.args[0].id)

    roots: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES):
            if any(_mentions_jit(d) for d in node.decorator_list):
                roots.add(node)
            elif node.name in jitted_names:
                roots.add(node)

    out: Set[ast.AST] = set(roots)
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES) and any(
            a in roots for a in _ancestors(node)
        ):
            out.add(node)
    return out


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    return names


def _tainted_names(fn: ast.AST) -> Set[str]:
    """Names (probably) holding tracers inside a jitted function: its
    parameters, plus anything assigned from an expression that reads a
    tainted name (one forward pass — no fixpoint, matching the "only flag
    certainties" stance)."""
    tainted = _param_names(fn)

    def expr_tainted(expr: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Name) and n.id in tainted
            for n in ast.walk(expr)
        )

    class Prop(ast.NodeVisitor):
        def visit_Assign(self, node: ast.Assign):
            if expr_tainted(node.value):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
            self.generic_visit(node)

        # Nested functions get their own analysis pass.
        def visit_FunctionDef(self, node):
            if node is not fn:
                return
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

    Prop().visit(fn)
    return tainted


def _dynamic_taint_uses(expr: ast.AST, tainted: Set[str]) -> List[ast.Name]:
    """Tainted Name reads in ``expr`` that are NOT static-metadata uses
    (``x.shape``, ``x is None``, ``isinstance(x, ...)``, ``len(...)`` of
    those)."""
    out: List[ast.Name] = []
    for node in ast.walk(expr):
        if not (isinstance(node, ast.Name) and node.id in tainted):
            continue
        parent = getattr(node, "_gl_parent", None)
        if isinstance(parent, ast.Attribute) and parent.attr in _STATIC_ATTRS:
            continue
        if isinstance(parent, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops
        ):
            continue
        if (
            isinstance(parent, ast.Call)
            and isinstance(parent.func, ast.Name)
            and parent.func.id in ("isinstance", "len", "type")
        ):
            continue
        out.append(node)
    return out


def _own_statements(fn: ast.AST) -> Iterator[ast.AST]:
    """Nodes of ``fn``'s body excluding nested function bodies."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES + (ast.Lambda,)):
                continue
            stack.append(child)


def _dotted(expr: ast.AST) -> str:
    """'jax.debug.print'-style dotted name of an expression, or ''."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return ""


# --- GL001 ----------------------------------------------------------------

@register
class HostSyncInJit(Rule):
    """Host-synchronizing call inside a jit-traced function.

    ``x.item()``, ``float(x)``/``int(x)``/``bool(x)`` on a tracer, and
    ``np.asarray``/``np.array`` all force a device->host transfer (or
    fail outright) at trace time, silently serializing the TPU pipeline
    when they do work. Return arrays from the jitted function and
    convert on the host instead.
    """

    id = "GL001"
    title = "host-sync-in-jit"

    _NP_FUNCS = {"asarray", "array", "float32", "float64", "int32", "int64"}

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        jitted = jit_context_functions(ctx.tree)
        for fn in jitted:
            tainted = _tainted_names(fn)
            for call in _own_statements(fn):
                if not isinstance(call, ast.Call):
                    continue
                f = call.func
                if isinstance(f, ast.Attribute) and f.attr == "item" \
                        and not call.args:
                    yield ctx.diag(
                        call, self.id,
                        "`.item()` inside a jit-traced function forces "
                        "a device sync; return the array and convert "
                        "on the host",
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy", "onp")
                    and f.attr in self._NP_FUNCS
                ):
                    yield ctx.diag(
                        call, self.id,
                        f"`{f.value.id}.{f.attr}(...)` inside a "
                        "jit-traced function concretizes the tracer "
                        "(host sync); use jnp or move it outside jit",
                    )
                elif (
                    isinstance(f, ast.Name)
                    and f.id in ("float", "int", "bool")
                    and len(call.args) == 1
                    and _dynamic_taint_uses(call.args[0], tainted)
                ):
                    yield ctx.diag(
                        call, self.id,
                        f"`{f.id}(...)` on a traced value inside jit "
                        "concretizes the tracer (host sync)",
                    )


# --- GL002 ----------------------------------------------------------------

@register
class TracerControlFlow(Rule):
    """Python ``if``/``while`` on a traced value inside a jit function.

    Python control flow runs at TRACE time: branching on a tracer raises
    ``TracerBoolConversionError`` (or worse, silently bakes one branch
    into the compiled program). Use ``lax.cond``/``lax.while_loop`` or
    ``jnp.where``; branching on static metadata (``x.shape``, ``x is
    None``, config flags) is fine and not flagged.
    """

    id = "GL002"
    title = "tracer-control-flow"

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        jitted = jit_context_functions(ctx.tree)
        for fn in jitted:
            tainted = _tainted_names(fn)
            for node in _own_statements(fn):
                if isinstance(node, (ast.If, ast.While)):
                    uses = _dynamic_taint_uses(node.test, tainted)
                    if uses:
                        kw = "if" if isinstance(node, ast.If) else "while"
                        yield ctx.diag(
                            node, self.id,
                            f"Python `{kw}` on traced value "
                            f"`{uses[0].id}` inside jit; use lax.cond / "
                            "lax.while_loop / jnp.where",
                        )


# --- GL003 ----------------------------------------------------------------

@register
class ModuleLevelJnpConstant(Rule):
    """Module-level ``jnp`` array constant.

    A ``jnp.array/zeros/ones/arange/...`` at module scope allocates on
    the default device at import time and is CAPTURED as a constant by
    every jit trace that touches it — it is re-uploaded per executable
    and pins the import to a backend. Build it inside the function (XLA
    constant-folds it) or keep it a ``np`` array.
    """

    id = "GL003"
    title = "module-level-jnp-constant"

    _BUILDERS = {
        "array", "asarray", "zeros", "ones", "full", "arange", "linspace",
        "eye", "zeros_like", "ones_like", "full_like",
    }

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        for stmt in ctx.tree.body:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            value = stmt.value
            if value is None:
                continue
            for call in ast.walk(value):
                if (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id == "jnp"
                    and call.func.attr in self._BUILDERS
                ):
                    yield ctx.diag(
                        stmt, self.id,
                        f"module-level `jnp.{call.func.attr}(...)` is "
                        "baked into every jit trace as a captured "
                        "constant; build it inside the function or use np",
                    )
                    break


# --- GL004 ----------------------------------------------------------------

@register
class FragileJaxImport(Rule):
    """Version-fragile jax import outside the compat shim.

    ``jax.experimental.*`` has no stability promise, and symbols like
    ``shard_map`` have already moved homes between pinned versions (the
    exact import that used to kill this repo's test collection). Route
    these through ``pvraft_tpu/compat.py`` — one file to touch on a jax
    upgrade — or suppress with a reason where no stable spelling exists.
    """

    id = "GL004"
    title = "fragile-jax-import"

    # Symbols that moved between jax versions: importing them from a
    # specific home is fragile in BOTH directions.
    _MOVED = {"shard_map"}

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        if ctx.norm_path.endswith("pvraft_tpu/compat.py"):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module == "jax":
                    for alias in node.names:
                        if alias.name in self._MOVED:
                            yield ctx.diag(
                                node, self.id,
                                f"`from jax import {alias.name}` is "
                                "version-fragile (moved between jax "
                                "releases); use pvraft_tpu.compat",
                            )
                elif node.module.split(".")[:2] == ["jax", "experimental"]:
                    yield ctx.diag(
                        node, self.id,
                        f"import from `{node.module}` (no stability "
                        "promise); route through pvraft_tpu.compat or "
                        "suppress with a reason",
                    )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[:2] == ["jax", "experimental"]:
                        yield ctx.diag(
                            node, self.id,
                            f"`import {alias.name}` (no stability "
                            "promise); route through pvraft_tpu.compat "
                            "or suppress with a reason",
                        )


# --- GL005 ----------------------------------------------------------------

@register
class JnpInHostData(Rule):
    """``jax.numpy`` imported in host-side data-loader code.

    Everything under ``pvraft_tpu/data/`` runs on the host (sampling,
    augmentation, batch assembly in worker threads): ``jnp`` there
    allocates on-device buffers per worker, serializes on the device
    lock, and silently moves preprocessing onto the accelerator. Use
    ``np``; the device boundary is ``loader.py``'s explicit
    ``jax.device_put`` prefetch.
    """

    id = "GL005"
    title = "jnp-in-host-data"

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        if "pvraft_tpu/data/" not in ctx.norm_path:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.numpy":
                        yield ctx.diag(
                            node, self.id,
                            "host-side data code must stay on np arrays; "
                            "jnp here puts loader workers on the device "
                            "(device transfer belongs in loader.py's "
                            "prefetch)",
                        )
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax" and any(
                    a.name == "numpy" for a in node.names
                ) or node.module == "jax.numpy":
                    yield ctx.diag(
                        node, self.id,
                        "host-side data code must stay on np arrays; "
                        "jnp here puts loader workers on the device",
                    )


# --- GL006 ----------------------------------------------------------------

@register
class MutableDefaultArg(Rule):
    """Mutable default argument.

    A ``[]``/``{}``/``set()`` default is created once at def time and
    shared across calls — in a codebase full of cached/jitted function
    factories this turns into cross-call state that survives retraces.
    Default to ``None`` and create inside.
    """

    id = "GL006"
    title = "mutable-default-arg"

    def _is_mutable(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set)):
            return True
        return (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("list", "dict", "set")
            and not node.args
            and not node.keywords
        )

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, _FUNC_NODES + (ast.Lambda,)):
                continue
            for default in list(fn.args.defaults) + [
                d for d in fn.args.kw_defaults if d is not None
            ]:
                if self._is_mutable(default):
                    name = getattr(fn, "name", "<lambda>")
                    yield ctx.diag(
                        default, self.id,
                        f"mutable default argument in `{name}` is shared "
                        "across calls; use None and create inside",
                    )


# --- GL007 ----------------------------------------------------------------

@register
class FStringDebugPrint(Rule):
    """f-string passed to ``jax.debug.print``.

    An f-string formats at TRACE time: the printed text shows
    ``Traced<ShapedArray...>`` instead of runtime values (and bakes one
    formatting into the program). ``jax.debug.print`` takes a format
    string with ``{}`` placeholders filled at run time:
    ``jax.debug.print("loss={l}", l=loss)``.
    """

    id = "GL007"
    title = "fstring-debug-print"

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if not dotted.endswith("debug.print"):
                continue
            if node.args and isinstance(node.args[0], ast.JoinedStr):
                yield ctx.diag(
                    node, self.id,
                    "f-string formats tracers at trace time; pass a "
                    'format string: jax.debug.print("x={x}", x=x)',
                )


# --- GL008 ----------------------------------------------------------------

@register
class AssertOnTracer(Rule):
    """``assert`` on a traced value inside a jit function.

    The assert runs at trace time: on a tracer it either raises
    ``TracerBoolConversionError`` or — under ``python -O`` — vanishes
    entirely, so it can never check runtime values. Use
    ``checkify.check`` or the ``@shapecheck`` contract layer for shape
    invariants (``pvraft_tpu.analysis.contracts``).
    """

    id = "GL008"
    title = "assert-on-tracer"

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        jitted = jit_context_functions(ctx.tree)
        for fn in jitted:
            tainted = _tainted_names(fn)
            for node in _own_statements(fn):
                if isinstance(node, ast.Assert):
                    uses = _dynamic_taint_uses(node.test, tainted)
                    if uses:
                        yield ctx.diag(
                            node, self.id,
                            f"`assert` on traced value `{uses[0].id}` "
                            "inside jit runs at trace time; use "
                            "checkify.check or @shapecheck",
                        )


# --- GL009 ----------------------------------------------------------------

@register
class UngatedDebugCallbackInJit(Rule):
    """Ungated ``jax.debug.print``/``callback``/``breakpoint`` inside jit.

    Debug callbacks compile INTO the program: every step pays a
    device->host round-trip that serializes the dispatch pipeline — the
    exact overhead the telemetry monitors (``pvraft_tpu/obs/monitors.py``)
    exist to avoid (they return plain array leaves instead). A callback
    is acceptable only behind a static debug flag so production traces
    never contain it: lexically inside an ``if`` (a config/env gate makes
    the call disappear from the trace when off), or suppressed with a
    reason. The telemetry-off audit
    (``analysis/audit.py:engine.train_step[telemetry_off_jaxpr]``)
    enforces the same invariant dynamically for the train step.
    """

    id = "GL009"
    title = "ungated-debug-callback-in-jit"

    _CALLS = ("debug.print", "debug.callback", "debug.breakpoint")

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        jitted = jit_context_functions(ctx.tree)
        for fn in jitted:
            for node in _own_statements(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted(node.func)
                if not any(dotted.endswith(c) for c in self._CALLS):
                    continue
                gated = any(
                    isinstance(a, ast.If) for a in _ancestors(node)
                    if any(b is fn for b in _ancestors(a))
                )
                if not gated:
                    yield ctx.diag(
                        node, self.id,
                        f"`{dotted}` inside jit with no static gate "
                        "compiles a host round-trip into every step; "
                        "guard it with a debug flag `if` or return the "
                        "value as a metrics leaf (obs/monitors.py)",
                    )
