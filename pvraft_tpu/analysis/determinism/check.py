"""detcheck driver: files -> det models -> GD rules -> diagnostics.

Mirrors ``concurrency/check.py``/``kernels/check.py``/``sharding/
check.py`` deliberately: the same ``Diagnostic`` type, the same
``# graftlint: disable=GDxxx -- reason`` suppression grammar (one
parser — what ``lint --stats`` counts is exactly what is honored
here), the same stable ordering. Scope is the WHOLE package: entropy
leaks everywhere, so unlike the plane-scoped engines detcheck walks
``pvraft_tpu/`` end to end (rng.py and compat.py are per-rule
exemptions as the contract owners, not scan holes).

The declared context comes from the data planes, never hardcoded: the
stream vocabulary is parsed from ``pvraft_tpu/rng.py``'s ``STREAMS``
tuple (AST, no import), and the GD003 hazard set from the live program
registry — each spec's thunk source yields its package imports
(the GK005 inspection discipline: the thunk is read, never run), the
package import graph closes them transitively, and any spec whose
closure reaches a hazard-op module must carry a ``determinism=``
declaration.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pvraft_tpu.analysis.engine import (
    Diagnostic,
    _expand_decorated_regions,
    _suppressed,
    _suppressions,
    iter_py_files,
)
from pvraft_tpu.analysis.determinism.model import build_module_det_model
from pvraft_tpu.analysis.determinism.rules import (
    DetContext,
    HazardSpec,
    all_determinism_rules,
)

# Spelled as a constant for docs/tests; resolved lazily by the CLI.
DEFAULT_SCOPE = ("pvraft_tpu",)


def _pkg_root() -> str:
    import pvraft_tpu

    return os.path.dirname(os.path.abspath(pvraft_tpu.__file__))


def default_scope() -> Tuple[str, ...]:
    """The gate's scan scope, as absolute paths of this checkout."""
    return (_pkg_root(),)


def declared_streams() -> Optional[Tuple[str, ...]]:
    """The stream vocabulary: first elements of the ``STREAMS`` tuple
    declared at module level of ``pvraft_tpu/rng.py`` — parsed from the
    AST so the checker arms without importing (and cannot drift from)
    the runtime contract. None when unreadable: GD002 reports that as
    a finding on any deriving file rather than silently skipping."""
    path = os.path.join(_pkg_root(), "rng.py")
    try:
        with open(path, "r", encoding="utf-8-sig") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == "STREAMS"
                   for t in targets):
            continue
        value = node.value
        if not isinstance(value, (ast.Tuple, ast.List)):
            return None
        names: List[str] = []
        for entry in value.elts:
            if isinstance(entry, (ast.Tuple, ast.List)) and entry.elts \
                    and isinstance(entry.elts[0], ast.Constant) \
                    and isinstance(entry.elts[0].value, str):
                names.append(entry.elts[0].value)
        return tuple(names)
    return None


# --- the GD003 registry inspection -----------------------------------------

_PKG_IMPORT_RE = re.compile(r"(?:from|import)\s+(pvraft_tpu(?:\.\w+)*)")


def _module_files() -> Dict[str, str]:
    """Dotted module name -> absolute path, for every module in the
    installed package (analysis/ excluded: the checker's own sources
    mention hazard names as string data, not as programs)."""
    root = _pkg_root()
    out: Dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d != "__pycache__"
            and not (dirpath == root and d == "analysis"))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, os.path.dirname(root))
            dotted = rel[:-3].replace(os.sep, ".")
            if dotted.endswith(".__init__"):
                dotted = dotted[: -len(".__init__")]
            out[dotted] = full
    return out


def _module_graph(files: Dict[str, str]
                  ) -> Tuple[Dict[str, Set[str]], Dict[str, List[str]]]:
    """(imports, hazards): per module, the package modules any import
    statement anywhere in it names (lazy function-level imports
    included — config-gated paths are still reachable code), and the
    hazard-op kinds its AST contains."""
    imports: Dict[str, Set[str]] = {}
    hazards: Dict[str, List[str]] = {}
    for dotted, path in files.items():
        try:
            with open(path, "r", encoding="utf-8-sig") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            imports[dotted] = set()
            continue
        mods: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[0] == "pvraft_tpu":
                        mods.add(a.name)
            elif isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.split(".")[0] == "pvraft_tpu":
                mods.add(node.module)
                for a in node.names:
                    # `from pvraft_tpu.data import loader` names a
                    # submodule; symbol imports just miss the lookup.
                    cand = f"{node.module}.{a.name}"
                    if cand in files:
                        mods.add(cand)
        imports[dotted] = {m for m in mods if m in files}
        model = build_module_det_model(tree)
        kinds = sorted({h.kind for h in model.hazard_ops})
        if kinds:
            hazards[dotted] = kinds
    return imports, hazards


def _thunk_roots(spec, spec_module_tree: Optional[ast.Module],
                 files: Dict[str, str]) -> Set[str]:
    """Package modules the spec's thunk source imports, plus those of
    same-module helper functions the thunk references (audit entries
    delegate to ``_model_entry``-style builders) — a fixpoint within
    the defining module."""
    import inspect

    try:
        source = inspect.getsource(spec.thunk)
    except (OSError, TypeError):
        return set()
    helper_imports: Dict[str, Set[str]] = {}
    helper_names: Dict[str, Set[str]] = {}
    if spec_module_tree is not None:
        for node in spec_module_tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                src_names = {n.id for n in ast.walk(node)
                             if isinstance(n, ast.Name)}
                helper_names[node.name] = src_names
                mods: Set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Import):
                        mods.update(a.name for a in sub.names
                                    if a.name.split(".")[0] == "pvraft_tpu")
                    elif isinstance(sub, ast.ImportFrom) and sub.module \
                            and sub.module.split(".")[0] == "pvraft_tpu":
                        mods.add(sub.module)
                        mods.update(
                            f"{sub.module}.{a.name}" for a in sub.names
                            if f"{sub.module}.{a.name}" in files)
                helper_imports[node.name] = mods

    roots = set(_PKG_IMPORT_RE.findall(source))
    # Helper fixpoint: pull in imports of same-module functions the
    # thunk (or an already-pulled helper) references by name.
    pulled: Set[str] = set()
    frontier = [source]
    while frontier:
        text = frontier.pop()
        for name, mods in helper_imports.items():
            if name in pulled:
                continue
            if re.search(rf"\b{re.escape(name)}\b", text):
                pulled.add(name)
                roots.update(mods)
                frontier.append(" ".join(sorted(helper_names[name])))
    return {r for r in roots if r in files}


def hazard_spec_records() -> List[HazardSpec]:
    """Every registered ProgramSpec whose static import closure reaches
    a nondeterminism-hazard op, with its declared stance. Import-light:
    ``load_catalog`` registers specs without importing jax (thunks stay
    lazy) and everything else is AST over package sources."""
    from pvraft_tpu.programs import load_catalog
    from pvraft_tpu.programs.spec import specs

    load_catalog()
    files = _module_files()
    imports, hazards = _module_graph(files)

    # Transitive closure memo: module -> hazard modules it reaches.
    reach_memo: Dict[str, Set[str]] = {}

    def reach(mod: str, seen: Set[str]) -> Set[str]:
        if mod in reach_memo:
            return reach_memo[mod]
        if mod in seen:
            return set()
        seen.add(mod)
        out: Set[str] = set()
        if mod in hazards:
            out.add(mod)
        for dep in imports.get(mod, ()):
            out |= reach(dep, seen)
        reach_memo[mod] = out
        return out

    module_trees: Dict[str, Optional[ast.Module]] = {}

    def tree_of(path: str) -> Optional[ast.Module]:
        if path not in module_trees:
            try:
                with open(path, "r", encoding="utf-8-sig") as f:
                    module_trees[path] = ast.parse(f.read(), filename=path)
            except (OSError, SyntaxError):
                module_trees[path] = None
        return module_trees[path]

    records: List[HazardSpec] = []
    for spec in specs().values():
        roots = _thunk_roots(spec, tree_of(spec.path) if spec.path else None,
                             files)
        hit: Dict[str, List[str]] = {}
        for r in sorted(roots):
            for hmod in sorted(reach(r, set())):
                hit.setdefault(hmod, hazards[hmod])
        if not hit:
            continue
        via = sorted(hit)[0]
        kinds = sorted({k for ks in hit.values() for k in ks})
        records.append(HazardSpec(
            name=spec.name,
            determinism=getattr(spec, "determinism", ""),
            path=spec.path.replace("\\", "/"),
            line=spec.line,
            via=via.replace(".", "/") + ".py",
            kinds=tuple(kinds)))
    records.sort(key=lambda r: (r.path, r.line, r.name))
    return records


# --- the driver ------------------------------------------------------------

def check_source(source: str, path: str = "<string>",
                 rule_ids: Sequence[str] = (),
                 streams: Optional[Sequence[str]] = None,
                 hazard_specs: Optional[Sequence[HazardSpec]] = None,
                 ) -> List[Diagnostic]:
    """Run the GD rules over one source string (suppressions applied)."""
    source = source.lstrip("\ufeff")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Diagnostic(path, e.lineno or 1, e.offset or 0, "GD000",
                           f"syntax error: {e.msg}")]
    model = build_module_det_model(tree)
    ctx = DetContext(path, source, tree, model,
                     declared_streams=streams, hazard_specs=hazard_specs)
    per_line, file_ids = _suppressions(source)
    _expand_decorated_regions(tree, per_line)
    out: List[Diagnostic] = []
    for rule_cls in all_determinism_rules():
        if rule_ids and rule_cls.id not in rule_ids:
            continue
        for d in rule_cls().check(ctx):
            if not _suppressed(d, per_line, file_ids):
                out.append(d)
    out.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return out


def check_paths(paths: Sequence[str], rule_ids: Sequence[str] = (),
                streams: Optional[Sequence[str]] = None,
                hazard_specs: Optional[Sequence[HazardSpec]] = None,
                ) -> Tuple[List[Diagnostic], int]:
    """Check files/directories. Returns (findings, files_checked).

    ``streams``/``hazard_specs`` default to the live declarations
    (rng.py's STREAMS, the registry hazard closure) so the clean-tree
    gate always arms GD002/GD003 with real data."""
    if streams is None:
        streams = declared_streams()
    if hazard_specs is None:
        hazard_specs = hazard_spec_records()
    findings: List[Diagnostic] = []
    n = 0
    for f in iter_py_files(paths):
        n += 1
        with open(f, "r", encoding="utf-8-sig") as fh:
            findings.extend(check_source(
                fh.read(), path=f, rule_ids=rule_ids, streams=streams,
                hazard_specs=hazard_specs))
    findings.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return findings, n
