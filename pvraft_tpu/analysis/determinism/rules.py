"""detcheck rules GD001-GD005 — the determinism/RNG failure classes.

Every campaign the ROADMAP points at (paper-parity convergence, pod
training with per-host seed derivation, the serve A/B canary) silently
assumes replayable runs; PV-RAFT's 32-iteration GRU refinement is
exactly the model where one nondeterministic reduction order compounds
into divergent runs. These rules make the RNG contract
(:mod:`pvraft_tpu.rng`), the hazard-op declarations (``determinism=``
on ProgramSpecs), the flag-routing discipline (``compat.py``) and the
iteration-order conventions machine-checked. Suppress with
``# graftlint: disable=GDxxx -- reason`` (shared pragma grammar;
reason-less suppressions fail ``lint --stats``).

Path scoping: inside the installed package ``pvraft_tpu/rng.py`` is
exempt from GD002 (it is the contract owner) and ``pvraft_tpu/compat.py``
from GD004 (the flag-routing owner); outside the package (fixtures,
inline test sources) every rule applies unconditionally so red/green
corpora stay honest.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

from pvraft_tpu.analysis.engine import Diagnostic, LintContext, Rule
from pvraft_tpu.analysis.determinism.model import (
    ModuleDetModel,
    _DERIVE_FUNCS,
    _tail,
    build_module_det_model,
    resolve_dotted,
)


@dataclasses.dataclass(frozen=True)
class HazardSpec:
    """One registered ProgramSpec whose static import closure reaches a
    nondeterminism-hazard op — the GD003 input, computed by
    :func:`~pvraft_tpu.analysis.determinism.check.hazard_spec_records`
    (or passed explicitly by fixtures)."""

    name: str
    determinism: str
    path: str
    line: int
    via: str    # module (path suffix) holding the hazard
    kinds: Tuple[str, ...]


class DetContext(LintContext):
    """LintContext + the extracted det model + the declared context.

    ``declared_streams=None`` means the caller supplied no stream
    vocabulary (rng.py unreadable): GD002 then reports the gap as a
    finding on any file that derives, rather than silently skipping.
    ``hazard_specs`` carries the registry's hazard closure; rules only
    report the specs declared in THIS file, so findings anchor at the
    registration line and the standard suppressions apply."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 model: Optional[ModuleDetModel] = None,
                 declared_streams: Optional[Sequence[str]] = None,
                 hazard_specs: Optional[Sequence[HazardSpec]] = None):
        super().__init__(path, source, tree)
        self.model = model if model is not None \
            else build_module_det_model(tree)
        self.declared_streams = (None if declared_streams is None
                                 else tuple(declared_streams))
        self.hazard_specs = tuple(hazard_specs or ())

    def package_suffix(self) -> Optional[str]:
        """'pvraft_tpu/...' relative suffix, or None for out-of-package
        sources (fixtures, inline strings) — those see every rule."""
        if "pvraft_tpu/" in self.norm_path:
            return "pvraft_tpu/" + self.norm_path.rsplit(
                "/pvraft_tpu/", 1)[-1]
        return None

    def diag_at(self, line: int, col: int, rule_id: str,
                message: str) -> Diagnostic:
        return Diagnostic(self.path, line, col, rule_id, message)


class DetRule(Rule):
    def check(self, ctx: DetContext) -> Iterable[Diagnostic]:
        raise NotImplementedError


_GD_REGISTRY: List[Type[DetRule]] = []


def gd_register(cls: Type[DetRule]) -> Type[DetRule]:
    if not cls.id or not cls.title:
        raise ValueError(f"rule {cls.__name__} must set id and title")
    if any(r.id == cls.id for r in _GD_REGISTRY):
        raise ValueError(f"duplicate rule id {cls.id}")
    _GD_REGISTRY.append(cls)
    return cls


def all_determinism_rules() -> Tuple[Type[DetRule], ...]:
    return tuple(sorted(_GD_REGISTRY, key=lambda r: r.id))


def _exempt(ctx: DetContext, exempt: Tuple[str, ...]) -> bool:
    suffix = ctx.package_suffix()
    return suffix is not None and suffix in exempt


# --- GD001 ----------------------------------------------------------------

_KEY_PRODUCERS = ("jax.random.key", "jax.random.PRNGKey")
_KEY_TRANSFORMS = ("split", "fold_in", "clone")


def _produces_key(value: ast.AST, aliases: Dict[str, str]) -> bool:
    """Does this expression mint or re-derive a PRNG key?"""
    if not isinstance(value, ast.Call):
        return False
    resolved = resolve_dotted(value.func, aliases)
    tail = _tail(value.func)
    return (resolved in _KEY_PRODUCERS
            or tail in _KEY_TRANSFORMS
            or tail == "derive")


@gd_register
class KeyReuse(DetRule):
    """jax PRNG key consumed twice, or consumed unsplit inside a loop.

    A key is one-shot entropy: passing the same key to two samplers (or
    to the same sampler every loop iteration) makes their draws
    identical — dropout masks that repeat across layers, per-step noise
    that repeats across steps. Tracked per function, in line order: an
    assignment from ``key``/``PRNGKey``/``derive``/``split``/``fold_in``
    makes a name fresh; any other call consuming it marks it spent;
    consuming a spent key — or consuming inside a loop a key derived
    outside it — is the finding. Fix: ``key, sub = jax.random.split(key)``
    per consumption, or ``fold_in`` the loop index.
    """

    id = "GD001"
    title = "key-reuse"

    def check(self, ctx: DetContext) -> Iterable[Diagnostic]:
        aliases = ctx.model.aliases
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_fn(ctx, node, aliases)

    def _check_fn(self, ctx: DetContext, fn: ast.AST,
                  aliases: Dict[str, str]) -> Iterable[Diagnostic]:
        # name -> {"depth": loop depth at assignment, "spent": line|None}
        keys: Dict[str, Dict[str, object]] = {}

        def assign_targets(node: ast.Assign) -> List[str]:
            names: List[str] = []
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    names.extend(e.id for e in t.elts
                                 if isinstance(e, ast.Name))
            return names

        def consumed_names(call: ast.Call) -> List[str]:
            out = []
            for arg in list(call.args) + [k.value for k in call.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in keys:
                        out.append(sub.id)
            return out

        def visit(stmts: Sequence[ast.stmt],
                  depth: int) -> Iterable[Diagnostic]:
            for stmt in stmts:
                # Consumption first where the statement holds calls
                # (covers `x = sampler(key)` reading key before the
                # assignment rebinds anything). Only the statement's
                # OWN expressions are scanned — compound bodies are
                # handled by the recursion below at their real loop
                # depth, and nested defs get their own _check_fn pass.
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan: List[ast.AST] = [stmt.iter]
                elif isinstance(stmt, (ast.While, ast.If)):
                    scan = [stmt.test]
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    scan = [i.context_expr for i in stmt.items]
                elif isinstance(stmt, (ast.Try, ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.ClassDef)):
                    scan = []
                else:
                    scan = [stmt]
                # One draw per statement: a nested consumer
                # (`outs.append(normal(key))`) is one consumption, not
                # one per enclosing call.
                done: set = set()
                for node in (n for root in scan for n in ast.walk(root)):
                    if not isinstance(node, ast.Call):
                        continue
                    tail = _tail(node.func)
                    if tail in _KEY_TRANSFORMS or tail == "derive":
                        continue  # split/fold_in re-derive, not consume
                    for name in consumed_names(node):
                        if name in done:
                            continue
                        done.add(name)
                        st = keys[name]
                        if st["spent"] is not None:
                            yield ctx.diag_at(
                                node.lineno, node.col_offset, self.id,
                                f"PRNG key `{name}` already consumed at "
                                f"line {st['spent']} — split it "
                                f"(`{name}, sub = jax.random.split("
                                f"{name})`) before each use")
                        elif depth > int(st["depth"]):  # type: ignore[call-overload]
                            yield ctx.diag_at(
                                node.lineno, node.col_offset, self.id,
                                f"PRNG key `{name}` (derived outside "
                                f"this loop) consumed inside it — every "
                                f"iteration draws identical randomness; "
                                f"fold_in the loop index or split per "
                                f"iteration")
                            st["spent"] = node.lineno
                        else:
                            st["spent"] = node.lineno
                # Then (re)binding.
                if isinstance(stmt, ast.Assign):
                    fresh = _produces_key(stmt.value, aliases)
                    for name in assign_targets(stmt):
                        if fresh:
                            keys[name] = {"depth": depth, "spent": None}
                        elif name in keys:
                            del keys[name]  # rebound to a non-key
                # Recurse into compound statements.
                if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    yield from visit(stmt.body, depth + 1)
                    yield from visit(stmt.orelse, depth)
                elif isinstance(stmt, ast.If):
                    yield from visit(stmt.body, depth)
                    yield from visit(stmt.orelse, depth)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    yield from visit(stmt.body, depth)
                elif isinstance(stmt, ast.Try):
                    yield from visit(stmt.body, depth)
                    for h in stmt.handlers:
                        yield from visit(h.body, depth)
                    yield from visit(stmt.orelse, depth)
                    yield from visit(stmt.finalbody, depth)

        yield from visit(fn.body, 0)


# --- GD002 ----------------------------------------------------------------

@gd_register
class UndeclaredEntropy(DetRule):
    """Entropy minted outside the ``pvraft_tpu.rng`` stream contract.

    Three shapes: (a) a raw RNG constructor — ``jax.random.key``/
    ``PRNGKey``, ``np.random.default_rng``/legacy globals, stdlib
    ``random`` — anywhere but ``rng.py`` invents a seed the config seed
    does not govern (the old warm-up-vs-loadgen seed-0 collision);
    (b) a time/pid/uuid-derived seed makes the run unreplayable by
    construction; (c) a ``derive``/``host_rng`` call whose stream name
    is not declared in :data:`pvraft_tpu.rng.STREAMS` bypasses the
    vocabulary the whole contract hangs on. Fix: declare a stream and
    call ``derive(seed, "<stream>", *indices)``.
    """

    id = "GD002"
    title = "undeclared-entropy"

    def check(self, ctx: DetContext) -> Iterable[Diagnostic]:
        if _exempt(ctx, ("pvraft_tpu/rng.py",)):
            return
        for site in ctx.model.rng_constructors:
            yield ctx.diag_at(
                site.line, site.col, self.id,
                f"raw RNG constructor `{site.resolved}` outside "
                f"pvraft_tpu/rng.py — derive entropy from the config "
                f"seed via a declared stream: rng.derive(seed, "
                f"'<stream>') / rng.host_rng(seed, '<stream>')")
        for site in ctx.model.time_seeds:
            yield ctx.diag_at(
                site.line, site.col, self.id,
                f"time/entropy source `{site.via}` seeds `{site.seeding}` "
                f"— a wall-clock seed is unreplayable by construction; "
                f"thread the config seed through a declared stream")
        for site in ctx.model.derive_calls:
            if ctx.declared_streams is None:
                yield ctx.diag_at(
                    site.line, site.col, self.id,
                    f"`{site.func}` call but the STREAMS vocabulary "
                    f"could not be read from pvraft_tpu/rng.py — the "
                    f"stream contract is unverifiable")
                continue
            if not site.stream_strs:
                yield ctx.diag_at(
                    site.line, site.col, self.id,
                    f"`{site.func}` call carries no stream name "
                    f"literal — name the stream: {site.func}(seed, "
                    f"'<stream>', ...)")
            for s in site.stream_strs:
                if s not in ctx.declared_streams:
                    yield ctx.diag_at(
                        site.line, site.col, self.id,
                        f"`{site.func}` uses undeclared stream {s!r} — "
                        f"declare it in pvraft_tpu.rng.STREAMS "
                        f"(known: {', '.join(ctx.declared_streams)})")


# --- GD003 ----------------------------------------------------------------

@gd_register
class UndeclaredHazardProgram(DetRule):
    """Hazard-op program registered without a ``determinism=`` stance.

    Unordered scatter-accumulates, segment reductions and ring-fold
    collectives are the ops whose float accumulation order is an
    implementation detail — bitwise replay can hold on one topology and
    silently break on another. A ProgramSpec whose static import
    closure reaches such an op must declare ``determinism="..."`` at
    its registration: the stance (unique-index scatter, topology-fixed
    ring order, accepted tolerance) becomes reviewable data instead of
    folklore, and the replay harness records it. Findings anchor at the
    registration line in THIS file.
    """

    id = "GD003"
    title = "undeclared-hazard-program"

    def check(self, ctx: DetContext) -> Iterable[Diagnostic]:
        norm = ctx.norm_path
        for spec in ctx.hazard_specs:
            spec_norm = spec.path.replace("\\", "/")
            if not (spec_norm == norm or norm.endswith(spec_norm)
                    or spec_norm.endswith(norm)):
                continue
            if spec.determinism:
                continue
            yield ctx.diag_at(
                spec.line, 0, self.id,
                f"program spec `{spec.name}` reaches nondeterminism-"
                f"hazard ops ({', '.join(spec.kinds)} via {spec.via}) "
                f"but declares no determinism= stance — state it at the "
                f"registration (e.g. determinism='unique-index-scatter; "
                f"replay-certified')")


# --- GD004 ----------------------------------------------------------------

@gd_register
class UnroutedDeterminismFlag(DetRule):
    """Backend determinism flag written outside ``compat.py``.

    ``XLA_FLAGS``, ``PYTHONHASHSEED``, matmul precision, x64 and the
    PRNG implementation/partitionability flags change numerics or RNG
    semantics process-wide; scattered writes make "which flags was this
    run under?" unanswerable and let two entry points disagree
    silently. ``compat.py`` is the one-file owner of version- and
    backend-fragile surfaces — route the write through a helper there
    (the ``force_host_device_count`` precedent).
    """

    id = "GD004"
    title = "unrouted-determinism-flag"

    def check(self, ctx: DetContext) -> Iterable[Diagnostic]:
        if _exempt(ctx, ("pvraft_tpu/compat.py",)):
            return
        for site in ctx.model.flag_writes:
            yield ctx.diag_at(
                site.line, site.col, self.id,
                f"determinism flag `{site.key}` written via {site.how} "
                f"outside pvraft_tpu/compat.py — route it through a "
                f"compat helper so every entry point shares one "
                f"declaration")


# --- GD005 ----------------------------------------------------------------

@gd_register
class IterationOrderHazard(DetRule):
    """Unordered iteration feeding data, trace or selection order.

    ``glob``/``listdir`` order is filesystem-dependent: feeding it to
    dataset indexing or checkpoint selection makes sample order (and
    therefore every downstream draw) differ across machines — wrap the
    enumeration in ``sorted(...)`` at the call. Set iteration order is
    salted per process: driving pytree construction or trace order from
    a set reorders jaxpr equations between runs — iterate
    ``sorted(...)`` of the set instead. (Dicts are insertion-ordered
    and fine.)
    """

    id = "GD005"
    title = "iteration-order-hazard"

    def check(self, ctx: DetContext) -> Iterable[Diagnostic]:
        for site in ctx.model.unsorted_globs:
            yield ctx.diag_at(
                site.line, site.col, self.id,
                f"filesystem enumeration `{site.callee}` is not wrapped "
                f"in sorted() — listing order is filesystem-dependent; "
                f"sort at the call site")
        for site in ctx.model.set_iters:
            yield ctx.diag_at(
                site.line, site.col, self.id,
                f"{site.detail} — set order is salted per process; "
                f"iterate sorted(...) instead")


# re-exported for check.py / fixtures
DERIVE_FUNCS = _DERIVE_FUNCS
