"""Bitwise replay: detcheck's dynamic twin.

The static rules prove entropy is *declared*; this harness proves the
declared entropy actually replays. It builds the registered train step
and serve dispatch twice from scratch — fresh thunk, fresh trace, fresh
compile, same config seed — runs both on identical stream-derived
inputs, and diffs every output leaf bitwise. A divergence means
something outside the seed contract leaked into the program (trace
order, an unordered reduction, uninitialized padding), exactly the
class of bug a convergence-parity campaign cannot afford to chase.

    python -m pvraft_tpu.analysis determinism --replay
    python -m pvraft_tpu.analysis determinism --replay \
        --check artifacts/determinism_report.json

The committed ``pvraft_determinism/v1`` artifact is regenerate-and-
compare pinned by ``scripts/lint.sh`` (the kernel/pod-plan
discipline). Platform honesty: the replay verdicts (each program
bitwise-identical against ITSELF) are enforced on every host; raw
digests are only compared against the committed ones when the platform
matches (CPU CI cannot check TPU hashes).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pvraft_tpu.rng import DEFAULT_SEED, STREAM_NAMES, host_rng

SCHEMA_VERSION = "pvraft_determinism/v1"

# The replay corpus: the registered train step and a serve dispatch
# (ISSUE 16). Audit-geometry specs — tiny dims, real code paths.
REPLAY_PROGRAMS = ("engine.train_step", "serve.predict")


def _materialize(args, seed: int) -> Tuple[Any, ...]:
    """Concrete host arrays for a thunk's abstract args, derived from
    the ``replay.input`` stream — leaf ``i`` always draws from the same
    substream, so two materializations are bitwise identical."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(args)
    out = []
    for i, leaf in enumerate(leaves):
        shape = tuple(leaf.shape)
        dtype = np.dtype(leaf.dtype)
        rng = host_rng(seed, "replay.input", i)
        if dtype == np.bool_:
            # Mostly-valid masks: exercises masked stats without
            # degenerate all-padding rows.
            arr = rng.random(shape) < 0.8
            if arr.ndim:
                arr[..., 0] = True
        elif np.issubdtype(dtype, np.floating):
            arr = rng.standard_normal(shape).astype(dtype)
        elif np.issubdtype(dtype, np.integer):
            arr = rng.integers(0, 4, shape).astype(dtype)
        else:
            raise TypeError(f"unsupported replay leaf dtype {dtype}")
        out.append(arr)
    return tuple(jax.tree_util.tree_unflatten(treedef, out))


def _digest(outputs) -> Tuple[str, int]:
    """(sha256 hex over every output leaf's dtype+shape+bytes, #leaves)."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    leaves = jax.tree_util.tree_leaves(outputs)
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest(), len(leaves)


def _run_once(name: str, seed: int) -> Tuple[str, int]:
    """Build the spec's program from scratch and run it on
    stream-derived inputs. A FULL rebuild per call on purpose: the
    second run re-traces and re-compiles, so trace-order
    nondeterminism diverges here instead of being cached away."""
    from pvraft_tpu.programs import load_catalog
    from pvraft_tpu.programs.spec import get

    load_catalog()
    spec = get(name)
    fn, args = spec.build()
    concrete = _materialize(args, seed)
    return _digest(fn(*concrete))


def replay_report(seed: int = DEFAULT_SEED,
                  programs: Sequence[str] = REPLAY_PROGRAMS
                  ) -> Dict[str, Any]:
    """Run each program twice from the same seed; diff bitwise."""
    import jax

    from pvraft_tpu.programs import load_catalog
    from pvraft_tpu.programs.spec import get

    load_catalog()
    entries: List[Dict[str, Any]] = []
    for name in programs:
        spec = get(name)
        d1, n1 = _run_once(name, seed)
        d2, n2 = _run_once(name, seed)
        entries.append({
            "name": name,
            "determinism": getattr(spec, "determinism", ""),
            "n_output_leaves": n1,
            "digest": d1,
            "digest_rerun": d2,
            "bitwise_identical": bool(d1 == d2 and n1 == n2),
        })
    all_ok = all(e["bitwise_identical"] for e in entries)
    return {
        "schema": SCHEMA_VERSION,
        "seed": int(seed),
        "platform": jax.default_backend(),
        "jax_version": jax.__version__,
        "streams": list(STREAM_NAMES),
        "programs": entries,
        "verdict": "bitwise" if all_ok else "divergent",
    }


def write_report(path: str, report: Dict[str, Any]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")


def load_report(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA_VERSION:
        raise ValueError(
            f"{path}: schema {doc.get('schema')!r}, "
            f"expected {SCHEMA_VERSION!r}")
    return doc


def check_report(path: str, fresh: Optional[Dict[str, Any]] = None
                 ) -> List[str]:
    """Regenerate-and-compare against the committed report.

    Hard on every host: the fresh replay must be bitwise and cover the
    committed program set with the committed seed/streams, and the
    committed report must itself claim bitwise. Digests are compared
    only when the committed platform matches this host's (platform
    honesty: ratios and hashes from another backend are recorded
    evidence, not cross-platform assertions). Returns problem strings;
    empty means the pin holds.
    """
    try:
        committed = load_report(path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        return [f"cannot read committed report: {e}"]
    if fresh is None:
        fresh = replay_report(seed=int(committed.get("seed", DEFAULT_SEED)))

    problems: List[str] = []
    if committed.get("verdict") != "bitwise":
        problems.append(
            f"committed verdict is {committed.get('verdict')!r}, "
            f"not 'bitwise'")
    if fresh["verdict"] != "bitwise":
        for e in fresh["programs"]:
            if not e["bitwise_identical"]:
                problems.append(
                    f"program {e['name']} does NOT replay bitwise on "
                    f"this host: {e['digest'][:16]} vs "
                    f"{e['digest_rerun'][:16]}")
    if committed.get("seed") != fresh["seed"]:
        problems.append(
            f"seed drift: committed {committed.get('seed')}, "
            f"fresh {fresh['seed']}")
    if committed.get("streams") != fresh["streams"]:
        problems.append(
            "stream vocabulary drift: committed "
            f"{committed.get('streams')} vs live {fresh['streams']} — "
            "regenerate the report after editing rng.STREAMS")
    want = {e["name"]: e for e in committed.get("programs", [])}
    got = {e["name"]: e for e in fresh["programs"]}
    if sorted(want) != sorted(got):
        problems.append(
            f"program set drift: committed {sorted(want)}, "
            f"fresh {sorted(got)}")
    same_platform = committed.get("platform") == fresh["platform"]
    for name in sorted(set(want) & set(got)):
        if not want[name].get("bitwise_identical"):
            problems.append(f"committed entry {name} is not bitwise")
        if want[name].get("determinism") != got[name].get("determinism"):
            problems.append(
                f"{name}: determinism stance drift — regenerate the "
                f"report after editing the spec declaration")
        if same_platform and want[name].get("digest") != \
                got[name].get("digest"):
            problems.append(
                f"{name}: output digest drift on {fresh['platform']} — "
                f"the program's numerics changed; regenerate "
                f"artifacts/determinism_report.json if intended")
    return problems
