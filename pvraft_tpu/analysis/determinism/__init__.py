"""detcheck: determinism/RNG-discipline static analysis (rules GD001+).

The sixth analysis engine, symmetric with graftlint/deepcheck/
threadcheck/kernelcheck/shardcheck: one :class:`~..engine.Diagnostic`
type, one ``# graftlint: disable=GDxxx -- reason`` pragma grammar, and
a dynamic twin (the bitwise replay harness in
:mod:`pvraft_tpu.analysis.determinism.replay`).

    python -m pvraft_tpu.analysis determinism            # static rules
    python -m pvraft_tpu.analysis determinism --replay   # bitwise replay
"""

from pvraft_tpu.analysis.determinism.check import (  # noqa: F401
    DEFAULT_SCOPE,
    check_paths,
    check_source,
    declared_streams,
    default_scope,
    hazard_spec_records,
)
from pvraft_tpu.analysis.determinism.rules import (  # noqa: F401
    DetContext,
    HazardSpec,
    all_determinism_rules,
)
