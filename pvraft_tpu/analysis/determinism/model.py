"""detcheck model: one AST walk -> the determinism-relevant sites.

Follows the kernelcheck/shardcheck discipline: a dataclass record per
site class, extracted in a single pass with an import-alias map so
``random.normal`` resolves to ``jax.random.normal`` in a file that did
``from jax import random`` but to the stdlib in a file that did
``import random`` — the distinction GD002 lives on. Rules never re-walk
the tree for extraction; they read these records.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted name of a Name/Attribute chain, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def build_alias_map(tree: ast.Module) -> Dict[str, str]:
    """Local name -> fully qualified module/symbol, from every import
    statement in the module (function-level imports included: the
    repo's thunks import lazily)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".", 1)[0]] = (
                    a.name if a.asname else a.name.split(".", 1)[0])
                if a.asname:
                    aliases[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def resolve_dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Dotted name with its FIRST segment resolved through the alias
    map: ``np.random.default_rng`` -> ``numpy.random.default_rng``,
    ``random.normal`` -> ``jax.random.normal`` under ``from jax import
    random``. Unresolved names pass through unchanged."""
    dotted = _dotted(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    full = aliases.get(head, head)
    return f"{full}.{rest}" if rest else full


@dataclasses.dataclass(frozen=True)
class RngConstructorSite:
    """A raw RNG constructor/legacy-sampler call (GD002)."""

    line: int
    col: int
    resolved: str  # fully resolved dotted callee


@dataclasses.dataclass(frozen=True)
class DeriveSite:
    """A ``derive``/``host_rng``/``host_entropy`` call (GD002 streams)."""

    line: int
    col: int
    func: str
    stream_strs: Tuple[str, ...]  # string-constant args, in order


@dataclasses.dataclass(frozen=True)
class TimeSeedSite:
    """A time/entropy call inside an RNG-seeding expression (GD002)."""

    line: int
    col: int
    via: str      # the time/entropy callee
    seeding: str  # the rng call it feeds


@dataclasses.dataclass(frozen=True)
class FlagWriteSite:
    """A watched determinism env/config flag written (GD004)."""

    line: int
    col: int
    key: str
    how: str  # "os.environ[...]", "jax.config.update", ...


@dataclasses.dataclass(frozen=True)
class UnsortedGlobSite:
    """A filesystem enumeration not wrapped in sorted() (GD005)."""

    line: int
    col: int
    callee: str


@dataclasses.dataclass(frozen=True)
class SetIterSite:
    """Direct iteration over a set expression (GD005)."""

    line: int
    col: int
    detail: str


@dataclasses.dataclass(frozen=True)
class HazardOpSite:
    """A nondeterminism-hazard op (GD003's module-level evidence)."""

    line: int
    col: int
    kind: str   # "scatter-accumulate" | "segment-reduction" | "ring-fold"
    callee: str


# GD002: numpy's legacy global API and Generator constructors, the
# stdlib random module, and raw jax key construction. jax.random
# SAMPLERS (normal, uniform, ...) are fine — they consume keys, they
# don't mint entropy.
_JAX_KEY_CONSTRUCTORS = ("jax.random.key", "jax.random.PRNGKey")
_TIME_ENTROPY = ("time.time", "time.time_ns", "time.monotonic",
                 "time.monotonic_ns", "time.perf_counter",
                 "time.perf_counter_ns", "os.urandom", "os.getpid",
                 "uuid.uuid1", "uuid.uuid4", "datetime.datetime.now",
                 "datetime.datetime.utcnow", "secrets.token_bytes")
_DERIVE_FUNCS = ("derive", "host_rng", "host_entropy")

# GD003 hazard vocabularies (exact callee tails — `_scatter_add_onehot`
# is a deliberate dense reformulation, not a scatter).
_SEGMENT_REDUCTIONS = ("segment_sum", "segment_max", "segment_min",
                      "segment_prod")
_SCATTER_OPS = ("scatter_add", "scatter", "scatter_mul", "psum_scatter")
_RING_OPS = ("ppermute",)
_AT_ACCUM_METHODS = ("add", "max", "min", "multiply", "mul")

# GD004 watched surfaces: the flags that silently change numerics or
# RNG semantics. Deliberately narrow — jax_platforms, cache dirs and
# the Pallas interpret escape hatch are placement/caching knobs, not
# determinism levers.
WATCHED_ENV_KEYS = ("XLA_FLAGS", "PYTHONHASHSEED")
WATCHED_CONFIG_KEYS = ("jax_default_matmul_precision", "jax_enable_x64",
                       "jax_threefry_partitionable",
                       "jax_default_prng_impl")

_FS_ENUM = {"glob.glob": "glob.glob", "glob.iglob": "glob.iglob",
            "os.listdir": "os.listdir", "os.scandir": "os.scandir"}
_FS_ENUM_METHODS = ("glob", "rglob", "iterdir")


def _is_rng_constructor(resolved: str) -> bool:
    if resolved.startswith("numpy.random."):
        return True
    if resolved == "random" or resolved.startswith("random."):
        return True
    return resolved in _JAX_KEY_CONSTRUCTORS


@dataclasses.dataclass
class ModuleDetModel:
    """Everything the GD rules read about one module."""

    aliases: Dict[str, str]
    rng_constructors: List[RngConstructorSite]
    derive_calls: List[DeriveSite]
    time_seeds: List[TimeSeedSite]
    flag_writes: List[FlagWriteSite]
    unsorted_globs: List[UnsortedGlobSite]
    set_iters: List[SetIterSite]
    hazard_ops: List[HazardOpSite]


def _at_accumulate(call: ast.Call) -> Optional[str]:
    """``x.at[idx].add(...)``-shaped scatter-accumulate, or None."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _AT_ACCUM_METHODS):
        return None
    sub = fn.value
    if isinstance(sub, ast.Subscript) and \
            isinstance(sub.value, ast.Attribute) and sub.value.attr == "at":
        return f".at[].{fn.attr}"
    return None


def build_module_det_model(tree: ast.Module) -> ModuleDetModel:
    aliases = build_alias_map(tree)
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node

    model = ModuleDetModel(aliases, [], [], [], [], [], [], [])

    def seed_expr_taint(call: ast.Call, seeding: str) -> None:
        for sub in ast.walk(call):
            if isinstance(sub, ast.Call) and sub is not call:
                r = resolve_dotted(sub.func, aliases)
                if r in _TIME_ENTROPY:
                    model.time_seeds.append(TimeSeedSite(
                        sub.lineno, sub.col_offset, r, seeding))

    for node in ast.walk(tree):
        # -- iteration-order hazards (GD005) --------------------------------
        if isinstance(node, (ast.For, ast.comprehension)):
            it = node.iter
            line = getattr(node, "lineno", None) or \
                getattr(it, "lineno", 0)
            col = getattr(node, "col_offset", None)
            if col is None:
                col = getattr(it, "col_offset", 0)
            if isinstance(it, ast.Set):
                model.set_iters.append(SetIterSite(
                    line, col, "iterates a set literal"))
            elif isinstance(it, ast.Call) and \
                    _tail(it.func) in ("set", "frozenset") and \
                    resolve_dotted(it.func, aliases) in ("set", "frozenset"):
                model.set_iters.append(SetIterSite(
                    line, col, f"iterates a {_tail(it.func)}() result"))

        if not isinstance(node, ast.Call):
            continue

        resolved = resolve_dotted(node.func, aliases)
        tail = _tail(node.func)

        # -- raw RNG constructors + time-derived seeds (GD002) --------------
        if resolved is not None and _is_rng_constructor(resolved):
            model.rng_constructors.append(RngConstructorSite(
                node.lineno, node.col_offset, resolved))
            seed_expr_taint(node, resolved)
        elif tail in _DERIVE_FUNCS and (
                resolved in _DERIVE_FUNCS
                or (resolved or "").startswith("pvraft_tpu.rng.")):
            strs = tuple(
                a.value for a in node.args
                if isinstance(a, ast.Constant) and isinstance(a.value, str))
            model.derive_calls.append(DeriveSite(
                node.lineno, node.col_offset, tail, strs))
            seed_expr_taint(node, f"{tail}(...)")

        # -- watched flag writes (GD004): call shapes ----------------------
        if resolved in ("os.environ.setdefault", "os.putenv",
                        "os.environ.update", "jax.config.update",
                        "config.update"):
            key = None
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                key = node.args[0].value
            elif resolved == "os.environ.update" and node.args and \
                    isinstance(node.args[0], ast.Dict):
                for k in node.args[0].keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str) and \
                            k.value in WATCHED_ENV_KEYS:
                        key = k.value
                        break
            watched = (key in WATCHED_ENV_KEYS
                       or key in WATCHED_CONFIG_KEYS)
            if key is not None and watched:
                model.flag_writes.append(FlagWriteSite(
                    node.lineno, node.col_offset, key, resolved))

        # -- filesystem enumeration (GD005) ---------------------------------
        fs_callee = None
        if resolved in _FS_ENUM:
            fs_callee = _FS_ENUM[resolved]
        elif tail in _FS_ENUM_METHODS and isinstance(node.func,
                                                     ast.Attribute):
            head = resolve_dotted(node.func.value, aliases) or ""
            # `glob.glob` already matched above; method form covers
            # Path objects (p.glob/p.rglob/p.iterdir).
            if head not in ("glob",):
                fs_callee = f".{tail}()"
        if fs_callee is not None:
            parent = parents.get(id(node))
            wrapped = (isinstance(parent, ast.Call)
                       and _tail(parent.func) == "sorted")
            if not wrapped:
                model.unsorted_globs.append(UnsortedGlobSite(
                    node.lineno, node.col_offset, fs_callee))

        # -- nondeterminism-hazard ops (GD003 evidence) ---------------------
        accum = _at_accumulate(node)
        if accum is not None:
            model.hazard_ops.append(HazardOpSite(
                node.lineno, node.col_offset, "scatter-accumulate", accum))
        elif tail in _SEGMENT_REDUCTIONS:
            model.hazard_ops.append(HazardOpSite(
                node.lineno, node.col_offset, "segment-reduction", tail))
        elif tail in _SCATTER_OPS:
            model.hazard_ops.append(HazardOpSite(
                node.lineno, node.col_offset, "scatter-accumulate", tail))
        elif tail in _RING_OPS:
            model.hazard_ops.append(HazardOpSite(
                node.lineno, node.col_offset, "ring-fold", tail))

    # -- watched flag writes (GD004): subscript/attribute assignment -------
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Subscript):
                base = resolve_dotted(t.value, aliases)
                key = None
                sl = t.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    key = sl.value
                if base == "os.environ" and key in WATCHED_ENV_KEYS:
                    model.flag_writes.append(FlagWriteSite(
                        node.lineno, node.col_offset, key,
                        "os.environ[...]"))
            elif isinstance(t, ast.Attribute):
                dotted = resolve_dotted(t, aliases) or ""
                leaf = dotted.rsplit(".", 1)[-1]
                if ".config." in f".{dotted}" and \
                        leaf in WATCHED_CONFIG_KEYS:
                    model.flag_writes.append(FlagWriteSite(
                        node.lineno, node.col_offset, leaf,
                        "config attribute"))

    for bucket in (model.rng_constructors, model.derive_calls,
                   model.time_seeds, model.flag_writes,
                   model.unsorted_globs, model.set_iters,
                   model.hazard_ops):
        bucket.sort(key=lambda s: (s.line, s.col))
    return model
