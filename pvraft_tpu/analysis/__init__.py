"""graftlint — JAX/TPU-aware static analysis for pvraft_tpu.

Three analysis engines plus a contract layer:

  * an AST lint engine (``pvraft_tpu.analysis.engine`` +
    ``pvraft_tpu.analysis.rules``) with TPU-specific rules: host-sync
    calls reachable from jitted code, Python control flow on tracers,
    version-fragile jax imports, module-level jnp constants baked into
    traces, and friends. Run it with

        python -m pvraft_tpu.analysis lint pvraft_tpu/ tests/

  * a jaxpr-level semantic engine (``pvraft_tpu.analysis.jaxpr``,
    ``python -m pvraft_tpu.analysis deepcheck``): GJ rules over the
    traced programs — collective consistency, donation efficacy,
    precision flow, retrace hazards.

  * a concurrency engine (``pvraft_tpu.analysis.concurrency``,
    ``python -m pvraft_tpu.analysis concurrency``): GC rules over the
    hand-threaded serve/obs/loader planes — guarded-by discipline,
    lock-order cycles, check-then-act/TOCTOU shapes, un-joined threads
    — plus the opt-in ``OrderedLock`` runtime lock-order sanitizer.

  * a shape/dtype contract layer (``pvraft_tpu.analysis.contracts``):
    the ``@shapecheck`` decorator on the package's public ops — a no-op
    unless ``PVRAFT_CHECKS=1`` — plus a ``jax.eval_shape`` trace-compat
    audit (``python -m pvraft_tpu.analysis trace``) that abstractly
    traces every registered op without running a FLOP.

All three engines share ONE ``Diagnostic`` type and ONE
``# graftlint: disable=RULE -- reason`` pragma grammar, so the
suppression-debt report (``lint --stats``) enumerates every engine's
blind spots with no second parser.

This package deliberately does NOT import jax at lint time: ``engine``,
``rules`` and ``concurrency`` are pure stdlib-``ast`` code so the
linters run in milliseconds anywhere; only ``contracts``/``audit``
(imported lazily by the ``trace`` subcommand and by decorated modules)
touch jax.
"""

from pvraft_tpu.analysis.engine import (  # noqa: F401
    Diagnostic,
    lint_paths,
    lint_source,
)
