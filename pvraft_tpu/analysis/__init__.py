"""graftlint — JAX/TPU-aware static analysis for pvraft_tpu.

Two halves:

  * an AST lint engine (``pvraft_tpu.analysis.engine`` +
    ``pvraft_tpu.analysis.rules``) with TPU-specific rules: host-sync
    calls reachable from jitted code, Python control flow on tracers,
    version-fragile jax imports, module-level jnp constants baked into
    traces, and friends. Run it with

        python -m pvraft_tpu.analysis lint pvraft_tpu/ tests/

  * a shape/dtype contract layer (``pvraft_tpu.analysis.contracts``):
    the ``@shapecheck`` decorator on the package's public ops — a no-op
    unless ``PVRAFT_CHECKS=1`` — plus a ``jax.eval_shape`` trace-compat
    audit (``python -m pvraft_tpu.analysis trace``) that abstractly
    traces every registered op without running a FLOP.

This package deliberately does NOT import jax at lint time: ``engine``
and ``rules`` are pure stdlib-``ast`` code so the linter runs in
milliseconds anywhere; only ``contracts``/``audit`` (imported lazily by
the ``trace`` subcommand and by decorated modules) touch jax.
"""

from pvraft_tpu.analysis.engine import (  # noqa: F401
    Diagnostic,
    lint_paths,
    lint_source,
)
