"""kernelcheck: Pallas/Mosaic static analysis + the VMEM/roofline planner.

The FOURTH analysis engine (after graftlint GL, deepcheck GJ and
threadcheck GC), sharing the one :class:`~pvraft_tpu.analysis.engine.
Diagnostic` type and ``# graftlint: disable=GKxxx -- reason`` pragma
grammar. Two halves:

* **checker** (``model.py`` + ``rules.py`` + ``check.py``): a concrete
  static model of every ``pallas_call`` site — grid, BlockSpecs, index
  maps, operands, kernel-body ops — and the GK001-GK006 rules over it
  (tile alignment, VMEM budget, grid coverage, Mosaic lowering hazards,
  registry coverage, interpreter escape hatch);
* **planner** (``planner.py``): joins the static models with the
  committed cost inventory into ``artifacts/kernel_plan.json``
  (``pvraft_kernel_plan/v1``) — per-kernel roofline verdicts, the
  static-vs-Mosaic HBM cross-validation pin, and the fused-GRU VMEM
  residency verdict ROADMAP item 1 cites.

CLI: ``python -m pvraft_tpu.analysis kernels [--plan]``. Pure stdlib
``ast`` + committed artifacts — no jax import anywhere on the check
path, so the gate runs on hosts with no accelerator stack at all.
"""

from pvraft_tpu.analysis.kernels.check import (         # noqa: F401
    DEFAULT_SCOPE,
    check_paths,
    check_source,
    default_scope,
    registered_kernel_modules,
)
from pvraft_tpu.analysis.kernels.model import (         # noqa: F401
    ArrayInfo,
    BlockSpecModel,
    KERNEL_BINDINGS,
    KernelModel,
    build_module_kernel_model,
)
from pvraft_tpu.analysis.kernels.planner import (       # noqa: F401
    PLAN_SCHEMA,
    build_plan,
    check_plan_file,
    fused_gru_residency,
    write_plan,
)
from pvraft_tpu.analysis.kernels.rules import (         # noqa: F401
    VMEM_BUDGET_BYTES,
    all_kernel_rules,
)
