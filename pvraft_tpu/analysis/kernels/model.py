"""Static Pallas kernel model: what the GK rules reason over.

Pure stdlib ``ast`` — like graftlint and threadcheck, this must run in
milliseconds on hosts with no accelerator stack. For every
``pl.pallas_call`` site in a scanned file the extractor produces a
:class:`KernelModel` holding the *concrete* launch geometry:

  * the ``grid`` tuple, evaluated to ints;
  * every input/output :class:`BlockSpecModel` — block shape (ints),
    the index-map lambda's AST, and the declaration site;
  * the abstract operands (``ArrayInfo``: shape + dtype) the call is
    applied to, and the declared ``out_shape`` structs;
  * the kernel body's ``FunctionDef`` (resolved through
    ``functools.partial``) for the GK004 hazard scan;
  * the ``interpret=`` keyword's AST for the GK006 escape-hatch check.

Shapes in the source are *expressions* (``(1, tile, k)`` where ``tile =
_pick_tile(n)``), so the extractor runs a tiny safe evaluator: sequential
constant propagation over the enclosing function's straight-line
assignments, seeded from a :data:`KERNEL_BINDINGS` environment (the
flagship geometry from :mod:`pvraft_tpu.programs.geometries` — the SAME
dims the ``kernel``-tagged ProgramSpecs compile at, so static numbers
and the committed Mosaic records describe one program). Module-level
helper functions (``_pick_tile``) are executed for real — compiled from
their own AST into a namespace with whitelisted builtins only, never
imported (importing ``ops/pallas`` would drag jax in).

A fixture (or future kernel) with literal dims needs no binding at all;
a kernel whose geometry can NOT be evaluated gets a ``GK000``
model-incomplete finding from the check driver — a new kernel either
models cleanly or fails the gate, it cannot silently skip analysis.

Everything here is deliberately under-approximate (no branching, no
cross-file dataflow): like the other engines, a gate that only flags
certainties gets kept.
"""

from __future__ import annotations

import ast
import dataclasses
import math
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# Bytes per element for the dtypes a kernel block can carry.
DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "fp8": 1,
}

# Dotted-tail attribute names that evaluate to a dtype string
# (``jnp.float32``, ``np.int32``, a bare ``float32`` import).
_DTYPE_TAILS = {
    "float32", "bfloat16", "float16", "float64", "int8", "int16",
    "int32", "int64", "uint8", "uint16", "uint32", "uint64",
}
_DTYPE_ALIASES = {"bool_": "bool", "bool": "bool"}


class EvalError(Exception):
    """A geometry expression the safe evaluator cannot resolve."""


def _dotted_tail(expr: ast.AST) -> str:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


@dataclasses.dataclass(frozen=True)
class ArrayInfo:
    """Abstract array: shape + dtype (the eval_shape view of an operand)."""

    shape: Tuple[int, ...]
    dtype: str = "float32"

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def nbytes(self) -> int:
        n = DTYPE_BYTES.get(self.dtype, 4)
        for d in self.shape:
            n *= d
        return n

    def __getitem__(self, key) -> "ArrayInfo":
        """Shape-level subscript: supports the slicing the kernels use
        (``xyz[..., 0]`` drops the axis, ``coords[..., 0:1]`` keeps a
        length-1 axis)."""
        if not isinstance(key, tuple):
            key = (key,)
        n_explicit = sum(1 for k in key if k is not Ellipsis)
        out: List[int] = []
        dim = 0
        for k in key:
            if k is Ellipsis:
                keep = len(self.shape) - n_explicit
                out.extend(self.shape[dim:dim + keep])
                dim += keep
            elif isinstance(k, slice):
                out.append(len(range(*k.indices(self.shape[dim]))))
                dim += 1
            elif isinstance(k, int):
                dim += 1  # integer index drops the axis
            else:
                raise EvalError(f"unsupported subscript {k!r}")
        out.extend(self.shape[dim:])
        return ArrayInfo(tuple(out), self.dtype)


@dataclasses.dataclass(frozen=True)
class BlockSpecModel:
    """One evaluated ``pl.BlockSpec``: concrete block shape + the
    index-map lambda's AST (None for whole-array specs)."""

    block: Optional[Tuple[int, ...]]
    index_map: Optional[ast.Lambda]
    line: int
    col: int

    def block_bytes(self, dtype: str) -> int:
        if self.block is None:
            return 0
        n = DTYPE_BYTES.get(dtype, 4)
        for d in self.block:
            n *= d
        return n


@dataclasses.dataclass(frozen=True)
class PartialModel:
    """``functools.partial(kernel_fn, **kw)`` — enough to resolve the
    kernel body and read its statically-evaluable keyword args."""

    func_name: str
    kwargs: Dict[str, Any]


class _InterpretMode:
    """Marker for a call to the ``interpret_mode()`` escape hatch."""


@dataclasses.dataclass
class KernelModel:
    """One ``pallas_call`` site, concretely modeled."""

    path: str
    line: int
    col: int
    func: str                     # enclosing module-level function
    kernel_fn_name: str = ""
    kernel_fn_node: Optional[ast.AST] = None
    grid: Optional[Tuple[int, ...]] = None
    in_specs: Optional[List[BlockSpecModel]] = None
    out_specs: Optional[List[BlockSpecModel]] = None
    out_info: Optional[List[ArrayInfo]] = None
    operands: Optional[List[Optional[ArrayInfo]]] = None
    scratch: Tuple[ArrayInfo, ...] = ()
    interpret_node: Optional[ast.AST] = None
    # True when `interpret=` EVALUATES to the interpret_mode() marker —
    # covers the `interp = interpret_mode()` local-variable spelling
    # the AST walk in GK006 cannot see.
    interpret_resolved: bool = False
    problems: List[str] = dataclasses.field(default_factory=list)

    def io_pairs(self) -> List[Tuple[str, BlockSpecModel, ArrayInfo]]:
        """(role, spec, operand) for every spec matched to a concrete
        operand — inputs first, then outputs."""
        out: List[Tuple[str, BlockSpecModel, ArrayInfo]] = []
        if self.in_specs and self.operands:
            for spec, op in zip(self.in_specs, self.operands):
                if op is not None:
                    out.append(("in", spec, op))
        if self.out_specs and self.out_info:
            for spec, op in zip(self.out_specs, self.out_info):
                out.append(("out", spec, op))
        return out

    def vmem_estimate_bytes(self) -> Optional[int]:
        """Static VMEM footprint: every grid-streamed block
        double-buffered (the pipeline loads the next block behind
        compute); whole-array (block=None) specs and scratch are
        resident once — not streamed, so not double-buffered."""
        pairs = self.io_pairs()
        if not pairs:
            return None
        total = 0
        for _, spec, op in pairs:
            if spec.block is None:
                total += op.nbytes
            else:
                total += 2 * spec.block_bytes(op.dtype)
        total += sum(s.nbytes for s in self.scratch)
        return total

    def hbm_operand_bytes(self) -> Optional[Tuple[int, int]]:
        """(input bytes, output bytes) of the full operands — what the
        compiled program's memory_analysis calls argument/output size."""
        if self.operands is None or self.out_info is None or \
                any(op is None for op in self.operands):
            return None
        return (sum(_hbm_layout_bytes(op) for op in self.operands
                    if op is not None),
                sum(_hbm_layout_bytes(o) for o in self.out_info))


def _hbm_layout_bytes(info: ArrayInfo) -> int:
    """HBM bytes of one operand under XLA:TPU's argument layout.

    Rank-2 arrays are stored (8, 128)-tiled — sublanes padded to 8,
    lanes to 128 — with XLA free to transpose when that wastes less
    (``pallas_gru_iter_fwd``'s (128, 64) weight lands as 64x128, zero
    pad). Rank>=3 arrays get a compact layout: XLA picks the dim order,
    and every kernel operand here has a >=128 axis to put minormost.
    Matches the committed ``programs_kernels.json`` argument sizes
    byte-exactly across all three kernels — the planner's fwd exactness
    pin (tests/test_kernelcheck.py) rides on this agreement."""
    if len(info.shape) != 2:
        return info.nbytes
    r, c = info.shape
    pad = lambda v, m: -(-v // m) * m  # noqa: E731
    elems = min(pad(r, 8) * pad(c, 128), pad(c, 8) * pad(r, 128))
    return elems * DTYPE_BYTES.get(info.dtype, 4)


@dataclasses.dataclass
class ModuleKernelModel:
    path: str
    kernels: List[KernelModel] = dataclasses.field(default_factory=list)
    functions: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)


# --- geometry bindings ------------------------------------------------------

def _flagship_env() -> Dict[str, Any]:
    from pvraft_tpu.programs import geometries as g

    b, n, k = g.FLAGSHIP_BATCH, g.FLAGSHIP_POINTS, g.FLAGSHIP_TRUNCATE_K
    corr = ArrayInfo((b, n, k))
    return {"b": b, "n": n, "k": k, "corr": corr}


def _voxel_env() -> Dict[str, Any]:
    env = _flagship_env()
    plane = ArrayInfo(env["corr"].shape)
    env.update(relx=plane, rely=plane, relz=plane,
               num_levels=3, base_scale=0.25, resolution=3)
    return env


def _fused_env() -> Dict[str, Any]:
    env = _flagship_env()
    b, n, k = env["b"], env["n"], env["k"]
    env.update(xyz=ArrayInfo((b, n, k, 3)), coords=ArrayInfo((b, n, 3)),
               num_levels=3, base_scale=0.25, resolution=3, knn=32)
    return env


def _gru_env() -> Dict[str, Any]:
    """``_gru_forward`` parameters at the flagship geometry: H=C=D=64
    feature blocks, the FLOW_PAD=8 padded flow, and the packed weight
    8-tuple from ``pack_gru_weights`` (shapes for hidden=64,
    context=64). ``truncate_k`` drives the plan-certified tile choice."""
    env = _flagship_env()
    b, n, k = env["b"], env["n"], env["k"]
    feat = ArrayInfo((b, n, 64))
    weights = (ArrayInfo((64, 64)), ArrayInfo((8, 64)),
               ArrayInfo((128, 64)), ArrayInfo((64, 192)),
               ArrayInfo((64, 192)), ArrayInfo((64, 192)),
               ArrayInfo((8, 192)), ArrayInfo((8, 192)))
    env.update(net=feat, inp=feat, cor=feat, flow8=ArrayInfo((b, n, 8)),
               weights=weights, truncate_k=k, dtype_name="float32")
    return env


# path suffix (forward slashes) -> {enclosing function: env factory}.
# The env binds the enclosing function's PARAMETERS at the flagship
# geometry — the same dims the kernel-tag ProgramSpecs Mosaic-compile at
# (programs/catalog.py), so the static model and the committed compile
# evidence describe the same program. A new kernel adds one row (or uses
# literal dims); an unbound, unevaluable kernel fails the gate via GK000.
KERNEL_BINDINGS: Dict[str, Dict[str, Callable[[], Dict[str, Any]]]] = {
    "pvraft_tpu/ops/pallas/voxel_corr.py": {
        "_voxel_forward_pallas": _voxel_env,
    },
    "pvraft_tpu/ops/pallas/corr_lookup.py": {
        "_fused_forward": _fused_env,
    },
    "pvraft_tpu/ops/pallas/gru_iter.py": {
        "_gru_forward": _gru_env,
    },
}


def binding_for(path: str, func: str) -> Dict[str, Any]:
    norm = path.replace("\\", "/")
    for suffix, funcs in KERNEL_BINDINGS.items():
        if norm.endswith(suffix) and func in funcs:
            return funcs[func]()
    return {}


# --- the safe evaluator -----------------------------------------------------

_SAFE_BUILTINS = {
    "range": range, "min": min, "max": max, "len": len, "abs": abs,
    "int": int, "float": float, "sum": sum, "tuple": tuple, "list": list,
    "enumerate": enumerate, "sorted": sorted, "round": round,
}

_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.Div: lambda a, b: a / b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Mod: lambda a, b: a % b,
    ast.Pow: lambda a, b: a ** b,
}


class _Evaluator:
    """Evaluates straight-line geometry expressions against an env.

    Module-level helper functions referenced by name (``_pick_tile``)
    are compiled from their own AST and executed in a namespace holding
    only :data:`_SAFE_BUILTINS` — real logic, no imports, no jax.
    """

    def __init__(self, env: Dict[str, Any],
                 module_funcs: Dict[str, ast.AST]):
        self.env = env
        self.module_funcs = module_funcs
        self._compiled: Dict[str, Callable] = {}

    def eval(self, node: ast.AST) -> Any:
        method = getattr(self, f"_eval_{type(node).__name__}", None)
        if method is None:
            raise EvalError(f"unsupported expression {type(node).__name__}")
        try:
            return method(node)
        except EvalError:
            raise
        except Exception as e:  # noqa: BLE001 — a TypeError from
            # ArrayInfo arithmetic, a ZeroDivisionError in a dim
            # expression, tuple(<int>) on a scalar block shape: ANY
            # failure inside the sandbox must surface as a GK000
            # model-incomplete finding, never crash the gate.
            raise EvalError(f"{type(e).__name__}: {e}") from e

    # -- leaves --------------------------------------------------------------

    def _eval_Constant(self, node: ast.Constant) -> Any:
        return node.value

    def _eval_Name(self, node: ast.Name) -> Any:
        if node.id in self.env:
            return self.env[node.id]
        raise EvalError(f"unbound name {node.id!r}")

    def _eval_Attribute(self, node: ast.Attribute) -> Any:
        if node.attr in _DTYPE_TAILS:
            return node.attr
        if node.attr in _DTYPE_ALIASES:
            return _DTYPE_ALIASES[node.attr]
        if node.attr == "inf":
            return math.inf
        base = self.eval(node.value)
        if isinstance(base, ArrayInfo) and node.attr in ("shape", "dtype",
                                                         "ndim", "nbytes"):
            return getattr(base, node.attr)
        raise EvalError(f"unsupported attribute .{node.attr}")

    # -- structure -----------------------------------------------------------

    def _eval_Tuple(self, node: ast.Tuple) -> tuple:
        return tuple(self.eval(e) for e in node.elts)

    def _eval_List(self, node: ast.List) -> list:
        return [self.eval(e) for e in node.elts]

    def _eval_BinOp(self, node: ast.BinOp) -> Any:
        op = _BINOPS.get(type(node.op))
        if op is None:
            raise EvalError(f"unsupported operator {type(node.op).__name__}")
        return op(self.eval(node.left), self.eval(node.right))

    def _eval_UnaryOp(self, node: ast.UnaryOp) -> Any:
        val = self.eval(node.operand)
        if isinstance(node.op, ast.USub):
            return -val
        if isinstance(node.op, ast.UAdd):
            return +val
        raise EvalError(f"unsupported unary {type(node.op).__name__}")

    def _eval_Subscript(self, node: ast.Subscript) -> Any:
        base = self.eval(node.value)
        key = self._eval_key(node.slice)
        try:
            return base[key]
        except (TypeError, IndexError, KeyError) as e:
            raise EvalError(f"subscript failed: {e}") from e

    def _eval_key(self, node: ast.AST) -> Any:
        if isinstance(node, ast.Tuple):
            return tuple(self._eval_key(e) for e in node.elts)
        if isinstance(node, ast.Slice):
            return slice(
                None if node.lower is None else self.eval(node.lower),
                None if node.upper is None else self.eval(node.upper),
                None if node.step is None else self.eval(node.step))
        val = self.eval(node)
        return val

    def _eval_IfExp(self, node: ast.IfExp) -> Any:
        return self.eval(node.body) if self.eval(node.test) \
            else self.eval(node.orelse)

    def _eval_GeneratorExp(self, node: ast.GeneratorExp) -> Any:
        return self._comprehend(node.elt, node.generators)

    def _eval_ListComp(self, node: ast.ListComp) -> Any:
        return self._comprehend(node.elt, node.generators)

    def _comprehend(self, elt: ast.AST, generators) -> list:
        if len(generators) != 1:
            raise EvalError("only single-generator comprehensions")
        gen = generators[0]
        if not isinstance(gen.target, ast.Name):
            raise EvalError("only simple comprehension targets")
        out = []
        for item in self.eval(gen.iter):
            sub = _Evaluator(dict(self.env, **{gen.target.id: item}),
                             self.module_funcs)
            if all(sub.eval(cond) for cond in gen.ifs):
                out.append(sub.eval(elt))
        return out

    # -- calls ---------------------------------------------------------------

    def _eval_Call(self, node: ast.Call) -> Any:
        tail = _dotted_tail(node.func)
        if tail == "BlockSpec":
            return self._block_spec(node)
        if tail == "ShapeDtypeStruct":
            shape = tuple(self.eval(node.args[0]))
            dtype = self.eval(node.args[1]) if len(node.args) > 1 else \
                "float32"
            if not isinstance(dtype, str):
                raise EvalError(f"non-string dtype {dtype!r}")
            return ArrayInfo(shape, _DTYPE_ALIASES.get(dtype, dtype))
        if tail == "partial":
            return self._partial(node)
        if tail == "interpret_mode":
            return _InterpretMode()
        if tail in ("stop_gradient",):
            return self.eval(node.args[0])
        if tail == "tuple" and len(node.args) == 1:
            return tuple(self.eval(node.args[0]))
        if tail in _SAFE_BUILTINS and isinstance(node.func, ast.Name):
            args = [self.eval(a) for a in node.args]
            return _SAFE_BUILTINS[tail](*args)
        if tail in self.module_funcs and isinstance(node.func, ast.Name):
            fn = self._compile_module_func(tail)
            args = [self.eval(a) for a in node.args]
            kwargs = {kw.arg: self.eval(kw.value)
                      for kw in node.keywords if kw.arg}
            try:
                return fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — helper misuse -> EvalError
                raise EvalError(f"{tail}() raised {type(e).__name__}: {e}")
        raise EvalError(f"unsupported call {tail or '<expr>'}()")

    def _block_spec(self, node: ast.Call) -> BlockSpecModel:
        block_node: Optional[ast.AST] = node.args[0] if node.args else None
        index_node: Optional[ast.AST] = node.args[1] if len(node.args) > 1 \
            else None
        for kw in node.keywords:
            if kw.arg == "block_shape":
                block_node = kw.value
            elif kw.arg == "index_map":
                index_node = kw.value
        block = None
        if block_node is not None and not (
                isinstance(block_node, ast.Constant)
                and block_node.value is None):
            block = tuple(self.eval(block_node))
            if not all(isinstance(d, int) for d in block):
                raise EvalError(f"non-integer block shape {block!r}")
        index_map = index_node if isinstance(index_node, ast.Lambda) else None
        return BlockSpecModel(block=block, index_map=index_map,
                              line=node.lineno, col=node.col_offset)

    def _partial(self, node: ast.Call) -> PartialModel:
        if not node.args:
            raise EvalError("partial() with no function")
        func_name = _dotted_tail(node.args[0])
        kwargs: Dict[str, Any] = {}
        for kw in node.keywords:
            if kw.arg is None:
                continue
            try:
                kwargs[kw.arg] = self.eval(kw.value)
            except EvalError:
                pass  # best-effort: geometry rules don't need every kwarg
        return PartialModel(func_name=func_name, kwargs=kwargs)

    def _compile_module_func(self, name: str) -> Callable:
        if name not in self._compiled:
            fndef = self.module_funcs[name]
            mod = ast.Module(body=[fndef], type_ignores=[])
            ast.fix_missing_locations(mod)
            ns: Dict[str, Any] = {"__builtins__": dict(_SAFE_BUILTINS)}
            try:
                exec(compile(mod, "<kernelcheck>", "exec"), ns)  # noqa: S102
            except Exception as e:  # noqa: BLE001
                raise EvalError(
                    f"helper {name} does not compile standalone: {e}")
            self._compiled[name] = ns[name]
        return self._compiled[name]


# --- extraction -------------------------------------------------------------

def _propagate(fn: ast.AST, env: Dict[str, Any],
               module_funcs: Dict[str, ast.AST]) -> _Evaluator:
    """Sequential constant propagation over the function's top-level
    straight-line assignments. Unevaluable values are simply left
    unbound — the rules that need them report precisely what's missing."""
    ev = _Evaluator(env, module_funcs)
    for stmt in getattr(fn, "body", ()):
        if not isinstance(stmt, ast.Assign):
            continue
        try:
            value = ev.eval(stmt.value)
        except EvalError:
            continue
        for target in stmt.targets:
            if isinstance(target, ast.Name):
                env[target.id] = value
            elif isinstance(target, ast.Tuple) and all(
                    isinstance(e, ast.Name) for e in target.elts):
                try:
                    parts = tuple(value)
                except TypeError:
                    continue
                if len(parts) == len(target.elts):
                    for e, v in zip(target.elts, parts):
                        env[e.id] = v
    return ev


def _as_spec_list(value: Any) -> Optional[List[BlockSpecModel]]:
    if isinstance(value, BlockSpecModel):
        return [value]
    if isinstance(value, (list, tuple)) and all(
            isinstance(v, BlockSpecModel) for v in value):
        return list(value)
    return None


def _as_info_list(value: Any) -> Optional[List[ArrayInfo]]:
    if isinstance(value, ArrayInfo):
        return [value]
    if isinstance(value, (list, tuple)) and all(
            isinstance(v, ArrayInfo) for v in value):
        return list(value)
    return None


def _attach_parents(root: ast.AST) -> None:
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            child._gk_parent = node  # type: ignore[attr-defined]


def _extract_site(call: ast.Call, fn: ast.FunctionDef, ev: _Evaluator,
                  path: str, module_funcs: Dict[str, ast.AST]
                  ) -> KernelModel:
    model = KernelModel(path=path, line=call.lineno, col=call.col_offset,
                        func=fn.name)

    # Kernel body: first positional arg, possibly through a partial.
    if call.args:
        kernel_arg = call.args[0]
        name = _dotted_tail(kernel_arg)
        resolved: Any = None
        try:
            resolved = ev.eval(kernel_arg)
        except EvalError:
            pass
        if isinstance(resolved, PartialModel):
            name = resolved.func_name
        if name:
            model.kernel_fn_name = name
            model.kernel_fn_node = module_funcs.get(name)

    kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}

    def need(key: str, convert, required: bool = True):
        node = kwargs.get(key)
        if node is None:
            if required:
                model.problems.append(f"missing `{key}=` keyword")
            return None
        try:
            value = ev.eval(node)
        except EvalError as e:
            model.problems.append(f"`{key}=` not statically evaluable "
                                  f"({e})")
            return None
        out = convert(value)
        if out is None:
            model.problems.append(f"`{key}=` evaluated to an unexpected "
                                  f"{type(value).__name__}")
        return out

    def as_grid(value):
        if isinstance(value, int):
            return (value,)
        if isinstance(value, tuple) and all(
                isinstance(v, int) for v in value):
            return value
        return None

    model.grid = need("grid", as_grid)
    model.in_specs = need("in_specs", _as_spec_list)
    model.out_specs = need("out_specs", _as_spec_list)
    model.out_info = need("out_shape", _as_info_list)
    model.interpret_node = kwargs.get("interpret")
    if model.interpret_node is not None:
        try:
            value = ev.eval(model.interpret_node)
        except EvalError:
            pass
        else:
            model.interpret_resolved = isinstance(value, _InterpretMode)

    scratch_node = kwargs.get("scratch_shapes")
    if scratch_node is not None:
        try:
            value = ev.eval(scratch_node)
        except EvalError:
            model.problems.append(
                "`scratch_shapes=` not statically evaluable")
        else:
            infos = _as_info_list(value)
            if infos is not None:
                model.scratch = tuple(infos)

    # Operands: the immediate outer call `pl.pallas_call(...)(ops...)`.
    parent = getattr(call, "_gk_parent", None)
    if isinstance(parent, ast.Call) and parent.func is call:
        ops: List[Optional[ArrayInfo]] = []
        for arg in parent.args:
            try:
                value = ev.eval(arg)
            except EvalError:
                ops.append(None)
                continue
            ops.append(value if isinstance(value, ArrayInfo) else None)
        model.operands = ops
        if any(op is None for op in ops):
            model.problems.append(
                "some call operands are not statically evaluable")
    else:
        model.problems.append(
            "pallas_call result is not applied at the call site — "
            "operands unknown")
    return model


def _imported_helpers(tree: ast.Module, path: str) -> Dict[str, ast.AST]:
    """FunctionDefs imported ``from pvraft_tpu... import name`` resolved
    from their home module's AST — so a helper like ``_pick_tile``
    (defined in ``voxel_corr.py``, imported by ``corr_lookup.py``)
    evaluates in both files. Source-level only: nothing is imported."""
    norm = os.path.abspath(path).replace(os.sep, "/")
    if "/pvraft_tpu/" not in norm:
        return {}
    root = norm.rsplit("/pvraft_tpu/", 1)[0]
    out: Dict[str, ast.AST] = {}
    for stmt in tree.body:
        if not (isinstance(stmt, ast.ImportFrom) and stmt.module
                and stmt.module.startswith("pvraft_tpu")):
            continue
        target = os.path.join(root, *stmt.module.split(".")) + ".py"
        try:
            with open(target, "r", encoding="utf-8-sig") as fh:
                other = ast.parse(fh.read(), filename=target)
        except (OSError, SyntaxError):
            continue
        wanted = {a.name: a.asname or a.name for a in stmt.names}
        for node in other.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in wanted:
                out[wanted[node.name]] = node
    return out


def build_module_kernel_model(tree: ast.Module, source: str,
                              path: str) -> ModuleKernelModel:
    """Extract every ``pallas_call`` site's :class:`KernelModel`."""
    del source  # symmetry with the other engines' builders
    module = ModuleKernelModel(path=path)
    module.functions = _imported_helpers(tree, path)
    module.functions.update({
        stmt.name: stmt for stmt in tree.body
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
    })
    _attach_parents(tree)
    for fn in tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        sites = [node for node in ast.walk(fn)
                 if isinstance(node, ast.Call)
                 and _dotted_tail(node.func) == "pallas_call"]
        if not sites:
            continue
        env = binding_for(path, fn.name)
        # Function parameters with defaults evaluate too (fixtures).
        defaults_ev = _Evaluator(dict(env), module.functions)
        args = fn.args
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):],
                                args.defaults):
            if arg.arg not in env:
                try:
                    env[arg.arg] = defaults_ev.eval(default)
                except EvalError:
                    pass
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None and arg.arg not in env:
                try:
                    env[arg.arg] = defaults_ev.eval(default)
                except EvalError:
                    pass
        ev = _propagate(fn, env, module.functions)
        for call in sites:
            module.kernels.append(
                _extract_site(call, fn, ev, path, module.functions))
    return module
