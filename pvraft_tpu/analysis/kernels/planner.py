"""VMEM/roofline planner: the ``pvraft_kernel_plan/v1`` artifact.

Joins the static kernel models (``kernels/model.py``, flagship-geometry
bindings) with the committed cost inventory
(``artifacts/programs_costs.json``) into a machine-checked plan:

* per ``kernel``-tagged ProgramSpec: arithmetic intensity (XLA flops /
  bytes accessed), a memory- vs compute-bound verdict against the v5e
  roofline, the static VMEM footprint, AND the static-vs-Mosaic HBM
  cross-validation — the static model's operand/output bytes must agree
  with the real deviceless compile's ``memory_analysis`` within
  :data:`CROSS_VALIDATION_FACTOR` (the pinned factor; backward programs
  legitimately diverge where XLA dead-code-eliminates an unused forward
  operand, which is why the pin is a factor and not equality);

* the headline: the **fused-GRU-iteration VMEM residency** computation
  ROADMAP item 1 demands — can the truncated correlation features
  (corr + candidate xyz, iteration-invariant) plus GRU hidden/context
  state for a tile of a 2048/8192-point scene stay VMEM-resident across
  all 32 lookup→MotionEncoder→ConvGRU iterations, at which tile size,
  with how much headroom — so the fusion kernel's expected roofline
  gain is a committed number BEFORE the kernel is written.

Everything is a pure function of committed inputs (geometry constants,
static models, the costs artifact) — no timestamps, no toolchain — so
the committed ``artifacts/kernel_plan.json`` is byte-deterministic and
``--plan --check`` regenerates and compares it exactly.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pvraft_tpu.analysis.engine import iter_py_files
from pvraft_tpu.analysis.kernels.check import (
    check_paths,
    default_scope,
    kernel_spec_imports,
)
from pvraft_tpu.analysis.kernels.model import KernelModel
from pvraft_tpu.analysis.kernels.rules import VMEM_BUDGET_BYTES

PLAN_SCHEMA = "pvraft_kernel_plan/v1"

# Static-vs-Mosaic agreement pin: static operand+output bytes vs the
# compiled memory_analysis argument+output bytes, both directions.
# Forward kernels agree essentially exactly today (ratios 1.0 /
# 0.999997 — the committed plan records them); the VJP programs sit at
# ~1.04-1.10x where XLA DCEs the unused `corr` operand out of the
# backward. 2.0 fails on the first real divergence (a dropped operand
# plane, a doubled buffer) while tolerating DCE.
CROSS_VALIDATION_FACTOR = 2.0

# v5e single-chip roofline (public TPU v5e specs): peak MXU throughput
# and HBM bandwidth. fp32 runs at half the bf16 MXU rate.
PEAK_FLOPS_BF16 = 197e12
PEAK_FLOPS_F32 = 98.5e12
HBM_BYTES_PER_S = 819e9

# The GRU refinement loop the fusion campaign targets: the paper's eval
# protocol runs 32 iterations (training runs FLAGSHIP_ITERS=8; 32 is
# the harder residency case and the serving-relevant one).
FUSED_GRU_ITERS = 32


def _round(x: float, sig: int = 6) -> float:
    """Stable float rounding so the artifact is byte-deterministic."""
    return float(f"{x:.{sig}g}")


# --- static model collection ------------------------------------------------

def collect_models(paths: Optional[Sequence[str]] = None,
                   ) -> Dict[str, List[KernelModel]]:
    """path-suffix ('pvraft_tpu/ops/pallas/x.py') -> kernel models."""
    from pvraft_tpu.analysis.kernels.model import build_module_kernel_model

    out: Dict[str, List[KernelModel]] = {}
    for f in iter_py_files(list(paths) if paths else list(default_scope())):
        with open(f, "r", encoding="utf-8-sig") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=f)
        except SyntaxError:
            continue
        module = build_module_kernel_model(tree, source, f)
        if not module.kernels:
            continue
        norm = os.path.abspath(f).replace(os.sep, "/")
        # rsplit: a checkout cloned into a directory itself named
        # pvraft_tpu must still yield the package-relative suffix.
        suffix = "pvraft_tpu/" + norm.rsplit("/pvraft_tpu/", 1)[-1] \
            if "/pvraft_tpu/" in norm else norm
        out[suffix] = module.kernels
    return out


def spec_module_map() -> Dict[str, str]:
    """kernel-tag ProgramSpec name -> the Pallas module suffix its
    thunk imports — a view over :func:`~.check.kernel_spec_imports`
    (THE catalog inspection, shared with GK005 so the two cannot
    drift). Specs importing several Pallas modules are ambiguous; the
    plan build reports them as problems rather than guessing."""
    return {name: mods[0]
            for name, mods in kernel_spec_imports().items() if mods}


# --- per-kernel roofline records -------------------------------------------

def _kernel_records(models: Dict[str, List[KernelModel]],
                    costs: Dict[str, Any],
                    imports: Optional[Dict[str, List[str]]] = None,
                    ) -> Tuple[List[Dict[str, Any]], List[str]]:
    """One plan record per kernel-tag cost record; problems listed
    separately (an out-of-pin cross-validation is a plan FAILURE).
    ``imports``: a pre-computed :func:`kernel_spec_imports` result so
    one catalog inspection serves the whole build."""
    cost_by_name = {r["name"]: r for r in costs.get("programs", ())
                    if isinstance(r, dict)}
    records: List[Dict[str, Any]] = []
    problems: List[str] = []
    if imports is None:
        imports = kernel_spec_imports()
    for name in sorted(imports):
        mods = imports[name]
        if len(mods) != 1:
            problems.append(
                f"kernel spec {name!r} imports {len(mods)} Pallas "
                f"modules ({mods}) — the planner needs an unambiguous "
                f"spec->module mapping; split the spec per module")
            continue
        module = mods[0]
        rec_cost = cost_by_name.get(name)
        if rec_cost is None:
            problems.append(
                f"kernel spec {name!r} has no record in the costs "
                f"artifact — regenerate programs_costs.json")
            continue
        kms = models.get(module, [])
        if not kms:
            problems.append(
                f"kernel spec {name!r} maps to {module!r} but no "
                f"pallas_call site was statically modeled there")
            continue
        if len(kms) > 1:
            # A second pallas_call in the module would make the
            # single-site record silently wrong (the compiled
            # memory_analysis covers the whole program) — refuse
            # loudly instead.
            problems.append(
                f"kernel spec {name!r}: {module!r} has {len(kms)} "
                f"pallas_call sites but the planner models one program "
                f"per module — split the module or extend the planner")
            continue
        km = kms[0]
        flops = float(rec_cost.get("flops", 0.0) or 0.0)
        bytes_acc = float(rec_cost.get("bytes_accessed", 0.0) or 0.0)
        mem = rec_cost.get("memory") or {}
        rec: Dict[str, Any] = {
            "name": name,
            "module": module,
            "grid": list(km.grid or ()),
            "static_vmem_bytes": km.vmem_estimate_bytes(),
            "vmem_budget_bytes": VMEM_BUDGET_BYTES,
            "flops": flops,
            "bytes_accessed": bytes_acc,
        }
        intensity = flops / bytes_acc if bytes_acc else 0.0
        ridge = PEAK_FLOPS_F32 / HBM_BYTES_PER_S
        rec["arithmetic_intensity_flops_per_byte"] = _round(intensity)
        rec["ridge_point_f32_flops_per_byte"] = _round(ridge)
        if flops == 0.0:
            # XLA's cost model does not see inside a Pallas custom
            # call: zero recorded flops means "Pallas body", and the
            # lookup is gather/VPU work with trivial FLOP density —
            # memory-bound regardless of the uncounted flops.
            rec["bound"] = "memory"
            rec["bound_basis"] = ("xla cost_analysis counts no flops "
                                  "inside the Pallas custom call; "
                                  "bytes dominate regardless")
        else:
            rec["bound"] = "memory" if intensity < ridge else "compute"
            rec["bound_basis"] = "arithmetic intensity vs f32 ridge point"
        if "optimal_seconds" in rec_cost and \
                float(rec_cost["optimal_seconds"]) > 0:
            rec["xla_optimal_seconds"] = _round(
                float(rec_cost["optimal_seconds"]))

        # Static-vs-Mosaic HBM cross-validation (the pinned factor).
        hbm = km.hbm_operand_bytes()
        if hbm is not None and mem:
            static_total = hbm[0] + hbm[1]
            compiled_total = (int(mem.get("argument_size_in_bytes", 0))
                              + int(mem.get("output_size_in_bytes", 0)))
            rec["static_hbm_bytes"] = static_total
            rec["compiled_hbm_bytes"] = compiled_total
            ratio = (static_total / compiled_total
                     if compiled_total else float("inf"))
            rec["static_vs_compiled_ratio"] = _round(ratio)
            rec["cross_validation_factor"] = CROSS_VALIDATION_FACTOR
            ok = (1.0 / CROSS_VALIDATION_FACTOR <= ratio
                  <= CROSS_VALIDATION_FACTOR)
            rec["cross_validated"] = ok
            if not ok:
                problems.append(
                    f"{name}: static HBM estimate {static_total} B vs "
                    f"compiled {compiled_total} B — ratio "
                    f"{ratio:.2f} outside the pinned "
                    f"[1/{CROSS_VALIDATION_FACTOR:g}, "
                    f"{CROSS_VALIDATION_FACTOR:g}] band; the static "
                    f"model and the real program have diverged")
        else:
            problems.append(
                f"{name}: cross-validation impossible (static operands "
                f"or compiled memory analysis missing)")
        records.append(rec)
    return records, problems


# --- the fused-GRU residency computation -----------------------------------

def _gru_dims() -> Dict[str, int]:
    """The per-point feature widths of one GRU refinement iteration —
    read from the REAL declarations (ModelConfig defaults, a jax-free
    dataclass, and the flagship geometry), so a hyperparameter change
    regenerates a different plan and the lint.sh compare stage catches
    the stale committed verdict instead of staying wrong-but-green."""
    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.programs import geometries as g

    cfg = ModelConfig(truncate_k=g.FLAGSHIP_TRUNCATE_K)
    return {
        "k": cfg.truncate_k,
        "hidden": cfg.hidden_dim,
        "context": cfg.context_dim,
        "vox_features": cfg.corr_levels * cfg.resolution ** 3,
        "knn": cfg.corr_knn,
    }


def fused_gru_residency(n_points: int, truncate_k: Optional[int] = None,
                        iters: int = FUSED_GRU_ITERS,
                        budget: int = VMEM_BUDGET_BYTES) -> Dict[str, Any]:
    """Max point-tile that keeps one fused GRU iteration chain
    VMEM-resident, with headroom.

    Residency model (fp32, bytes per tile of T points):

    * **resident across all iterations** — loaded once per tile, the
      whole point of the fusion: corr (T, K), candidate xyz planes
      3 x (T, K), GRU hidden (T, 64), context (T, 64), coords (T, 3);
    * **per-iteration working set** — live within one iteration, reused
      across iterations: voxel features (T, 81), knn corr (T, 32), knn
      rel (T, 96), MotionEncoder activations 3 x (T, 64), GRU gate
      activations 4 x (T, 128+) inputs/z/r/q, flow delta (T, 3).

    Tiles are multiples of 8 (fp32 sublane) dividing ``n_points``.
    """
    d = _gru_dims()
    k = truncate_k if truncate_k is not None else d["k"]
    f32 = 4

    def tile_bytes(t: int) -> Tuple[int, int]:
        resident = t * f32 * (
            k                     # corr (T, K)
            + 3 * k               # candidate xyz planes 3 x (T, K)
            + d["hidden"]         # GRU hidden state
            + d["context"]        # context features
            + 3                   # current coords
        )
        working = t * f32 * (
            d["vox_features"]     # voxel pyramid features
            + d["knn"]            # knn corr
            + 3 * d["knn"]        # knn rel offsets
            + 3 * d["hidden"]     # MotionEncoder activations
            + 4 * 2 * d["hidden"]  # GRU concat input + z/r/q gates
            + 3                   # flow delta
        )
        return resident, working

    tiles = [t for t in range(8, n_points + 1, 8) if n_points % t == 0]
    best: Optional[int] = None
    for t in tiles:
        resident, working = tile_bytes(t)
        if resident + working <= budget:
            best = t
    out: Dict[str, Any] = {
        "n_points": n_points,
        "truncate_k": k,
        "iters": iters,
        "vmem_budget_bytes": budget,
    }
    full_res, full_work = tile_bytes(n_points)
    out["full_scene_bytes"] = full_res + full_work
    out["full_scene_resident"] = full_res + full_work <= budget
    if best is None:
        out["fits"] = False
        out["verdict"] = (
            f"no multiple-of-8 tile of {n_points} points fits the "
            f"{budget // 2**20} MiB budget at K={k}")
        return out
    resident, working = tile_bytes(best)
    out.update({
        "fits": True,
        "tile_points": best,
        "resident_bytes": resident,
        "working_bytes": working,
        "total_bytes": resident + working,
        "headroom_bytes": budget - resident - working,
        "n_tiles": n_points // best,
    })
    # The roofline gain: unfused, every iteration re-reads the (N, K)
    # candidate block (corr + 3 xyz planes) from HBM; fused, each tile
    # reads it once and keeps it resident for all `iters` iterations.
    per_iter_hbm = n_points * 4 * k * f32
    out["unfused_candidate_hbm_bytes"] = per_iter_hbm * iters
    out["fused_candidate_hbm_bytes"] = per_iter_hbm
    out["candidate_traffic_reduction_factor"] = iters
    out["verdict"] = (
        f"resident at tile={best} (x{n_points // best} tiles): "
        f"{(resident + working) / 2**20:.2f} MiB of "
        f"{budget // 2**20} MiB, headroom "
        f"{(budget - resident - working) / 2**20:.2f} MiB; candidate "
        f"block read once instead of {iters}x -> {iters}x less HBM "
        f"traffic on the lookup's dominant operand")
    return out


def shipped_gru_geometry() -> Dict[str, Any]:
    """The tile geometry the SHIPPED fused kernel actually runs
    (``ops/pallas/gru_iter.py``), derived from the kernel's own tile
    policy and the real model dims — a hyperparameter or policy change
    regenerates a different plan and the compare stage catches it.

    The shipped kernel fuses MotionEncoder+ConvGRU **within one
    iteration**; the cross-iteration residency the study rows above
    model is precluded at exact parity because every iteration runs
    global ops over the full point axis between GRU updates (GroupNorm
    statistics inside the CorrLookup heads, the FlowHead's SetConv
    graph gathers) — a tile cannot stay resident across an all-points
    barrier. The per-iteration fusion still removes one full HBM
    round-trip of the hx concat + gate activations per iteration.
    """
    from pvraft_tpu.ops.pallas.gru_iter import FLOW_PAD, _gru_tile
    from pvraft_tpu.programs import geometries as g

    d = _gru_dims()
    h, c, f32 = d["hidden"], d["context"], 4
    # Whole-array weight residency: wc, wf, wh, wn3, wi3, wh3, wf3, bias
    # (the packed lane-stacked layout pack_gru_weights emits).
    weight_bytes = f32 * (
        h * h + FLOW_PAD * h + 2 * h * h
        + (h + c + 2 * FLOW_PAD) * 3 * h
    )
    rows = []
    for k in (d["k"], 128):
        t = _gru_tile(g.FLAGSHIP_POINTS, k)
        # Streamed per grid step: net/inp/cor (T, h) + flow8 (T, 8) in,
        # net (T, h) out; GK002's double-buffer model (2x streamed).
        stream_bytes = t * f32 * (4 * h + FLOW_PAD)
        vmem = 2 * stream_bytes + weight_bytes
        rows.append({
            "truncate_k": k,
            "n_points": g.FLAGSHIP_POINTS,
            "tile_points": t,
            "streamed_block_bytes": stream_bytes,
            "resident_weight_bytes": weight_bytes,
            "vmem_bytes": vmem,
            "vmem_budget_bytes": VMEM_BUDGET_BYTES,
            "fits": vmem <= VMEM_BUDGET_BYTES,
        })
    return {
        "module": "pvraft_tpu/ops/pallas/gru_iter.py",
        "scope": "per-iteration MotionEncoder+ConvGRU fusion",
        "cross_iteration_residency": False,
        "why_not_cross_iteration": (
            "every refinement iteration runs full-point-axis global ops "
            "between GRU updates (GroupNorm statistics in the CorrLookup "
            "heads, SetConv graph gathers in the FlowHead), so a point "
            "tile cannot stay VMEM-resident across iterations at exact "
            "numerical parity; the study rows above remain the "
            "what-if-restructured ceiling"),
        "tiles": rows,
    }


# --- plan assembly ----------------------------------------------------------

def build_plan(costs_path: str,
               paths: Optional[Sequence[str]] = None) -> Dict[str, Any]:
    """The full ``pvraft_kernel_plan/v1`` document. Raises ValueError
    on any plan problem (missing costs record, failed cross-validation,
    kernelcheck findings in the scanned scope) — the plan is only
    committable when the checker and the pin agree."""
    with open(costs_path, "r", encoding="utf-8") as f:
        costs = json.load(f)
    models = collect_models(paths)
    # One catalog inspection serves both GK005 (via check_paths) and
    # the spec->module mapping below.
    imports = kernel_spec_imports()
    registered = {m for mods in imports.values() for m in mods}
    findings, _notes, _n = check_paths(
        list(paths) if paths else list(default_scope()),
        registered_modules=registered)
    records, problems = _kernel_records(models, costs, imports)
    if findings:
        problems = [f"kernelcheck finding: {d.format()}"
                    for d in findings] + problems
    if problems:
        raise ValueError("kernel plan cannot be built:\n  "
                         + "\n  ".join(problems))

    from pvraft_tpu.programs import geometries as g

    residency = [
        fused_gru_residency(2048),
        fused_gru_residency(g.FLAGSHIP_POINTS),
        # Planning alternatives: a truncated candidate set buys bigger
        # resident tiles (the corr features dominate at K=512).
        fused_gru_residency(g.FLAGSHIP_POINTS, truncate_k=256),
        fused_gru_residency(g.FLAGSHIP_POINTS, truncate_k=128),
    ]
    return {
        "schema": PLAN_SCHEMA,
        "topology": costs.get("topology"),
        "costs_artifact": os.path.basename(costs_path),
        "vmem_budget_bytes": VMEM_BUDGET_BYTES,
        "roofline": {
            "peak_flops_bf16": PEAK_FLOPS_BF16,
            "peak_flops_f32": PEAK_FLOPS_F32,
            "hbm_bytes_per_s": HBM_BYTES_PER_S,
            "basis": "public TPU v5e single-chip specs",
        },
        "cross_validation_factor": CROSS_VALIDATION_FACTOR,
        "kernels": records,
        "fused_gru_residency": residency,
        "shipped_fused_gru": shipped_gru_geometry(),
    }


def write_plan(plan: Dict[str, Any], out_path: str) -> None:
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(plan, f, indent=1, sort_keys=True)
        f.write("\n")


def check_plan_file(path: str, costs_path: str) -> List[str]:
    """Regenerate the plan from the committed inputs and compare —
    a stale or hand-edited artifact fails here, the programs_list.txt
    discipline. Returns problems ([] = up to date)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            committed = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable: {e}"]
    if not isinstance(committed, dict):
        return [f"{path}: artifact is {type(committed).__name__}, not a "
                f"{PLAN_SCHEMA} object — regenerate"]
    try:
        fresh = build_plan(costs_path)
    except (OSError, ValueError) as e:
        return [f"{path}: cannot rebuild plan: {e}"]
    if committed != fresh:
        drift = []
        for key in sorted(set(committed) | set(fresh)):
            if committed.get(key) != fresh.get(key):
                drift.append(key)
        return [
            f"{path}: committed plan drifted from the regenerated one "
            f"(differing keys: {', '.join(drift)}) — regenerate: "
            f"python -m pvraft_tpu.analysis kernels --plan --out {path}"]
    return []
