"""kernelcheck driver: files -> kernel models -> GK rules -> diagnostics.

Mirrors ``concurrency/check.py`` deliberately: same ``Diagnostic`` type,
same ``# graftlint: disable=GKxxx -- reason`` suppression grammar (one
parser — what ``lint --stats`` counts is exactly what is honored here),
same stable ordering. Scope defaults to the Pallas kernel plane
(``ops/pallas/``), resolved relative to the installed package so
``python -m pvraft_tpu.analysis kernels`` works from any cwd.

A ``pallas_call`` site whose geometry cannot be statically modeled gets
a ``GK000`` finding (the GC000/GL000 discipline): a new kernel either
evaluates — literal dims, or one :data:`~.model.KERNEL_BINDINGS` row at
its certified geometry — or fails the gate; it can never silently skip
analysis.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pvraft_tpu.analysis.engine import (
    Diagnostic,
    _expand_decorated_regions,
    _suppressed,
    _suppressions,
    iter_py_files,
)
from pvraft_tpu.analysis.kernels.model import build_module_kernel_model
from pvraft_tpu.analysis.kernels.rules import (
    KernelContext,
    all_kernel_rules,
)


def default_scope() -> Tuple[str, ...]:
    """The gate's scan scope, as absolute paths of this checkout."""
    import pvraft_tpu

    pkg = os.path.dirname(os.path.abspath(pvraft_tpu.__file__))
    return (os.path.join(pkg, "ops", "pallas"),)


# Spelled as a constant for docs/tests; resolved lazily by the CLI.
DEFAULT_SCOPE = ("pvraft_tpu/ops/pallas",)

_IMPORT_RE = re.compile(
    r"(?:from|import)\s+(pvraft_tpu\.ops\.pallas\.\w+)")


def kernel_spec_imports() -> "Dict[str, List[str]]":
    """kernel-tag ProgramSpec name -> normalized path suffixes of every
    Pallas module its thunk source imports (order-preserving, deduped).
    THE one catalog inspection — GK005's coverage set and the planner's
    spec->module mapping both derive from it, so they cannot drift.
    Import-light: ``load_catalog`` registers specs without importing jax
    (thunks stay lazy), and the thunk *source* is inspected, never run."""
    import inspect

    from pvraft_tpu.programs import by_tag, load_catalog

    load_catalog()
    out: Dict[str, List[str]] = {}
    for spec in by_tag("kernel"):
        try:
            source = inspect.getsource(spec.thunk)
        except (OSError, TypeError):
            continue
        mods: List[str] = []
        for mod in _IMPORT_RE.findall(source):
            suffix = mod.replace(".", "/") + ".py"
            if suffix not in mods:
                mods.append(suffix)
        out[spec.name] = mods
    return out


def registered_kernel_modules() -> Set[str]:
    """Path suffixes of every Pallas module some ``kernel``-tagged
    ProgramSpec imports — the GK005 coverage set."""
    return {m for mods in kernel_spec_imports().values() for m in mods}


def check_source(source: str, path: str = "<string>",
                 rule_ids: Sequence[str] = (),
                 registered_modules: Optional[Set[str]] = None,
                 ) -> Tuple[List[Diagnostic], List[Diagnostic]]:
    """Run the GK rules over one source string (suppressions applied).

    Returns ``(findings, notes)`` — notes are advisory layout
    observations (GK001 whole-axis blocks) that never fail the gate.
    """
    source = source.lstrip("\ufeff")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return ([Diagnostic(path, e.lineno or 1, e.offset or 0, "GK000",
                            f"syntax error: {e.msg}")], [])
    model = build_module_kernel_model(tree, source, path)
    ctx = KernelContext(path, source, tree, model,
                        registered_modules=registered_modules)
    per_line, file_ids = _suppressions(source)
    _expand_decorated_regions(tree, per_line)
    out: List[Diagnostic] = []
    for km in model.kernels:
        for problem in km.problems:
            d = Diagnostic(
                path, km.line, km.col, "GK000",
                f"pallas_call in `{km.func}` cannot be statically "
                f"modeled: {problem} — use literal dims or add a "
                f"KERNEL_BINDINGS row at the kernel's certified geometry")
            if (not rule_ids or "GK000" in rule_ids) and \
                    not _suppressed(d, per_line, file_ids):
                out.append(d)
    for rule_cls in all_kernel_rules():
        if rule_ids and rule_cls.id not in rule_ids:
            continue
        for d in rule_cls().check(ctx):
            if not _suppressed(d, per_line, file_ids):
                out.append(d)
    notes = [d for d in ctx.notes
             if not _suppressed(d, per_line, file_ids)
             and (not rule_ids or d.rule_id in rule_ids)]
    out.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    notes.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return out, notes


def check_paths(paths: Sequence[str], rule_ids: Sequence[str] = (),
                registered_modules: Optional[Set[str]] = None,
                ) -> Tuple[List[Diagnostic], List[Diagnostic], int]:
    """Check files/directories. Returns (findings, notes, files_checked).

    ``registered_modules`` defaults to the live registry's kernel-tag
    coverage set, so the clean-tree gate always arms GK005."""
    if registered_modules is None:
        registered_modules = registered_kernel_modules()
    findings: List[Diagnostic] = []
    notes: List[Diagnostic] = []
    n = 0
    for f in iter_py_files(paths):
        n += 1
        with open(f, "r", encoding="utf-8-sig") as fh:
            d, w = check_source(fh.read(), path=f, rule_ids=rule_ids,
                                registered_modules=registered_modules)
        findings.extend(d)
        notes.extend(w)
    findings.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    notes.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return findings, notes, n
