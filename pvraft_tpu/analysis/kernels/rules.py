"""kernelcheck rules GK001-GK006 — the Pallas/Mosaic failure classes.

The repo has already eaten one silent Mosaic lowering regression (PR 5:
the fused-lookup kernel's integer-iota ``reduce_min`` argmin stopped
compiling under toolchain drift) and the fused-GRU campaign (ROADMAP
item 1) is about to multiply the amount of Pallas code. These rules make
BlockSpec geometry, tile alignment, VMEM residency and the known Mosaic
hazard patterns machine-checked surfaces, the way graftlint/deepcheck/
threadcheck already gate the other layers. Suppress with
``# graftlint: disable=GKxxx -- reason`` (shared pragma grammar;
reason-less suppressions fail ``lint --stats``).

Severity discipline (GK001): a *chosen* tile of a larger axis that
breaks the TPU layout (last dim % 128, second-minor % 8 fp32 / % 16
bf16) is an ERROR — pick a different tile. A block dim that simply IS
the whole operand axis (the 81-cell voxel output, a knn=32 column
block) cannot be re-tiled without changing semantics: those are emitted
as layout *notes* (``ctx.notes``) — printed, never failing the gate.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple, Type

from pvraft_tpu.analysis.engine import Diagnostic, LintContext, Rule
from pvraft_tpu.analysis.kernels.model import (
    ArrayInfo,
    BlockSpecModel,
    KernelModel,
    ModuleKernelModel,
    _dotted_tail,
)

# The on-chip vector memory a single core can feed a kernel from
# (v5e/v4 class: ~16 MiB usable per core; the Mosaic default
# vmem_limit_bytes is in the same band). One number, used by GK002 and
# the planner.
VMEM_BUDGET_BYTES = 16 * 1024 * 1024

# Minimal layout tiles per dtype: (sublane, lane). Lane is always 128.
SUBLANE_MULTIPLE = {"float32": 8, "int32": 8, "uint32": 8,
                    "bfloat16": 16, "float16": 16,
                    "int8": 32, "uint8": 32, "bool": 32}
LANE_MULTIPLE = 128


class KernelContext(LintContext):
    """LintContext + the extracted kernel models + a notes channel.

    ``registered_modules`` is the set of normalized path suffixes that
    some ``kernel``-tagged ProgramSpec covers (GK005); ``None`` means
    the caller did not supply registry context and GK005 stays silent.
    """

    def __init__(self, path: str, source: str, tree: ast.Module,
                 model: ModuleKernelModel,
                 registered_modules: Optional[Set[str]] = None):
        super().__init__(path, source, tree)
        self.model = model
        self.registered_modules = registered_modules
        self.notes: List[Diagnostic] = []

    def note(self, line: int, col: int, rule_id: str, message: str) -> None:
        d = Diagnostic(self.path, line, col, rule_id, message)
        if d not in self.notes:
            self.notes.append(d)


class KernelRule(Rule):
    """Base for GK rules: sees one file's :class:`KernelContext`."""

    def check(self, ctx: KernelContext) -> Iterable[Diagnostic]:
        raise NotImplementedError


_GK_REGISTRY: List[Type[KernelRule]] = []


def gk_register(cls: Type[KernelRule]) -> Type[KernelRule]:
    if not cls.id or not cls.title:
        raise ValueError(f"rule {cls.__name__} must set id and title")
    if any(r.id == cls.id for r in _GK_REGISTRY):
        raise ValueError(f"duplicate rule id {cls.id}")
    _GK_REGISTRY.append(cls)
    return cls


def all_kernel_rules() -> Tuple[Type[KernelRule], ...]:
    return tuple(sorted(_GK_REGISTRY, key=lambda r: r.id))


# --- GK001 ----------------------------------------------------------------

@gk_register
class TileMisalignment(KernelRule):
    """Block tile breaks the TPU (sublane, lane) layout.

    VMEM blocks are laid out in (sublane x 128-lane) tiles — (8, 128)
    for fp32, (16, 128) for bf16. A block whose last dim is not a
    multiple of 128 (or second-minor not a multiple of the dtype
    sublane) is padded per tile: wasted lanes, and historically the
    geometry most likely to hit Mosaic lowering edge cases. A *chosen*
    tile of a larger axis is an error (re-tile it); a block dim that
    equals the whole operand axis is geometry-inherent and only noted.
    """

    id = "GK001"
    title = "tile-misalignment"

    def check(self, ctx: KernelContext) -> Iterable[Diagnostic]:
        for km in ctx.model.kernels:
            for role, spec, op in km.io_pairs():
                if spec.block is None or len(spec.block) < 1:
                    continue
                yield from self._dim(ctx, km, role, spec, op,
                                     -1, LANE_MULTIPLE, "last (lane)")
                if len(spec.block) >= 2:
                    sub = SUBLANE_MULTIPLE.get(op.dtype, 8)
                    yield from self._dim(ctx, km, role, spec, op,
                                         -2, sub, "second-minor (sublane)")

    def _dim(self, ctx: KernelContext, km: KernelModel, role: str,
             spec: BlockSpecModel, op: ArrayInfo, axis: int,
             multiple: int, label: str) -> Iterable[Diagnostic]:
        block_d = spec.block[axis]
        if block_d % multiple == 0:
            return
        if block_d == 1:
            # A squeezed/batch-like dim (the leading `1` of a (1, T, K)
            # block, a row-per-step pattern): padded but deliberate and
            # universally supported — never a misalignment finding.
            return
        operand_d = op.shape[axis] if len(op.shape) >= abs(axis) else None
        msg = (f"{role} block {spec.block} {label} dim {block_d} is not a "
               f"multiple of {multiple} for {op.dtype}")
        if operand_d == block_d:
            # The block spans the whole axis: inherent to the operand
            # geometry, padded to one layout tile — note, don't fail.
            ctx.note(spec.line, spec.col, self.id,
                     msg + " (whole-axis block: geometry-inherent, padded "
                           "in VMEM — consider packing small feature axes "
                           "if this block dominates)")
            return
        tiled = (f" while tiling an axis of {operand_d}"
                 if operand_d is not None
                 else " (block rank exceeds the operand's)")
        yield Diagnostic(
            ctx.path, spec.line, spec.col, self.id,
            msg + tiled + " — the chosen tile forces per-block padding "
                  f"and relayout; pick a multiple of {multiple}")


# --- GK002 ----------------------------------------------------------------

@gk_register
class VmemBudget(KernelRule):
    """Static VMEM footprint exceeds the per-core budget.

    Every grid-streamed input/output block is double-buffered by the
    pipeline (next block loads behind compute), plus single-buffered
    scratch. If 2 x sum(block bytes) + scratch > ~16 MiB the kernel
    cannot stay resident and Mosaic either spills or refuses; this
    surfaces at lowering time on a real toolchain but silently at HEAD
    without one.
    """

    id = "GK002"
    title = "vmem-budget-exceeded"

    def check(self, ctx: KernelContext) -> Iterable[Diagnostic]:
        for km in ctx.model.kernels:
            est = km.vmem_estimate_bytes()
            if est is None:
                continue
            if est > VMEM_BUDGET_BYTES:
                yield Diagnostic(
                    ctx.path, km.line, km.col, self.id,
                    f"kernel `{km.kernel_fn_name or km.func}` needs "
                    f"~{est / 2**20:.1f} MiB of VMEM (double-buffered "
                    f"blocks + scratch) against the "
                    f"~{VMEM_BUDGET_BYTES / 2**20:.0f} MiB/core budget — "
                    f"shrink the block tile or split the kernel")


# --- GK003 ----------------------------------------------------------------

def _index_map_roles(spec: BlockSpecModel,
                     n_grid: int) -> Optional[List[Tuple[str, int]]]:
    """Per block dim: ("axis", grid_pos) | ("const", value) | ("expr", 0).
    None when the lambda shape itself is malformed for the grid."""
    lam = spec.index_map
    if lam is None:
        return None
    params = [a.arg for a in lam.args.args]
    if len(params) != n_grid:
        return None
    body = lam.body
    elts: Sequence[ast.AST]
    if isinstance(body, ast.Tuple):
        elts = body.elts
    else:
        elts = [body]
    roles: List[Tuple[str, int]] = []
    for e in elts:
        if isinstance(e, ast.Name) and e.id in params:
            roles.append(("axis", params.index(e.id)))
        elif isinstance(e, ast.Constant) and isinstance(e.value, int):
            roles.append(("const", e.value))
        else:
            roles.append(("expr", 0))
    return roles


@gk_register
class GridCoverageMismatch(KernelRule):
    """grid x block under- or over-covers an operand axis.

    For an identity-mapped dim, ``block[d] * grid[g]`` must equal the
    operand's axis: less leaves a remainder the kernel never touches
    (silently wrong output — there is no masked remainder in these
    kernels), more reads/writes out of bounds (padded reads, dropped
    writes — also silently wrong). For a constant-0 dim the block must
    span the whole axis.
    """

    id = "GK003"
    title = "grid-coverage-mismatch"

    def check(self, ctx: KernelContext) -> Iterable[Diagnostic]:
        for km in ctx.model.kernels:
            if km.grid is None:
                continue
            for role, spec, op in km.io_pairs():
                if spec.block is None:
                    continue
                roles = _index_map_roles(spec, len(km.grid))
                if roles is None or len(roles) != len(spec.block) \
                        or len(spec.block) != len(op.shape):
                    continue
                for d, (kind, val) in enumerate(roles):
                    block_d = spec.block[d]
                    if kind == "axis":
                        covered = block_d * km.grid[val]
                        if covered != op.shape[d]:
                            how = "under" if covered < op.shape[d] else "over"
                            yield Diagnostic(
                                ctx.path, spec.line, spec.col, self.id,
                                f"{role} dim {d}: block {block_d} x grid "
                                f"axis {val} ({km.grid[val]} steps) covers "
                                f"{covered} of the operand's {op.shape[d]} "
                                f"— {how}-coverage with no masked "
                                f"remainder; fix the grid/tile or mask "
                                f"the tail block")
                    elif kind == "const" and val == 0:
                        if block_d != op.shape[d]:
                            how = ("under" if block_d < op.shape[d]
                                   else "over")
                            yield Diagnostic(
                                ctx.path, spec.line, spec.col, self.id,
                                f"{role} dim {d}: constant-indexed block "
                                f"of {block_d} against an operand axis of "
                                f"{op.shape[d]} — {how}-coverage; a "
                                f"constant index map must span the axis")


# --- GK004 ----------------------------------------------------------------

_REDUCE_MINMAX = {"min", "max", "argmin", "argmax", "reduce_min",
                  "reduce_max"}
_INT_DTYPES = {"int8", "int16", "int32", "int64", "uint8", "uint16",
               "uint32", "uint64"}
_FLOAT_DTYPES = {"float32", "bfloat16", "float16"}


def _dtype_of_node(node: ast.AST) -> Optional[str]:
    tail = _dotted_tail(node)
    if tail in _INT_DTYPES or tail in _FLOAT_DTYPES or tail == "float64":
        return tail
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _float_cast_covered(expr: ast.AST) -> Set[int]:
    """ids of every node living under an ``.astype(<float dtype>)`` call
    — an integer iota inside one of these is sanctioned (the PR-5 fix),
    wherever the cast sits in a compound expression."""
    covered: Set[int] = set()
    for node in ast.walk(expr):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and node.args
                and _dtype_of_node(node.args[0]) in _FLOAT_DTYPES):
            for sub in ast.walk(node.func.value):
                covered.add(id(sub))
    return covered


def _uncast_int_iotas(expr: ast.AST) -> List[ast.Call]:
    """Integer-dtype iota calls in ``expr`` NOT covered by a float
    astype anywhere above them."""
    covered = _float_cast_covered(expr)
    out: List[ast.Call] = []
    for call in ast.walk(expr):
        if (isinstance(call, ast.Call)
                and _dotted_tail(call.func) in ("broadcasted_iota", "iota")
                and id(call) not in covered):
            dtype = _dtype_of_node(call.args[0]) if call.args else None
            if dtype is None or dtype in _INT_DTYPES:
                out.append(call)
    return out


def _int_iota_names(fn: ast.AST) -> Set[str]:
    """Locals assigned from an INTEGER iota that is not float-cast
    anywhere in the assignment expression (the PR-5 pre-fix shape).
    `x = broadcasted_iota(jnp.int32, ...)` is tracked;
    `x = broadcasted_iota(jnp.int32, ...).astype(jnp.float32)` — and
    any compound expression around that cast — is not; neither is a
    name whose cast is a separate reassignment
    (`x = x.astype(jnp.float32)`), the fix written as two statements."""
    names: Set[str] = set()
    recast: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        tainted = bool(_uncast_int_iotas(node.value))
        has_float_cast = bool(_float_cast_covered(node.value))
        for t in node.targets:
            if isinstance(t, ast.Name):
                if tainted:
                    names.add(t.id)
                elif has_float_cast:
                    recast.add(t.id)
    # Un-tainting is the safe direction: a missed finding here is still
    # caught by the deviceless Mosaic compile gate; a false finding
    # would force a pragma on the rule's own recommended fix.
    return names - recast


def _hazard_int_reduce(fn: ast.AST) -> Iterable[Tuple[int, int, str]]:
    """Integer-dtype min/max/arg-extremum reductions — the exact class
    that silently stopped lowering in PR 5."""
    int_names = _int_iota_names(fn)
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and _dotted_tail(node.func) in _REDUCE_MINMAX
                and node.args):
            continue
        arg = node.args[0]
        mentioned = {n.id for n in ast.walk(arg) if isinstance(n, ast.Name)}
        if mentioned & int_names:
            which = sorted(mentioned & int_names)[0]
            yield (node.lineno, node.col_offset,
                   f"`{_dotted_tail(node.func)}` reduction over integer "
                   f"iota `{which}` — Mosaic has no integer min/max "
                   f"reduction lowering (the PR-5 regression class); "
                   f"generate the iota as i32 and `.astype` it to f32 "
                   f"before reducing (f32 is exact to 2^24)")
            continue
        if _uncast_int_iotas(arg):
            yield (node.lineno, node.col_offset,
                   f"`{_dotted_tail(node.func)}` reduction over an "
                   f"inline integer iota — cast the iota to f32 "
                   f"first (PR-5 regression class)")


def _hazard_1d_iota(fn: ast.AST) -> Iterable[Tuple[int, int, str]]:
    """1D iota generation: Mosaic requires >= 2D iota on TPU."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        tail = _dotted_tail(node.func)
        if tail == "arange":
            yield (node.lineno, node.col_offset,
                   "`arange` in a kernel body produces a 1D iota — Mosaic "
                   "requires >= 2D; use `lax.broadcasted_iota` with an "
                   "explicit dimension")
        elif tail == "iota" and len(node.args) >= 2:
            # lax.iota(dtype, size) is always rank-1.
            yield (node.lineno, node.col_offset,
                   "`lax.iota` is rank-1 — Mosaic requires >= 2D iota; "
                   "use `lax.broadcasted_iota`")
        elif tail == "broadcasted_iota" and len(node.args) >= 2:
            shape = node.args[1]
            if isinstance(shape, ast.Tuple) and len(shape.elts) == 1:
                yield (node.lineno, node.col_offset,
                       "`broadcasted_iota` over a rank-1 shape — Mosaic "
                       "requires >= 2D iota; keep the block rank >= 2")


def _hazard_f64_cast(fn: ast.AST) -> Iterable[Tuple[int, int, str]]:
    """float64 anywhere in a kernel body: TPU has no f64 — the cast
    either fails to lower or silently truncates under x64 config."""
    for node in ast.walk(fn):
        if _dotted_tail(node) == "float64":
            yield (node.lineno, node.col_offset,
                   "float64 in a kernel body — TPU/Mosaic has no f64; "
                   "use float32 (exact for indices to 2^24)")


# Extensible pattern table: (hazard id, matcher over one kernel-body
# FunctionDef). New Mosaic hazards learned from toolchain drift get a
# row here plus a red/green fixture under tests/fixtures/kernelcheck/.
MOSAIC_HAZARDS: Tuple[Tuple[str, object], ...] = (
    ("int-minmax-reduce", _hazard_int_reduce),
    ("iota-1d", _hazard_1d_iota),
    ("float64-cast", _hazard_f64_cast),
)


@gk_register
class MosaicLoweringHazard(KernelRule):
    """Known Mosaic lowering hazard pattern in a kernel body.

    An extensible table (:data:`MOSAIC_HAZARDS`) of op shapes that have
    broken (or are documented unsupported) in the Mosaic TPU lowering:
    integer min/max reductions (the PR-5 silent regression), 1D iota,
    float64 casts. The deviceless compile gate catches these too — but
    only on hosts with a libtpu; this rule fails them everywhere,
    pattern-first, with the fix in the message.
    """

    id = "GK004"
    title = "mosaic-lowering-hazard"

    def check(self, ctx: KernelContext) -> Iterable[Diagnostic]:
        seen: Set[Tuple[int, int, str]] = set()
        bodies: Dict[str, ast.AST] = {}
        for km in ctx.model.kernels:
            if km.kernel_fn_node is not None:
                bodies.setdefault(km.kernel_fn_name, km.kernel_fn_node)
                # Same-module helpers called from the kernel body run
                # inside the kernel too (voxel_level_means).
                for node in ast.walk(km.kernel_fn_node):
                    if isinstance(node, ast.Call):
                        callee = _dotted_tail(node.func)
                        helper = ctx.model.functions.get(callee)
                        if helper is not None:
                            bodies.setdefault(callee, helper)
        for name, fn in sorted(bodies.items()):
            for hazard_id, matcher in MOSAIC_HAZARDS:
                for line, col, msg in matcher(fn):  # type: ignore[operator]
                    key = (line, col, hazard_id)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Diagnostic(
                        ctx.path, line, col, self.id,
                        f"[{hazard_id}] in kernel body `{name}`: {msg}")


# --- GK005 ----------------------------------------------------------------

@gk_register
class UnregisteredKernel(KernelRule):
    """``pallas_call`` entry point with no ``kernel``-tagged ProgramSpec.

    The deviceless Mosaic compile gate (``programs compile --tag
    kernel``) only certifies what the registry enumerates: a Pallas
    kernel module no ``kernel``-tagged spec imports is invisible to the
    gate — the exact shape under which the PR-5 regression rotted at
    HEAD. Register fwd (and VJP, if custom) specs in
    ``programs/catalog.py``.
    """

    id = "GK005"
    title = "unregistered-kernel"

    def check(self, ctx: KernelContext) -> Iterable[Diagnostic]:
        if ctx.registered_modules is None or not ctx.model.kernels:
            return
        norm = ctx.norm_path
        if any(norm.endswith(suffix) for suffix in ctx.registered_modules):
            return
        km = min(ctx.model.kernels, key=lambda k: (k.line, k.col))
        yield Diagnostic(
            ctx.path, km.line, km.col, self.id,
            "this module's pallas_call has no `kernel`-tagged ProgramSpec "
            "— the deviceless Mosaic compile gate cannot see it and "
            "toolchain drift will rot silently; register it in "
            "pvraft_tpu/programs/catalog.py")


# --- GK006 ----------------------------------------------------------------

@gk_register
class InterpretModeLeak(KernelRule):
    """``pallas_call`` without the ``interpret_mode()`` escape hatch.

    CPU tier-1 (and the host leg of the cost inventory) runs every
    kernel through the Pallas interpreter via
    ``interpret=interpret_mode()`` (``PVRAFT_PALLAS_INTERPRET``). A
    site that hardcodes ``interpret=False`` (or omits the kwarg) can
    never run in CI; ``interpret=True`` silently benchmarks the
    interpreter on TPU. Wire the shared helper.
    """

    id = "GK006"
    title = "interpret-mode-leak"

    def check(self, ctx: KernelContext) -> Iterable[Diagnostic]:
        for km in ctx.model.kernels:
            if km.interpret_resolved:
                continue  # `interp = interpret_mode()` local spelling
            node = km.interpret_node
            if node is not None and any(
                    isinstance(n, ast.Call)
                    and _dotted_tail(n.func) == "interpret_mode"
                    for n in ast.walk(node)):
                continue
            if node is None:
                detail = "has no `interpret=` keyword"
            elif isinstance(node, ast.Constant):
                detail = f"hardcodes `interpret={node.value!r}`"
            else:
                detail = "computes `interpret=` without interpret_mode()"
            yield Diagnostic(
                ctx.path, km.line, km.col, self.id,
                f"pallas_call {detail} — route it through "
                f"`pvraft_tpu.ops.pallas.interpret_mode()` so CPU tier-1 "
                f"interprets and TPU compiles (PVRAFT_PALLAS_INTERPRET "
                f"escape hatch)")
