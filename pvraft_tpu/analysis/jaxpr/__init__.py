"""Jaxpr-level semantic analysis (deepcheck): the GJ rule family.

Where graftlint (``pvraft_tpu.analysis.rules``) reads source text, this
subpackage reads the traced program — every registered audit entry is
traced to a ClosedJaxpr and walked for collective consistency, donation
efficacy, precision flow and retrace hazards. Entry point:

    python -m pvraft_tpu.analysis deepcheck
"""

from pvraft_tpu.analysis.jaxpr.deepcheck import (  # noqa: F401
    DeepcheckReport,
    EntryReport,
    format_report,
    run_deepcheck,
    summary_line,
)
from pvraft_tpu.analysis.jaxpr.rules import (  # noqa: F401
    EntryContext,
    JaxprRule,
    all_jaxpr_rules,
    normalize_jaxpr_str,
)
from pvraft_tpu.analysis.jaxpr.walk import (  # noqa: F401
    COLLECTIVE_PRIMITIVES,
    Site,
    collective_fingerprint,
    dtype_conversions,
    walk,
)
