"""ClosedJaxpr walking utilities shared by the GJ rule family.

The deepcheck rules (``pvraft_tpu.analysis.jaxpr.rules``) don't read
source text — they read the *traced program*. This module turns a
``ClosedJaxpr`` into a flat list of :class:`Site` records, one per
equation at every nesting depth, each annotated with everything the
rules need and the jaxpr itself doesn't say locally:

- ``bound_axes``: mesh axis names bound by the enclosing
  ``shard_map``/``pmap`` binders (collectives over anything else are
  broken SPMD programs — rule GJ001);
- ``live``: whether the equation's results transitively reach a live
  output (a dead collective is wasted inter-chip traffic — GJ002).
  Liveness is computed per sub-jaxpr with the outer equation's used
  outputs as the root set; ``scan`` carries run through a fixpoint so a
  value that only feeds the *next* iteration still counts as live;
- ``dead_final_carry``: set on a collective that produces a scan carry
  whose final value is discarded after the loop — every iteration's
  communication is needed except the last one, which is pure waste
  (the ring-parallel pattern GJ002 exists to catch);
- ``source``: the ``(file, line)`` that issued the primitive (via
  ``compat.eqn_user_frame``) so findings anchor to real code and the
  ``# graftlint: disable=...`` suppressions apply.

Only duck-typing against the jaxpr data structures (``.eqns``,
``.outvars``, ``params`` sub-jaxprs) — no private jax imports here, so
the walker keeps working when internal modules move.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

# Primitives that move bytes between devices. ``axis_index`` and friends
# are cheap metadata lookups, not traffic — deliberately excluded.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
})


def _is_jaxpr(x: Any) -> bool:
    return hasattr(x, "eqns") and hasattr(x, "outvars")


def _as_jaxpr(x: Any):
    """Unwrap ClosedJaxpr -> Jaxpr; pass a raw Jaxpr through."""
    if _is_jaxpr(x):
        return x
    inner = getattr(x, "jaxpr", None)
    return inner if _is_jaxpr(inner) else None


def _is_var(v: Any) -> bool:
    # Var/DropVar have .aval and no .val; Literal carries .val.
    return hasattr(v, "aval") and not hasattr(v, "val")


def _is_drop(v: Any) -> bool:
    return type(v).__name__ == "DropVar"


def collective_axes(eqn) -> Tuple[Any, ...]:
    """The axis names a collective equation communicates over.

    jax spells the parameter ``axes`` (psum/pmean/pmax/pmin) or
    ``axis_name`` (ppermute/all_gather/...), either a single name or a
    tuple; entries can be ints for positional (vmapped) axes."""
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(axes)


@dataclasses.dataclass
class Site:
    """One equation in the walked program, with its analysis context."""

    eqn: Any
    depth: int
    bound_axes: frozenset
    live: bool
    # Collective feeding a scan carry whose final value is discarded
    # after the loop (the "last ring hop" pattern).
    dead_final_carry: bool = False
    # Enclosing call-primitive names, outermost first (e.g.
    # ("pjit:train_step", "scan")) — for human-readable reports.
    path: Tuple[str, ...] = ()

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name

    def source(self) -> Optional[Tuple[str, int]]:
        from pvraft_tpu.compat import eqn_user_frame

        si = getattr(self.eqn, "source_info", None)
        return eqn_user_frame(si) if si is not None else None


def _sub_jaxprs(params: Dict[str, Any]) -> Iterator[Any]:
    """Every Jaxpr found in an equation's params (generic fallback for
    call-like primitives the walker doesn't special-case)."""
    for v in params.values():
        if isinstance(v, (tuple, list)):
            for item in v:
                j = _as_jaxpr(item)
                if j is not None:
                    yield j
        else:
            j = _as_jaxpr(v)
            if j is not None:
                yield j


def _real_effects(eqn) -> bool:
    """True effects only: jax tags every collective with a
    ``NamedAxisEffect`` (axis bookkeeping, not IO/ordering), which must
    not shield a dead collective from liveness analysis."""
    return any(
        type(e).__name__ != "NamedAxisEffect"
        for e in (getattr(eqn, "effects", None) or ())
    )


def _liveness(
    jaxpr, live_out: Sequence[bool]
) -> Tuple[List[bool], List[bool], set]:
    """Backward pass: per-eqn liveness, per-invar liveness, and the set
    of live variables (for per-OUTPUT liveness of call-like equations —
    a jit call can be live through one output while another output, and
    the collective feeding it, is dead).

    An equation is live when any of its (non-drop) outputs transitively
    reaches a live jaxpr output, or when it carries real effects (an
    effectful equation must run regardless of dataflow)."""
    live_vars = set()
    for v, lv in zip(jaxpr.outvars, live_out):
        if lv and _is_var(v) and not _is_drop(v):
            live_vars.add(v)
    eqn_live_rev: List[bool] = []
    for eqn in reversed(jaxpr.eqns):
        live = _real_effects(eqn) or any(
            (not _is_drop(o)) and o in live_vars for o in eqn.outvars
        )
        eqn_live_rev.append(live)
        if live:
            for iv in eqn.invars:
                if _is_var(iv):
                    live_vars.add(iv)
    invar_live = [v in live_vars for v in jaxpr.invars]
    return list(reversed(eqn_live_rev)), invar_live, live_vars


def _producer(jaxpr, var) -> Optional[Any]:
    for eqn in jaxpr.eqns:
        if any(o is var for o in eqn.outvars):
            return eqn
    return None


def walk(closed) -> List[Site]:
    """Flatten a ClosedJaxpr into analysis Sites, all depths included."""
    sites: List[Site] = []
    top = _as_jaxpr(closed)
    _walk(top, [True] * len(top.outvars), frozenset(), 0, (), sites)
    return sites


def _eqn_label(eqn) -> str:
    name = eqn.primitive.name
    tag = eqn.params.get("name")
    return f"{name}:{tag}" if isinstance(tag, str) else name


def _walk(jaxpr, live_out, bound, depth, path, sites: List[Site]) -> None:
    eqn_live, _, live_vars = _liveness(jaxpr, live_out)

    def out_live(eqn, live):
        # Per-OUTPUT liveness: an output is live iff something actually
        # consumes it — not merely because a sibling output does.
        return [
            live and not _is_drop(o) and o in live_vars
            for o in eqn.outvars
        ]

    for eqn, live in zip(jaxpr.eqns, eqn_live):
        site = Site(eqn=eqn, depth=depth, bound_axes=bound, live=live,
                    path=path)
        sites.append(site)
        name = eqn.primitive.name
        inner_bound = bound
        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            auto = frozenset(eqn.params.get("auto") or ())
            names = frozenset(getattr(mesh, "axis_names", ()) or ())
            inner_bound = bound | (names - auto)
        elif name in ("xla_pmap", "pmap"):
            axis = eqn.params.get("axis_name")
            if axis is not None:
                inner_bound = bound | {axis}
        sub_path = path + (_eqn_label(eqn),)

        if name in ("pjit", "shard_map", "closed_call", "core_call",
                    "remat", "checkpoint", "custom_vjp_call_jaxpr"):
            # Outputs map 1:1 onto the inner jaxpr's outputs.
            key = "fun_jaxpr" if name == "custom_vjp_call_jaxpr" else "jaxpr"
            inner = _as_jaxpr(eqn.params.get(key))
            if inner is not None:
                lo = out_live(eqn, live)
                # remat/custom_vjp inner jaxprs may carry extra residual
                # outputs beyond the eqn's outvars; pad as live.
                lo += [live] * (len(inner.outvars) - len(lo))
                _walk(inner, lo[: len(inner.outvars)], inner_bound,
                      depth + 1, sub_path, sites)
                continue
        if name == "scan":
            _walk_scan(eqn, out_live(eqn, live), inner_bound, depth + 1,
                       sub_path, sites)
            continue
        if name == "cond":
            branches = eqn.params.get("branches", ())
            for br in branches:
                inner = _as_jaxpr(br)
                if inner is not None:
                    lo = out_live(eqn, live)
                    _walk(inner, lo[: len(inner.outvars)], inner_bound,
                          depth + 1, sub_path, sites)
            continue
        # Generic fallback (while, custom_jvp, pallas_call grids, ...):
        # conservative — treat every inner output as live so nothing is
        # falsely reported dead.
        for inner in _sub_jaxprs(eqn.params):
            _walk(inner, [live] * len(inner.outvars), inner_bound,
                  depth + 1, sub_path, sites)


def _walk_scan(eqn, outer_live: List[bool], bound, depth, path,
               sites: List[Site]) -> None:
    body = _as_jaxpr(eqn.params["jaxpr"])
    if body is None:  # defensive: unknown scan encoding
        return
    num_carry = eqn.params.get("num_carry", 0)
    num_consts = eqn.params.get("num_consts", 0)
    carry_live = list(outer_live[:num_carry])
    ys_live = list(outer_live[num_carry:])
    # Fixpoint: a carry whose final value is dropped is still live if it
    # feeds, through the body, a carry/output that IS live — it matters
    # to later iterations.
    for _ in range(num_carry + 1):
        _, invar_live, _ = _liveness(body, carry_live + ys_live)
        new_carry = [
            cl or invar_live[num_consts + i]
            for i, cl in enumerate(carry_live)
        ]
        if new_carry == carry_live:
            break
        carry_live = new_carry
    before = len(sites)
    _walk(body, carry_live + ys_live, bound, depth, path, sites)
    body_sites = sites[before:]
    # The "last ring hop" pattern: a collective producing a carry whose
    # final value is discarded. Every iteration's send is needed to feed
    # the next fold — except the last one, whose result nobody reads.
    for j in range(num_carry):
        if outer_live[j]:
            continue
        out_v = body.outvars[j]
        if not _is_var(out_v) or _is_drop(out_v):
            continue
        prod = _producer(body, out_v)
        if prod is not None and prod.primitive.name in COLLECTIVE_PRIMITIVES:
            for s in body_sites:
                if s.eqn is prod:
                    s.dead_final_carry = True
                    break


# --- derived views --------------------------------------------------------

def collective_fingerprint(sites: Sequence[Site]) -> Tuple[Tuple, ...]:
    """Deterministic summary of the program's communication schedule:
    ordered (primitive, axes, operand shape, operand dtype) tuples. Two
    step variants with equal fingerprints issue identical collective
    sequences — the SPMD-compatibility contract GJ003 checks."""
    out = []
    for s in sites:
        if s.primitive not in COLLECTIVE_PRIMITIVES:
            continue
        axes = tuple(str(a) for a in collective_axes(s.eqn))
        opnd = next((v for v in s.eqn.invars if _is_var(v)), None)
        aval = getattr(opnd, "aval", None)
        shape = tuple(getattr(aval, "shape", ()))
        dtype = str(getattr(aval, "dtype", "?"))
        out.append((s.primitive, axes, shape, dtype))
    return tuple(out)


def dtype_conversions(sites: Sequence[Site]) -> Dict[Tuple[str, str], int]:
    """Count of convert_element_type edges, keyed (src, dst) dtype names
    — the program's precision-flow map (promotions and truncations)."""
    out: Dict[Tuple[str, str], int] = {}
    for s in sites:
        if s.primitive != "convert_element_type":
            continue
        src = next((v for v in s.eqn.invars if _is_var(v)), None)
        src_dt = str(getattr(getattr(src, "aval", None), "dtype", "?"))
        dst_dt = str(s.eqn.params.get("new_dtype", "?"))
        key = (src_dt, dst_dt)
        out[key] = out.get(key, 0) + 1
    return out


LOW_PRECISION = frozenset({"bfloat16", "float16"})


def low_precision_sites(sites: Sequence[Site]) -> List[Site]:
    """Sites whose outputs carry a 16-bit float dtype."""
    out = []
    for s in sites:
        for o in s.eqn.outvars:
            dt = str(getattr(getattr(o, "aval", None), "dtype", ""))
            if dt in LOW_PRECISION:
                out.append(s)
                break
    return out
