"""deepcheck rules GJ001+ — semantic checks on the *traced* program.

graftlint (GL001-GL009) reads source text; these rules read the
ClosedJaxpr that jax actually hands to XLA, so they see through
factories, closures, ``shard_map`` bodies and ``scan`` loops. Each
rule's class docstring is its user-facing documentation (printed by
``python -m pvraft_tpu.analysis deepcheck --list-rules``). Findings
anchor to the source line that issued the offending primitive when jax
can name one, so the ordinary ``# graftlint: disable=GJxxx -- reason``
suppressions apply at that line; entry-level findings anchor to the
audit-entry registration site in ``analysis/audit.py``.

The corpus is the trace-compat audit registry: every public op and step
variant already registers a ``(fn, args)`` thunk there, which is exactly
the whole-program surface deepcheck needs.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Type

from pvraft_tpu.analysis.engine import Diagnostic
from pvraft_tpu.analysis.jaxpr.walk import (
    COLLECTIVE_PRIMITIVES,
    LOW_PRECISION,
    Site,
    collective_axes,
    collective_fingerprint,
    dtype_conversions,
    low_precision_sites,
)

_HEX = re.compile(r"0x[0-9a-f]+")


def normalize_jaxpr_str(s: str) -> str:
    """Jaxpr strings embed object addresses (custom_jvp thunk reprs);
    normalize them so two traces of the same program compare equal."""
    return _HEX.sub("0x0", s)


@dataclasses.dataclass
class EntryContext:
    """Everything the GJ rules can ask about one audit entry."""

    name: str
    precision: str                 # GJ006 intent: "f32" | "bf16_grads" | "any"
    spmd_group: Optional[str]      # GJ003 fingerprint group, or None
    anchor_path: str               # suppression anchor: registration site
    anchor_line: int
    fn: Callable
    args: tuple
    closed: Any                    # ClosedJaxpr of fn(*args)
    sites: List[Site]
    thunk: Optional[Callable]      # rebuilds (fn, args) — GJ007 retrace probe

    def diag(self, rule_id: str, message: str,
             site: Optional[Site] = None) -> Diagnostic:
        path, line = self.anchor_path, self.anchor_line
        if site is not None:
            src = site.source()
            if src is not None:
                path, line = src
        return Diagnostic(path=path, line=line, col=0, rule_id=rule_id,
                          message=f"{message} [entry: {self.name}]")


class JaxprRule:
    """Base class: subclasses set ``id``/``title`` and implement
    ``check`` (per entry). Rules needing the whole corpus at once
    (fingerprint comparison) also implement ``check_corpus``."""

    id: str = ""
    title: str = ""

    def check(self, ectx: EntryContext) -> Iterable[Diagnostic]:
        return ()

    @classmethod
    def check_corpus(
        cls, ectxs: List[EntryContext]
    ) -> Iterable[Diagnostic]:
        return ()


_REGISTRY: List[Type[JaxprRule]] = []


def register(cls: Type[JaxprRule]) -> Type[JaxprRule]:
    if not cls.id or not cls.title:
        raise ValueError(f"rule {cls.__name__} must set id and title")
    if any(r.id == cls.id for r in _REGISTRY):
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY.append(cls)
    return cls


def all_jaxpr_rules() -> Tuple[Type[JaxprRule], ...]:
    return tuple(sorted(_REGISTRY, key=lambda r: r.id))


def _fmt_aval(aval) -> str:
    dt = str(getattr(aval, "dtype", "?"))
    shape = ",".join(str(d) for d in getattr(aval, "shape", ()))
    return f"{dt}[{shape}]"


# --- GJ001 ----------------------------------------------------------------

@register
class UnboundCollectiveAxis(JaxprRule):
    """Collective over an axis name no enclosing binder provides.

    A ``psum``/``ppermute``/``all_gather`` axis must be bound by an
    enclosing ``shard_map`` (over a mesh axis it maps manually) or
    ``pmap``. An unbound axis traces only under an ambient ``axis_env``
    and fails the moment the function is jitted standalone — the
    classic "works in the test harness, dies on the TPU pod" hazard.
    """

    id = "GJ001"
    title = "unbound-collective-axis"

    def check(self, ectx: EntryContext) -> Iterable[Diagnostic]:
        for site in ectx.sites:
            if site.primitive not in COLLECTIVE_PRIMITIVES:
                continue
            unbound = [
                a for a in collective_axes(site.eqn)
                if isinstance(a, str) and a not in site.bound_axes
            ]
            if unbound:
                yield ectx.diag(
                    self.id,
                    f"`{site.primitive}` over axis "
                    f"{'/'.join(map(repr, unbound))} with no enclosing "
                    "shard_map/pmap binding it; the program cannot be "
                    "jitted standalone",
                    site,
                )


# --- GJ002 ----------------------------------------------------------------

@register
class DeadCollective(JaxprRule):
    """Collective whose result is never consumed — wasted inter-chip
    traffic.

    Two shapes: (a) the result reaches no live output at all (pure dead
    code that XLA may or may not strip, but the intent bug is real
    either way); (b) the result only feeds a loop carry whose final
    value is discarded after the loop — every iteration's send matters
    except the last, so the ring issues one full hop of ICI traffic
    nobody reads. Peel the final fold out of the loop.
    """

    id = "GJ002"
    title = "dead-collective"

    def check(self, ectx: EntryContext) -> Iterable[Diagnostic]:
        for site in ectx.sites:
            if site.primitive not in COLLECTIVE_PRIMITIVES:
                continue
            if not site.live:
                yield ectx.diag(
                    self.id,
                    f"dead `{site.primitive}` (result unused): the "
                    "collective moves bytes across the "
                    f"{'/'.join(map(str, collective_axes(site.eqn)))} "
                    "axis for nothing",
                    site,
                )
            elif site.dead_final_carry:
                yield ectx.diag(
                    self.id,
                    f"`{site.primitive}` feeds a loop carry whose final "
                    "value is discarded: the last iteration's hop is "
                    "wasted comm; peel the final fold out of the loop",
                    site,
                )


# --- GJ003 ----------------------------------------------------------------

@register
class CollectiveFingerprintDrift(JaxprRule):
    """Step variants in one SPMD group issue different collective
    sequences.

    Variants of the same step (default / optimized-backward / telemetry)
    must stay SPMD-compatible: under multi-process execution every
    process must issue the SAME ordered collective sequence or the mesh
    deadlocks. Entries registered with a shared ``spmd_group`` in
    ``analysis/audit.py`` are fingerprinted (ordered primitive, axes,
    shape, dtype) and compared; a variant that grows a collective the
    others lack fails here before it hangs a pod.
    """

    id = "GJ003"
    title = "collective-fingerprint-drift"

    @classmethod
    def check_corpus(
        cls, ectxs: List[EntryContext]
    ) -> Iterable[Diagnostic]:
        groups: Dict[str, List[EntryContext]] = {}
        for e in ectxs:
            if e.spmd_group:
                groups.setdefault(e.spmd_group, []).append(e)
        for gname in sorted(groups):
            members = groups[gname]
            if len(members) < 2:
                continue
            prints = {e.name: collective_fingerprint(e.sites)
                      for e in members}
            ref = members[0]
            ref_fp = prints[ref.name]
            for e in members[1:]:
                if prints[e.name] != ref_fp:
                    yield e.diag(
                        cls.id,
                        f"collective fingerprint differs from "
                        f"`{ref.name}` within spmd_group "
                        f"'{gname}': {prints[e.name]!r} vs {ref_fp!r}; "
                        "SPMD-incompatible variants deadlock a "
                        "multi-process mesh",
                    )


# --- GJ004 ----------------------------------------------------------------

@register
class UnaliasableDonation(JaxprRule):
    """Donated buffer XLA cannot alias to any output — a silent copy.

    ``donate_argnums`` only saves memory when the donated input's
    (shape, dtype) matches an output buffer XLA can reuse. A donated
    buffer with no matching output is quietly copied instead: the
    params/opt_state still exist twice in HBM at peak, exactly the 2x
    the donation was supposed to remove, with nothing but a lowering
    warning nobody reads.
    """

    id = "GJ004"
    title = "unaliasable-donation"

    def check(self, ectx: EntryContext) -> Iterable[Diagnostic]:
        for site in ectx.sites:
            yield from self._check_pjit(ectx, site)

    def _check_pjit(self, ectx, site) -> Iterable[Diagnostic]:
        eqn = site.eqn
        if site.primitive != "pjit":
            return
        donated = eqn.params.get("donated_invars") or ()
        if not any(donated):
            return
        outs = [_fmt_aval(o.aval) for o in eqn.outvars]
        remaining: Dict[str, int] = {}
        for o in outs:
            remaining[o] = remaining.get(o, 0) + 1
        unmatched: List[Tuple[int, str]] = []
        for i, (iv, d) in enumerate(zip(eqn.invars, donated)):
            if not d:
                continue
            key = _fmt_aval(iv.aval)
            if remaining.get(key, 0) > 0:
                remaining[key] -= 1
            else:
                unmatched.append((i, key))
        name = eqn.params.get("name", "<jit>")
        for i, key in unmatched:
            yield ectx.diag(
                self.id,
                f"donated arg {i} of jitted `{name}` ({key}) matches no "
                "output buffer; XLA copies it silently — the donated "
                "state still costs 2x HBM at peak",
                site,
            )


# --- GJ005 ----------------------------------------------------------------

@register
class UndonatedStateBuffer(JaxprRule):
    """Donation-opted-in step leaves a donatable input buffer undonated.

    In a jitted program that already donates state (the author marked it
    consume-on-call), an UNdonated input whose (shape, dtype) matches an
    output buffer that no donated input claims is a missed alias: XLA
    must allocate the output fresh while the input sits dead — peak HBM
    one full buffer higher than necessary. Donate it or document why the
    caller still needs it.
    """

    id = "GJ005"
    title = "undonated-state-buffer"

    def check(self, ectx: EntryContext) -> Iterable[Diagnostic]:
        for site in ectx.sites:
            yield from self._check_pjit(ectx, site)

    def _check_pjit(self, ectx, site) -> Iterable[Diagnostic]:
        eqn = site.eqn
        if site.primitive != "pjit":
            return
        donated = eqn.params.get("donated_invars") or ()
        if not any(donated):
            # No donation opt-in: eval-style programs legitimately keep
            # every input alive (params are reused across calls).
            return
        remaining: Dict[str, int] = {}
        for o in eqn.outvars:
            key = _fmt_aval(o.aval)
            remaining[key] = remaining.get(key, 0) + 1
        for iv, d in zip(eqn.invars, donated):
            if d:
                key = _fmt_aval(iv.aval)
                if remaining.get(key, 0) > 0:
                    remaining[key] -= 1
        name = eqn.params.get("name", "<jit>")
        for i, (iv, d) in enumerate(zip(eqn.invars, donated)):
            if d:
                continue
            key = _fmt_aval(iv.aval)
            if remaining.get(key, 0) > 0:
                remaining[key] -= 1
                yield ectx.diag(
                    self.id,
                    f"undonated arg {i} of jitted `{name}` ({key}) "
                    "matches an unclaimed output buffer; donating it "
                    "would let XLA alias instead of allocating fresh",
                    site,
                )


# --- GJ006 ----------------------------------------------------------------

@register
class PrecisionDrift(JaxprRule):
    """Traced precision disagrees with the entry's declared intent.

    Every audit entry declares its precision intent (default ``f32``;
    the bf16-gradient step declares ``bf16_grads``). The rule walks the
    dtype flow of the whole traced program: an ``f32`` program must
    contain no 16-bit float values anywhere (a stray cast deep in a
    factory silently truncates gradients — the drift class the Gemma
    TPU report blames for most regressions); a ``bf16_grads`` program
    must actually contain the f32->bf16 truncation it advertises (an
    inert lever is measurement fraud in every A/B that cites it) and
    must not leak bf16 out of the step.
    """

    id = "GJ006"
    title = "precision-drift"

    def check(self, ectx: EntryContext) -> Iterable[Diagnostic]:
        if ectx.precision == "any":
            return
        conv = dtype_conversions(ectx.sites)
        if ectx.precision == "f32":
            lp = low_precision_sites(ectx.sites)
            if lp:
                conv_map = {
                    f"{a}->{b}": n for (a, b), n in sorted(conv.items())
                    if a in LOW_PRECISION or b in LOW_PRECISION
                }
                yield ectx.diag(
                    self.id,
                    f"{len(lp)} equation(s) carry 16-bit float values in "
                    "a float32-intent program (conversions: "
                    f"{conv_map}); declare the intent in the audit "
                    "entry or remove the cast",
                    lp[0],
                )
            return
        if ectx.precision == "bf16_grads":
            down = conv.get(("float32", "bfloat16"), 0)
            if down == 0:
                yield ectx.diag(
                    self.id,
                    "entry declares bf16_grads but the trace contains "
                    "no float32->bfloat16 truncation: the grad_dtype "
                    "lever is inert in this configuration",
                )
            leaks = [
                _fmt_aval(a) for a in getattr(ectx.closed, "out_avals", ())
                if str(getattr(a, "dtype", "")) in LOW_PRECISION
            ]
            if leaks:
                yield ectx.diag(
                    self.id,
                    f"bf16 leaks out of the step ({', '.join(leaks)}): "
                    "grads must be restored to float32 before the "
                    "optimizer state update",
                )
            return
        yield ectx.diag(
            self.id,
            f"unknown precision intent {ectx.precision!r} on the audit "
            "entry (expected 'f32', 'bf16_grads' or 'any')",
        )


# --- GJ007 ----------------------------------------------------------------

@register
class RetraceHazard(JaxprRule):
    """Program structure changes between equivalent traces — silent
    recompiles in production.

    Two probes. (a) Determinism: rebuilding the entry and retracing must
    reproduce the jaxpr byte-for-byte (addresses normalized); a trace
    that embeds fresh state (counters, dict order, ``id()``-derived
    names) misses the jit cache on every call and recompiles a
    multi-second XLA program per step. (b) Weak types: scalar inputs
    retraced as Python scalars (weak-typed, what callers actually pass)
    must yield the same output dtypes; if they differ, the same call
    site silently computes in two precisions depending on who called
    first — the recompilation class the source linter cannot see.
    """

    id = "GJ007"
    title = "retrace-hazard"

    def check(self, ectx: EntryContext) -> Iterable[Diagnostic]:
        import jax

        if ectx.thunk is None:
            return
        # (a) trace determinism: rebuild from scratch, compare jaxprs.
        try:
            fn2, args2 = ectx.thunk()
            second = jax.make_jaxpr(fn2)(*args2)
        except Exception as e:  # noqa: BLE001 - report, don't crash
            yield ectx.diag(
                self.id,
                f"entry could not be re-traced for the determinism "
                f"probe: {type(e).__name__}: {e}",
            )
            return
        first_s = normalize_jaxpr_str(str(ectx.closed))
        second_s = normalize_jaxpr_str(str(second))
        if first_s != second_s:
            # The first trace ran at corpus-build time; other entries
            # traced since can evict jax's bounded tracing caches, and
            # the pretty-printer dedups shared sub-jaxprs by object
            # identity — a cache-evicted `jnp.where` prints inline
            # instead of as a `_whereNN` table entry, differing as text
            # while the program is structurally unchanged. Confirm with
            # a third rebuild traced back-to-back with the second:
            # genuine per-build state (counters, dict order) differs on
            # EVERY rebuild; the printer-sharing artifact does not.
            try:
                fn3, args3 = ectx.thunk()
                third = jax.make_jaxpr(fn3)(*args3)
            except Exception as e:  # noqa: BLE001 - report, don't crash
                yield ectx.diag(
                    self.id,
                    f"entry could not be re-traced for the determinism "
                    f"probe: {type(e).__name__}: {e}",
                )
                return
            if second_s != normalize_jaxpr_str(str(third)):
                yield ectx.diag(
                    self.id,
                    "re-tracing the rebuilt entry produced a different "
                    "jaxpr: the trace embeds per-build state, so every "
                    "jit call misses the cache and recompiles",
                )
        # (b) weak-type probe on 0-d inputs.
        yield from self._weak_probe(ectx)

    def _weak_probe(self, ectx: EntryContext) -> Iterable[Diagnostic]:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(ectx.args)
        scalar_idx = [
            i for i, leaf in enumerate(leaves)
            if isinstance(leaf, jax.ShapeDtypeStruct) and leaf.shape == ()
            and leaf.dtype.kind in "fi"
        ]
        if not scalar_idx:
            return
        weak = list(leaves)
        for i in scalar_idx:
            weak[i] = 1.0 if leaves[i].dtype.kind == "f" else 1
        weak_args = jax.tree_util.tree_unflatten(treedef, weak)
        try:
            strong_out = jax.eval_shape(ectx.fn, *ectx.args)
            weak_out = jax.eval_shape(ectx.fn, *weak_args)
        except Exception as e:  # noqa: BLE001 - report, don't crash
            yield ectx.diag(
                self.id,
                f"weak-type probe failed to trace: "
                f"{type(e).__name__}: {e}",
            )
            return
        s_dts = [str(x.dtype) for x in jax.tree_util.tree_leaves(strong_out)]
        w_dts = [str(x.dtype) for x in jax.tree_util.tree_leaves(weak_out)]
        if s_dts != w_dts:
            yield ectx.diag(
                self.id,
                "retracing with Python scalars in place of 0-d arrays "
                f"changes output dtypes ({s_dts} -> {w_dts}): callers "
                "passing plain scalars get a silently different (and "
                "separately compiled) program",
            )
