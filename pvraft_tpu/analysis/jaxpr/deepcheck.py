"""deepcheck driver: trace the audit corpus, run the GJ rules, report.

``python -m pvraft_tpu.analysis deepcheck`` — the jaxpr-level sibling of
``lint`` (AST rules) and ``trace`` (eval_shape audit). Every entry in
the trace-compat audit registry (``pvraft_tpu/analysis/audit.py``) is
traced to a ClosedJaxpr with ``jax.make_jaxpr`` and walked by the GJ001+
rule family: collective consistency, donation efficacy, precision flow,
retrace hazards. Zero FLOPs — tracing only, CPU-safe.

Findings are ordinary :class:`Diagnostic`\\ s anchored at the source line
that issued the primitive (or the audit-entry registration site), so the
standard ``# graftlint: disable=GJxxx -- reason`` suppressions apply and
``lint --stats`` accounts for the debt.
"""

from __future__ import annotations

import dataclasses
import os
import traceback
from typing import Dict, List, Sequence, Tuple

from pvraft_tpu.analysis.engine import Diagnostic, filter_file_suppressions
from pvraft_tpu.analysis.jaxpr.rules import (
    EntryContext,
    all_jaxpr_rules,
)
from pvraft_tpu.analysis.jaxpr.walk import (
    COLLECTIVE_PRIMITIVES,
    collective_fingerprint,
    dtype_conversions,
    walk,
)


@dataclasses.dataclass
class EntryReport:
    """Per-entry trace outcome and program statistics."""

    name: str
    ok: bool
    detail: str = ""        # error summary when not ok
    n_eqns: int = 0         # walked equations, all depths
    n_collectives: int = 0
    fingerprint: Tuple = ()
    conversions: Dict[Tuple[str, str], int] = dataclasses.field(
        default_factory=dict)


@dataclasses.dataclass
class DeepcheckReport:
    diagnostics: List[Diagnostic]
    suppressed: int
    entries: List[EntryReport]

    @property
    def failures(self) -> List[EntryReport]:
        return [e for e in self.entries if not e.ok]

    @property
    def ok(self) -> bool:
        return not self.diagnostics and not self.failures


def _relpath(path: str) -> str:
    """Repo-root-relative display path — stable across checkouts and
    invocation directories, which is what the golden report pins."""
    import pvraft_tpu

    root = os.path.dirname(os.path.dirname(os.path.abspath(
        pvraft_tpu.__file__)))
    try:
        rel = os.path.relpath(path, root)
    except ValueError:  # different drive (windows)
        return path
    return path if rel.startswith("..") else rel.replace(os.sep, "/")


def run_deepcheck(
    entries=None,
    select_rules: Sequence[str] = (),
    entry_filter: Sequence[str] = (),
    retrace: bool = True,
) -> DeepcheckReport:
    """Trace every audit entry and run the GJ rules over the programs.

    ``entries``: ``{name: AuditEntry}`` corpus (defaults to the full
    audit registry). ``select_rules`` restricts to the named rule ids;
    ``entry_filter`` keeps entries whose name contains any given
    substring. ``retrace=False`` skips GJ007's rebuild probe (used by
    tests that check structural rules in isolation). Never raises on a
    broken entry: trace failures become ``EntryReport(ok=False)`` so one
    bad op can't hide the rest — and fail the gate themselves.
    """
    import jax

    if entries is None:
        from pvraft_tpu.analysis.audit import entries as audit_entries

        entries = audit_entries()

    reports: List[EntryReport] = []
    ectxs: List[EntryContext] = []
    for name in sorted(entries):
        if entry_filter and not any(s in name for s in entry_filter):
            continue
        meta = entries[name]
        try:
            fn, args = meta.thunk()
            closed = jax.make_jaxpr(fn)(*args)
            sites = walk(closed)
        except Exception as e:  # noqa: BLE001 - report, don't crash
            last = traceback.format_exception_only(type(e), e)[-1].strip()
            reports.append(EntryReport(name, ok=False, detail=last[:500]))
            continue
        ectxs.append(EntryContext(
            name=name,
            precision=getattr(meta, "precision", "f32"),
            spmd_group=getattr(meta, "spmd_group", None),
            anchor_path=getattr(meta, "path", "") or "<registry>",
            anchor_line=getattr(meta, "line", 0) or 1,
            fn=fn,
            args=args,
            closed=closed,
            sites=sites,
            thunk=meta.thunk if retrace else None,
        ))
        reports.append(EntryReport(
            name, ok=True,
            n_eqns=len(sites),
            n_collectives=sum(
                1 for s in sites if s.primitive in COLLECTIVE_PRIMITIVES
            ),
            fingerprint=collective_fingerprint(sites),
            conversions=dtype_conversions(sites),
        ))

    diags: List[Diagnostic] = []
    for rule_cls in all_jaxpr_rules():
        if select_rules and rule_cls.id not in select_rules:
            continue
        rule = rule_cls()
        for ectx in ectxs:
            diags.extend(rule.check(ectx))
        diags.extend(rule_cls.check_corpus(ectxs))

    kept, suppressed = filter_file_suppressions(diags)
    kept = [dataclasses.replace(d, path=_relpath(d.path)) for d in kept]
    kept.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id, d.message))
    return DeepcheckReport(diagnostics=kept, suppressed=suppressed,
                           entries=reports)


def format_report(report: DeepcheckReport, verbose: bool = False) -> str:
    """Findings (and, verbose, per-entry program stats) as stable text —
    the shape the golden fixture pins down."""
    lines: List[str] = []
    for e in report.entries:
        if not e.ok:
            lines.append(f"[FAIL] {e.name}: {e.detail}")
        elif verbose:
            conv = ", ".join(
                f"{a}->{b} x{n}" for (a, b), n in sorted(e.conversions.items())
            ) or "none"
            lines.append(
                f"[ok] {e.name}: eqns={e.n_eqns} "
                f"collectives={e.n_collectives} converts: {conv}"
            )
    for d in report.diagnostics:
        lines.append(d.format())
    return "\n".join(lines)


def summary_line(report: DeepcheckReport) -> str:
    return (
        f"deepcheck: {len(report.diagnostics)} finding(s), "
        f"{len(report.failures)} trace failure(s), "
        f"{report.suppressed} suppressed, over "
        f"{len(report.entries)} audit entr{'y' if len(report.entries) == 1 else 'ies'}"
    )
