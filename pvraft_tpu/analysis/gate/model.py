"""Build the evidence model the GE rules check.

One read-only pass over the repo: the tracked artifact set, every
citation and ``<!-- claim: -->`` in the claim docs, each artifact's
``schema`` field, the backticked row tokens of the artifacts/README
index, the ``# gate-stage:`` manifests, and every ``pvraft_*/vN``
schema literal in package/scripts source. Pure stdlib; no jax.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
import re
import subprocess
from typing import Dict, List, Optional, Sequence, Tuple

from pvraft_tpu.analysis.gate.evidence import (
    CLAIM_DOCS,
    EPHEMERAL_PATHS,
    Citation,
    Claim,
    extract_citations,
    extract_claims,
)

_SCHEMA_LITERAL_RE = re.compile(r"pvraft_[a-z0-9_]+/v\d+")

# Backticked tokens in artifacts/README rows: the per-artifact index.
_ROW_TOKEN_RE = re.compile(r"`([^`\s]+)`")


@dataclasses.dataclass
class EvidenceModel:
    root: str
    docs: Dict[str, List[str]]                      # relpath -> lines
    tracked: List[str]                              # artifacts/... relpaths
    citations: List[Citation]
    claims: List[Claim]
    artifact_schemas: Dict[str, Optional[str]]      # relpath -> schema field
    index_patterns: List[Tuple[int, str]]           # artifacts/README rows
    manifests: Dict[str, List[Tuple[int, str]]]     # path -> [(line, stage)]
    source_schemas: List[Tuple[str, int, str]]      # (path, line, schema)
    errors: List[Tuple[str, int, str]]              # GE000 material


def _ephemeral(rel: str) -> bool:
    return any(rel == e or rel.startswith(e + "/") for e in EPHEMERAL_PATHS)


def tracked_artifacts(root: str, use_git: bool = True) -> List[str]:
    """Committed evidence: git-tracked artifacts/ files, unioned with the
    on-disk tree (minus declared-ephemeral subtrees) so a freshly written,
    not-yet-added artifact is already checked before commit."""
    found = set()
    if use_git:
        try:
            out = subprocess.run(
                ["git", "-C", root, "ls-files", "--", "artifacts"],
                capture_output=True, text=True, timeout=30, check=False,
            )
            if out.returncode == 0:
                for line in out.stdout.splitlines():
                    line = line.strip()
                    if line and not _ephemeral(line):
                        found.add(line)
        except OSError:
            pass
    art_dir = os.path.join(root, "artifacts")
    if os.path.isdir(art_dir):
        for dirpath, dirnames, filenames in os.walk(art_dir):
            rel_dir = os.path.relpath(dirpath, root).replace(os.sep, "/")
            dirnames[:] = [
                d for d in dirnames
                if d != "__pycache__" and not _ephemeral(f"{rel_dir}/{d}")
            ]
            for fn in filenames:
                rel = f"{rel_dir}/{fn}"
                if not _ephemeral(rel):
                    found.add(rel)
    found.discard("artifacts/README.md")
    return sorted(found)


def _artifact_schema(path: str) -> Tuple[bool, Optional[str]]:
    """(parsed_ok, schema field) of a .json / .jsonl artifact."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            if path.endswith(".jsonl"):
                first = fh.readline()
                doc = json.loads(first) if first.strip() else {}
            else:
                doc = json.load(fh)
    except (OSError, ValueError):
        return False, None
    if isinstance(doc, dict):
        schema = doc.get("schema")
        return True, schema if isinstance(schema, str) else None
    return True, None


def _index_patterns(lines: Sequence[str]) -> List[Tuple[int, str]]:
    """artifacts/README table rows -> (line, fnmatch pattern) per token.

    Tokens are the backticked filenames in the first column (and inline
    mentions): ``<...>`` templates become ``*``; a leading-dot token
    like ``.events.jsonl`` indexes every artifact with that suffix.
    """
    out: List[Tuple[int, str]] = []
    for i, line in enumerate(lines, start=1):
        if not line.lstrip().startswith("|"):
            continue
        first_col = line.split("|")[1] if line.count("|") >= 2 else line
        for tok in _ROW_TOKEN_RE.findall(first_col):
            pat = re.sub(r"<[^<>]*>", "*", tok)
            if pat.startswith("."):
                pat = "*" + pat
            if pat.startswith("artifacts/"):
                pat = pat[len("artifacts/"):]
            out.append((i, pat))
    return out


def _scan_source_schemas(root: str) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    roots = [os.path.join(root, "pvraft_tpu"), os.path.join(root, "scripts")]
    for base in roots:
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                try:
                    with open(path, "r", encoding="utf-8") as fh:
                        for i, line in enumerate(fh, start=1):
                            for m in _SCHEMA_LITERAL_RE.finditer(line):
                                out.append((rel, i, m.group(0)))
                except OSError:
                    continue
    return out


DEFAULT_MANIFESTS: Tuple[str, ...] = (
    "scripts/lint.sh",
    ".github/workflows/ci.yml",
)


def build_evidence_model(
    root: Optional[str] = None,
    docs: Sequence[str] = CLAIM_DOCS,
    manifest_paths: Sequence[str] = DEFAULT_MANIFESTS,
    use_git: bool = True,
) -> EvidenceModel:
    from pvraft_tpu.analysis.gate.stages import parse_manifest

    root = os.path.abspath(root or os.getcwd())
    model = EvidenceModel(
        root=root, docs={}, tracked=[], citations=[], claims=[],
        artifact_schemas={}, index_patterns=[], manifests={},
        source_schemas=[], errors=[],
    )

    for doc in docs:
        path = os.path.join(root, doc)
        if not os.path.exists(path):
            continue
        try:
            with open(path, "r", encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError as exc:
            model.errors.append((doc, 1, f"unreadable claim doc ({exc})"))
            continue
        model.docs[doc] = lines
        model.citations.extend(extract_citations(doc, lines))
        model.claims.extend(extract_claims(doc, lines))
        if doc == "artifacts/README.md":
            model.index_patterns = _index_patterns(lines)

    model.tracked = tracked_artifacts(root, use_git=use_git)
    for rel in model.tracked:
        if rel.endswith((".json", ".jsonl")):
            ok, schema = _artifact_schema(os.path.join(root, rel))
            if not ok:
                model.errors.append((rel, 1, "unparseable JSON artifact"))
            model.artifact_schemas[rel] = schema

    for mpath in manifest_paths:
        path = os.path.join(root, mpath)
        if not os.path.exists(path):
            continue
        try:
            with open(path, "r", encoding="utf-8") as fh:
                model.manifests[mpath] = parse_manifest(fh.read())
        except OSError as exc:
            model.errors.append((mpath, 1, f"unreadable manifest ({exc})"))

    model.source_schemas = _scan_source_schemas(root)
    return model


def first_match(rel: str, validators) -> Optional[object]:
    """First VALIDATORS row whose glob covers an artifact (None = none)."""
    for spec in validators:
        for pattern in spec.globs:
            if fnmatch.fnmatch(rel, pattern):
                return spec
    return None
