"""Declared evidence data plane for gatecheck.

The PARTITION_RULES/KERNEL_BINDINGS precedent applied to the evidence
discipline itself: which committed artifact is validated by which gate
stage (``VALIDATORS``, an ordered first-match table like
``scripts/artifact_budget.py``'s glob caps), which docs carry headline
claims (``CLAIM_DOCS``), and which ``artifacts/`` subtrees are declared
ephemeral run products rather than committed evidence
(``EPHEMERAL_PATHS``). The GE rules (``rules.py``) are thin checks over
this table plus the repo state — changing the evidence story means
editing data here, and the rules keep the table honest against the
tracked tree both ways.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Sequence, Tuple

# Docs whose artifact citations and <!-- claim: --> annotations gatecheck
# sweeps. artifacts/README.md is additionally the index GE001 checks the
# tracked artifact set against.
CLAIM_DOCS: Tuple[str, ...] = (
    "README.md",
    "BENCHMARKS.md",
    "ROADMAP.md",
    "artifacts/README.md",
)

# artifacts/ subtrees that are ephemeral run products (gitignored caches,
# raw queue logs): citable as directories in prose, never required to
# exist on a fresh checkout, never indexed per-file.
EPHEMERAL_PATHS: Tuple[str, ...] = (
    "artifacts/xla_cache",
    "artifacts/logs",
)


@dataclasses.dataclass(frozen=True)
class ValidatorSpec:
    """One row of the evidence registry.

    ``schema``: the ``pvraft_*/v1`` schema string this row owns ("" for
    evidence that predates the schema discipline or is pinned by other
    means). GE004 enforces each schema appears on exactly one row.

    ``globs``: artifact paths (repo-relative, fnmatch) this row covers.
    First matching row across the table wins — keep specific globs
    (``*.trace.json``) above broad ones (``serve_*.json``), the
    artifact_budget.py discipline. Empty globs = a run-product schema
    with no committed artifact (snapshots, advisor hints).

    ``stage``: the gate stage (``stages.GATE_STAGES`` name) that
    validates the covered artifacts ("" when the pin lives elsewhere —
    the note says where). GE005 checks stage names resolve.

    ``note``: how this evidence stays honest — shown in findings so a
    GE002 hit tells the author what kind of row to add.
    """

    schema: str
    globs: Tuple[str, ...]
    stage: str
    note: str


# Ordered, first match wins (specific before broad — the serve_*.json row
# must come after the trace/slo/calibration rows it would shadow).
VALIDATORS: Tuple[ValidatorSpec, ...] = (
    ValidatorSpec(
        schema="pvraft_kernel_plan/v1",
        globs=("artifacts/kernel_plan.json",),
        stage="kernel-plan",
        note="regenerate-and-compare vs the static kernel models",
    ),
    ValidatorSpec(
        schema="pvraft_pod_plan/v1",
        globs=("artifacts/pod_plan.json",),
        stage="pod-plan",
        note="regenerate-and-compare vs PARTITION_RULES x params_tree x costs",
    ),
    ValidatorSpec(
        schema="pvraft_params_tree/v1",
        globs=("artifacts/params_tree.json",),
        stage="params-tree",
        note="regenerate-and-compare vs the registry eval_shape tree",
    ),
    ValidatorSpec(
        schema="pvraft_determinism/v1",
        globs=("artifacts/determinism_report.json",),
        stage="determinism-replay",
        note="fresh bitwise replay on this host, digests pinned per platform",
    ),
    ValidatorSpec(
        schema="pvraft_costs/v1",
        globs=("artifacts/programs_costs.json",),
        stage="costs-check",
        note="schema + both-direction registry coverage",
    ),
    ValidatorSpec(
        schema="",
        globs=("artifacts/programs_kernels.json",),
        stage="kernels-evidence",
        note="pinned both directions vs the kernel-tag registry",
    ),
    ValidatorSpec(
        schema="pvraft_bench/v1",
        globs=("artifacts/bench_*.json",),
        stage="validate-bench",
        note="schema + bench_compare self-comparison wiring",
    ),
    ValidatorSpec(
        schema="pvraft_capacity/v1",
        globs=("artifacts/capacity_report.json",),
        stage="validate-capacity",
        note="schema + regenerate from the artifact's own recorded inputs",
    ),
    ValidatorSpec(
        schema="pvraft_cost_calibration/v1",
        globs=("artifacts/serve_calibration.json",),
        stage="validate-calibration",
        note="identity held at every snapshot; comparable=true off-TPU rejected",
    ),
    ValidatorSpec(
        schema="pvraft_events/v1",
        globs=("artifacts/*.events.jsonl",),
        stage="validate-events",
        note="every committed event log parses against the stream schema",
    ),
    ValidatorSpec(
        schema="pvraft_trace/v1",
        globs=("artifacts/*.trace.json",),
        stage="validate-trace",
        note="completeness/orphan counts recomputed from the spans",
    ),
    ValidatorSpec(
        schema="pvraft_slo/v1",
        globs=("artifacts/*.slo.json",),
        stage="validate-slo",
        note="stage-sum vs e2e honesty ratio checked at the declared band",
    ),
    # Broad serve row AFTER the trace/slo/calibration rows above.
    ValidatorSpec(
        schema="pvraft_serve_load/v1",
        globs=("artifacts/serve_*.json",),
        stage="validate-load",
        note="loadgen evidence; server_metrics reconcile",
    ),
    ValidatorSpec(
        schema="pvraft_fleet_chaos/v1",
        globs=("artifacts/fleet_chaos.json",),
        stage="validate-fleet",
        note="generator-refused unless identity held at every snapshot, "
             "spillover resolved the lost backend and recompiles == 0; "
             "embedded load block re-validated via the serve validator",
    ),
    ValidatorSpec(
        schema="pvraft_step_profile/v1",
        globs=("artifacts/step_profile.json",),
        stage="validate-profile",
        note="stage breakdown must telescope to the measured total",
    ),
    ValidatorSpec(
        schema="pvraft_gate/v1",
        globs=("artifacts/gate_*.json",),
        stage="validate-gate-report",
        note="committed gate reports: full run, all stages ok/cached",
    ),
    # Run-product schemas with no committed artifact: declared here so
    # GE004 still sees exactly one owner for the schema string.
    ValidatorSpec(
        schema="pvraft_snapshot/v1",
        globs=(),
        stage="",
        note="divergence snapshots live under experiments/, never committed",
    ),
    ValidatorSpec(
        schema="pvraft_bucket_advisor/v1",
        globs=(),
        stage="",
        note="serve bucket advisor hints are run products, never committed",
    ),
    # Pre-schema / otherwise-pinned evidence (schema=""): covered rows so
    # GE002 stays quiet for the right reason, with the pin named.
    ValidatorSpec(
        schema="",
        globs=("artifacts/programs_list.txt",),
        stage="",
        note="pinned both directions by tests/test_programs.py",
    ),
    ValidatorSpec(
        schema="",
        globs=(
            "artifacts/convergence_*.json",
            "artifacts/ft3d_pipeline_convergence*.json",
            "artifacts/refine_convergence.json",
        ),
        stage="",
        note="generator-gated convergence evidence (writer refuses on red gates)",
    ),
    ValidatorSpec(
        schema="",
        globs=(
            "artifacts/grad_parity.json",
            "artifacts/protocol_parity*.json",
            "artifacts/trajectory_parity.json",
            "artifacts/loader_parity.json",
            "artifacts/loader_bench.json",
        ),
        stage="",
        note="generator-gated parity/bench evidence vs the torch reference",
    ),
    ValidatorSpec(
        schema="",
        globs=(
            "artifacts/scale16k_*.json",
            "artifacts/eval_tpu.json",
            "artifacts/tpu_consistency.json",
            "artifacts/aot_readiness.json",
            "artifacts/multistep_probe.jsonl",
        ),
        stage="",
        note="pre-schema on-chip/queue evidence; superseding schemas tracked in ROADMAP",
    ),
    ValidatorSpec(
        schema="",
        globs=("artifacts/*.log", "artifacts/logs/*"),
        stage="",
        note="raw queue logs: history, not citable evidence",
    ),
    ValidatorSpec(
        schema="",
        globs=("artifacts/legacy/*",),
        stage="",
        note="pre-gate CPU-fallback-era queue records (ex repo root): "
             "explicitly incomparable history, never citable — the "
             "artifacts/README 'Pre-gate bench records' section is the pin",
    ),
)


# --- citation / claim extraction -------------------------------------------

# An artifacts/ path cited in prose. Template spellings survive the
# match (<timestamp>, {a,b}, *) and are normalized by _normalize_citation.
_CITE_RE = re.compile(r"artifacts/[A-Za-z0-9_.{},*<>/-]*[A-Za-z0-9_*>}]")

# The GE003 machine-checkable citation convention. The value under check
# is the LAST numeric token on the line before the claim comment. An
# optional unit transform maps raw artifact units onto prose units:
# ``@gib``/``@mib`` divide a byte field, ``@len`` takes a collection's
# length ("95 leaves" against the leaves array itself).
CLAIM_RE = re.compile(
    r"<!--\s*claim:\s*(?P<src>artifacts/[A-Za-z0-9_./-]+)"
    r"#(?P<field>[A-Za-z0-9_.-]+)(?:@(?P<unit>[a-z]+))?\s*-->"
)

CLAIM_UNITS = ("gib", "mib", "len")

_NUM_RE = re.compile(r"[-+]?\d[\d,]*(?:\.\d+)?")


@dataclasses.dataclass(frozen=True)
class Citation:
    doc: str
    line: int
    raw: str
    patterns: Tuple[str, ...]  # normalized fnmatch patterns


@dataclasses.dataclass(frozen=True)
class Claim:
    doc: str
    line: int
    src: str
    field: str
    unit: str  # "" or one of CLAIM_UNITS
    quoted: Optional[str]  # numeric token preceding the comment, or None


def _expand_braces(pattern: str) -> List[str]:
    """One level of {a,b} brace expansion (citation templates use one)."""
    m = re.search(r"\{([^{}]*)\}", pattern)
    if not m:
        return [pattern]
    out: List[str] = []
    for alt in m.group(1).split(","):
        out.extend(_expand_braces(pattern[: m.start()] + alt + pattern[m.end():]))
    return out


def _normalize_citation(raw: str) -> List[str]:
    """Cited path -> fnmatch patterns (templates become globs)."""
    raw = raw.rstrip(".,;:)")
    raw = re.sub(r"<[^<>]*>", "*", raw)
    return [p for p in _expand_braces(raw) if p not in ("artifacts", "artifacts/")]


def extract_citations(doc: str, lines: Sequence[str]) -> List[Citation]:
    out: List[Citation] = []
    for i, line in enumerate(lines, start=1):
        for m in _CITE_RE.finditer(line):
            raw = m.group(0)
            pats = tuple(_normalize_citation(raw))
            if pats:
                out.append(Citation(doc=doc, line=i, raw=raw, patterns=pats))
    return out


def extract_claims(doc: str, lines: Sequence[str]) -> List[Claim]:
    """Claims on a line consume the numeric tokens left of each comment.

    Multiple claims per line work left-to-right: each claim's quoted
    value is the last number in the segment between the previous claim
    comment and its own. Lines inside fenced code blocks are syntax
    examples, not claims (the docstring-pragma discipline).
    """
    out: List[Claim] = []
    fenced = False
    for i, line in enumerate(lines, start=1):
        if line.lstrip().startswith("```"):
            fenced = not fenced
            continue
        if fenced:
            continue
        prev_end = 0
        for m in CLAIM_RE.finditer(line):
            segment = line[prev_end : m.start()]
            nums = _NUM_RE.findall(segment)
            out.append(
                Claim(
                    doc=doc,
                    line=i,
                    src=m.group("src"),
                    field=m.group("field"),
                    unit=m.group("unit") or "",
                    quoted=nums[-1] if nums else None,
                )
            )
            prev_end = m.end()
    return out


def resolve_field(obj: object, dotted: str):
    """Walk a dotted path through dicts (keys) and lists (int indices).

    Returns (found: bool, value).
    """
    cur = obj
    for seg in dotted.split("."):
        if isinstance(cur, dict):
            if seg not in cur:
                return False, None
            cur = cur[seg]
        elif isinstance(cur, list):
            if not re.fullmatch(r"-?\d+", seg):
                return False, None
            idx = int(seg)
            if not (-len(cur) <= idx < len(cur)):
                return False, None
            cur = cur[idx]
        else:
            return False, None
    return True, cur


def apply_unit(value: object, unit: str):
    """Apply a claim unit transform. Returns (ok, transformed)."""
    if not unit:
        return True, value
    if unit == "len":
        if isinstance(value, (list, dict, str)):
            return True, len(value)
        return False, value
    if unit in ("gib", "mib"):
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return False, value
        return True, value / (2 ** 30 if unit == "gib" else 2 ** 20)
    return False, value


def claim_matches(quoted: str, value: object) -> bool:
    """Quoted prose number vs artifact value, at the prose's precision.

    The prose is allowed to round: ``10.46`` matches any value within
    half its last printed digit (|v - p| <= 0.5 * 10^-d). Commas in the
    prose are thousands separators.
    """
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return False
    text = quoted.replace(",", "")
    try:
        prose = float(text)
    except ValueError:
        return False
    digits = len(text.split(".", 1)[1]) if "." in text else 0
    tol = 0.5 * 10.0 ** (-digits)
    return abs(float(value) - prose) <= tol + 1e-12
