"""The gate stage registry: lint.sh's bash stage list as declared data.

Each :class:`GateStage` row carries the stage's shell command, the input
globs its result is a pure function of (the content-hash cache key), its
dependencies, and its environment pins. ``scripts/lint.sh`` is now a
thin shim over ``python -m pvraft_tpu.analysis gate``; both it and
``.github/workflows/ci.yml`` carry a ``# gate-stage: <name>`` manifest
line per stage, and GE005 pins manifest == registry in both directions
so bash, CI and this table cannot drift.

Input globs err wide on purpose: a stage that re-runs unnecessarily
costs minutes once; a stage that stays cached across a real change
costs the gate its meaning. Stages whose commands import the model
stack therefore hash the whole package.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Tuple

# Shared glob vocabularies. PKG covers every Python file in the package
# (glob's ``**`` includes the empty path, so top-level modules match).
PKG = ("pvraft_tpu/**/*.py",)
ANALYSIS_CORE = (
    "pvraft_tpu/analysis/engine.py",
    "pvraft_tpu/analysis/__main__.py",
)
LINT_SCOPE = PKG + ("tests/**/*.py", "scripts/*.py")

# Environment pin vocabularies (merged over os.environ by the runner).
CPU = (("JAX_PLATFORMS", "cpu"),)


@dataclasses.dataclass(frozen=True)
class GateStage:
    """One declared gate stage.

    ``command`` runs under ``bash -c`` from the repo root. ``inputs``
    are repo-relative globs (``**`` recursive); the stage is cached iff
    every matched file's content hash is unchanged since the last green
    run of the same command+env. ``deps`` name stages that must finish
    ok first (e.g. the warm ``artifacts/xla_cache`` handoff).
    ``virtual_devices`` > 0 appends
    ``--xla_force_host_platform_device_count=N`` to XLA_FLAGS — the
    lint.sh ``_audit_flags`` idiom (a real 2-shard seq axis so deepcheck
    walks contain the ring ppermutes, not a degenerate p=1 loop).
    ``doc`` preserves the old lint.sh stage comment.
    """

    name: str
    command: str
    inputs: Tuple[str, ...]
    deps: Tuple[str, ...] = ()
    env: Tuple[Tuple[str, str], ...] = ()
    virtual_devices: int = 0
    doc: str = ""


GATE_STAGES: Tuple[GateStage, ...] = (
    GateStage(
        name="graftlint",
        command="python -m pvraft_tpu.analysis lint pvraft_tpu/ tests/ scripts/",
        inputs=LINT_SCOPE,
        doc="AST rules over pvraft_tpu/ + tests/ + scripts/. Same scope as "
            "the --stats pass: what the debt report counts as a blind spot "
            "must be a file the rules actually run on.",
    ),
    GateStage(
        name="lint-stats",
        command="python -m pvraft_tpu.analysis lint --stats pvraft_tpu/ tests/ scripts/",
        inputs=LINT_SCOPE,
        doc="The gate's blind spots, enumerated: per-rule counts of active "
            "`graftlint: disable` pragmas (one shared grammar across the "
            "engines); any suppression without a `-- reason` exits non-zero.",
    ),
    GateStage(
        name="gatecheck",
        command="python -m pvraft_tpu.analysis gate --rules",
        inputs=(
            "README.md",
            "BENCHMARKS.md",
            "ROADMAP.md",
            "artifacts/README.md",
            "artifacts/**",
            "scripts/lint.sh",
            ".github/workflows/ci.yml",
            "pvraft_tpu/analysis/gate/*.py",
        ) + ANALYSIS_CORE + ("scripts/*.py",),
        doc="The seventh engine checking the evidence discipline itself: "
            "dangling citations/unindexed artifacts (GE001), artifacts no "
            "validator covers (GE002), stale <!-- claim: --> numbers "
            "(GE003), schema-exactly-once (GE004), stage-set identity "
            "across registry/lint.sh/ci.yml (GE005).",
    ),
    GateStage(
        name="threadcheck",
        command="python -m pvraft_tpu.analysis concurrency",
        inputs=(
            "pvraft_tpu/serve/**/*.py",
            "pvraft_tpu/fleet/**/*.py",
            "pvraft_tpu/obs/**/*.py",
            "pvraft_tpu/data/*.py",
            "pvraft_tpu/analysis/concurrency/*.py",
        ) + ANALYSIS_CORE,
        doc="Concurrency static analysis (GC rules) over serve/fleet/obs/"
            "loader: "
            "guarded-by discipline, lock-order cycles, check-then-act "
            "shapes, un-joined non-daemon threads. Pure stdlib AST, no jax. "
            "The dynamic half is opt-in at test time (PVRAFT_CHECKS=1 turns "
            "the serve/obs locks into OrderedLocks).",
    ),
    GateStage(
        name="kernelcheck",
        command="python -m pvraft_tpu.analysis kernels",
        inputs=(
            "pvraft_tpu/ops/**/*.py",
            "pvraft_tpu/programs/*.py",
            "pvraft_tpu/analysis/kernels/*.py",
        ) + ANALYSIS_CORE,
        doc="Pallas/Mosaic static analysis (GK rules) over ops/pallas: tile "
            "alignment vs the (sublane, lane) layout, static double-buffered "
            "VMEM budget, grid x block coverage, the Mosaic lowering hazard "
            "table, kernel-tag registry coverage, interpret_mode(). Pure "
            "stdlib AST, no jax; layout notes print but never fail.",
    ),
    GateStage(
        name="kernel-plan",
        command="python -m pvraft_tpu.analysis kernels --check artifacts/kernel_plan.json",
        inputs=(
            "pvraft_tpu/ops/**/*.py",
            "pvraft_tpu/programs/*.py",
            "pvraft_tpu/analysis/kernels/*.py",
            "artifacts/kernel_plan.json",
            "artifacts/programs_costs.json",
        ) + ANALYSIS_CORE,
        doc="artifacts/kernel_plan.json is a pure function of the static "
            "kernel models + the committed cost inventory: regenerate and "
            "compare, enforcing the static-vs-Mosaic HBM cross-validation "
            "(pinned factor 2.0) that keeps the fused-GRU residency verdict "
            "honest.",
    ),
    GateStage(
        name="shardcheck",
        command="python -m pvraft_tpu.analysis sharding",
        inputs=PKG + ("artifacts/params_tree.json",),
        doc="SPMD/multi-host static analysis (GS rules) over the "
            "multi-process planes: partition-rule exactly-once coverage vs "
            "the committed param-tree inventory, mesh-axis discipline, the "
            "eager-stack idiom, unguarded process-0 I/O, batch-contract "
            "arithmetic. Pure stdlib AST + the jax-free data planes.",
    ),
    GateStage(
        name="pod-plan",
        command="python -m pvraft_tpu.analysis sharding --check artifacts/pod_plan.json",
        inputs=PKG + (
            "artifacts/pod_plan.json",
            "artifacts/params_tree.json",
            "artifacts/programs_costs.json",
        ),
        doc="artifacts/pod_plan.json is a pure function of PARTITION_RULES x "
            "params_tree.json x programs_costs.json x the candidate meshes: "
            "regenerate and compare, enforcing the sharded-step honesty "
            "cross-check vs the compiled dp_sp_2x2_train_step live bytes.",
    ),
    GateStage(
        name="detcheck",
        command="python -m pvraft_tpu.analysis determinism",
        inputs=PKG,
        doc="Determinism/seed-discipline static analysis (GD rules) over the "
            "whole package: PRNG key reuse, entropy outside the rng stream "
            "contract, nondeterminism-hazard ops without a declared stance, "
            "backend flags outside compat.py, iteration-order hazards.",
    ),
    GateStage(
        name="determinism-replay",
        command="python -m pvraft_tpu.analysis determinism --check artifacts/determinism_report.json",
        inputs=PKG + ("artifacts/determinism_report.json",),
        env=CPU,
        doc="The dynamic half of detcheck: rebuild the registered train step "
            "and serve dispatch twice from the config seed and diff every "
            "output leaf bitwise, HERE and now; raw digests additionally "
            "pinned when the committed platform matches.",
    ),
    GateStage(
        name="kernels-evidence",
        command="python -m pvraft_tpu.programs compile --check artifacts/programs_kernels.json",
        inputs=(
            "pvraft_tpu/programs/*.py",
            "pvraft_tpu/ops/**/*.py",
            "artifacts/programs_kernels.json",
        ),
        doc="artifacts/programs_kernels.json must name exactly the "
            "kernel-tagged registry specs, each with a successful Mosaic "
            "compile record — both directions. Pure validation, no "
            "toolchain, no compiles.",
    ),
    GateStage(
        name="programs-verify",
        command="python -m pvraft_tpu.programs verify",
        inputs=PKG,
        env=CPU,
        virtual_devices=8,
        doc="Registry-wide eval_shape verify (zero-FLOP abstract traces): "
            "every ProgramSpec — audit entries, the AOT catalog, the "
            "profiler ladder. CPU pin: shape propagation needs no "
            "accelerator and must not grab one.",
    ),
    GateStage(
        name="params-tree",
        command="python -m pvraft_tpu.programs params --check artifacts/params_tree.json",
        inputs=PKG + ("artifacts/params_tree.json",),
        env=CPU,
        virtual_devices=8,
        doc="artifacts/params_tree.json is the jax-free cache of the "
            "flagship param tree the GS001 gate and the pod planner join "
            "against; one eval_shape regenerates and compares.",
    ),
    GateStage(
        name="deepcheck",
        command="python -m pvraft_tpu.analysis deepcheck",
        inputs=PKG,
        env=CPU,
        virtual_devices=8,
        doc="jaxpr-level semantic analysis (GJ rules) over the audit corpus: "
            "collective consistency, donation efficacy, precision flow, "
            "retrace hazards. Tracing only — zero FLOPs, CPU-safe. The 8 "
            "virtual devices give the ring audit entries a REAL 2-shard seq "
            "axis, so the walks contain the ring ppermutes.",
    ),
    GateStage(
        name="kernel-compile",
        command="python -m pvraft_tpu.programs compile --tag kernel --allow-missing-toolchain",
        inputs=PKG,
        env=CPU,
        doc="Deviceless Mosaic compile of every Pallas kernel entry point "
            "through the REAL XLA:TPU pipeline against the declared v5e "
            "topology — toolchain drift fails here, not silently at HEAD. "
            "--allow-missing-toolchain: hosts with no libtpu skip LOUDLY.",
    ),
    GateStage(
        name="costs-smoke",
        command="python -m pvraft_tpu.programs costs --tag kernel --allow-missing-toolchain",
        inputs=PKG,
        deps=("kernel-compile",),
        env=CPU,
        doc="pvraft_costs/v1 smoke over the Pallas kernel specs (same "
            "deviceless Mosaic topology; depends on kernel-compile so the "
            "shared artifacts/xla_cache is warm) — a cost_analysis()/"
            "memory_analysis() API drift fails HERE, not at the next full "
            "regeneration. Same loud-skip semantics without libtpu.",
    ),
    GateStage(
        name="costs-check",
        command="python -m pvraft_tpu.programs costs --check artifacts/programs_costs.json",
        inputs=PKG + ("artifacts/programs_costs.json",),
        env=CPU,
        virtual_devices=8,
        doc="artifacts/programs_costs.json must be schema-valid AND cover "
            "every non-expect_failure ProgramSpec, both directions. Pure "
            "validation — no toolchain, no compiles.",
    ),
    GateStage(
        name="validate-bench",
        command=(
            'bench_artifacts=$(ls artifacts/bench_*.json 2>/dev/null || true); '
            'if [ -n "$bench_artifacts" ]; then '
            "python -m pvraft_tpu.obs validate-bench $bench_artifacts && "
            "python scripts/bench_compare.py artifacts/bench_baseline.json "
            "artifacts/bench_baseline.json; "
            'else echo "(no committed bench artifacts)"; fi'
        ),
        inputs=(
            "pvraft_tpu/obs/**/*.py",
            "scripts/bench_compare.py",
            "artifacts/bench_*.json",
        ),
        doc="pvraft_bench/v1: the committed baseline must parse against the "
            "schema (platform/comparable first-class — a CPU fallback can "
            "never masquerade as a TPU number), and bench_compare must "
            "accept a self-comparison (schema -> comparability -> noise "
            "band -> exit code, end to end).",
    ),
    GateStage(
        name="validate-capacity",
        command=(
            "python -m pvraft_tpu.obs validate-capacity artifacts/capacity_report.json && "
            "python scripts/capacity_report.py --check artifacts/capacity_report.json"
        ),
        inputs=(
            "pvraft_tpu/obs/**/*.py",
            "pvraft_tpu/serve/**/*.py",
            "scripts/capacity_report.py",
            "artifacts/capacity_report.json",
            "artifacts/programs_costs.json",
            "artifacts/serve_cpu_synthetic.json",
            "artifacts/serve_cpu_synthetic.slo.json",
        ),
        env=CPU,
        doc="pvraft_capacity/v1: schema-validate (chips-needed recomputed, "
            "not trusted), then regenerate from the artifact's OWN recorded "
            "inputs and compare — a hand-edited chips number, or drift "
            "between planner code and committed plan, fails here.",
    ),
    GateStage(
        name="validate-calibration",
        command="python -m pvraft_tpu.obs validate-calibration artifacts/serve_calibration.json",
        inputs=(
            "pvraft_tpu/obs/**/*.py",
            "artifacts/serve_calibration.json",
        ),
        env=CPU,
        doc="pvraft_cost_calibration/v1: predicted-vs-measured ledger from a "
            "real loadgen run with the cost surface armed — the identity "
            "must have held at every polled snapshot, ratios recompute, and "
            "comparable=true off-TPU is a schema violation.",
    ),
    GateStage(
        name="artifact-budget",
        command="python scripts/artifact_budget.py",
        inputs=("scripts/artifact_budget.py", "artifacts/**"),
        doc="Per-glob byte caps over committed evidence.",
    ),
    GateStage(
        name="validate-events",
        command=(
            'event_logs=$(ls artifacts/*.events.jsonl tests/fixtures/*.events.jsonl 2>/dev/null || true); '
            'if [ -n "$event_logs" ]; then '
            "python -m pvraft_tpu.obs validate $event_logs; "
            'else echo "(no committed event logs)"; fi'
        ),
        inputs=(
            "pvraft_tpu/obs/**/*.py",
            "artifacts/*.events.jsonl",
            "tests/fixtures/*.events.jsonl",
        ),
        doc="pvraft_events/v1: any event log shipped as evidence plus the "
            "golden test fixture must parse against the schema — a drifted "
            "writer fails the gate before a TPU run produces unreadable "
            "telemetry.",
    ),
    GateStage(
        name="validate-load",
        command=(
            "serve_artifacts=$(ls artifacts/serve_*.json 2>/dev/null "
            "| grep -v -e '\\.trace\\.json$' -e '\\.slo\\.json$' "
            "-e 'serve_calibration\\.json$' || true); "
            'if [ -n "$serve_artifacts" ]; then '
            "python -m pvraft_tpu.serve validate-load $serve_artifacts; "
            'else echo "(no committed serve artifacts)"; fi'
        ),
        inputs=(
            "pvraft_tpu/serve/**/*.py",
            "artifacts/serve_*.json",
        ),
        doc="pvraft_serve_load/v1: the serve latency/throughput evidence "
            "must parse against its schema. The trace/SLO siblings and the "
            "calibration evidence have their own validators in other "
            "stages — excluded here (the VALIDATORS first-match order).",
    ),
    GateStage(
        name="validate-fleet",
        command="python -m pvraft_tpu.fleet validate artifacts/fleet_chaos.json",
        inputs=(
            "pvraft_tpu/fleet/**/*.py",
            "pvraft_tpu/serve/loadgen.py",
            "artifacts/fleet_chaos.json",
        ),
        doc="pvraft_fleet_chaos/v1: the committed 2-backend chaos evidence "
            "(backend loss resolved by spillover, zero-recompile hot-swap "
            "under the sealed watchdog, a canary verdict) must re-validate "
            "structurally — embedded load block included, through the serve "
            "validator.",
    ),
    GateStage(
        name="validate-trace",
        command=(
            'trace_artifacts=$(ls artifacts/*.trace.json 2>/dev/null || true); '
            'if [ -n "$trace_artifacts" ]; then '
            "python -m pvraft_tpu.obs validate-trace $trace_artifacts; "
            'else echo "(no committed trace artifacts)"; fi'
        ),
        inputs=(
            "pvraft_tpu/obs/**/*.py",
            "artifacts/*.trace.json",
        ),
        doc="pvraft_trace/v1: span trees grouped per trace; the validator "
            "recomputes completeness and orphan counts from the spans, so a "
            "hand-edited 'complete' flag cannot pass.",
    ),
    GateStage(
        name="validate-slo",
        command=(
            'slo_artifacts=$(ls artifacts/*.slo.json 2>/dev/null || true); '
            'if [ -n "$slo_artifacts" ]; then '
            "python -m pvraft_tpu.obs validate-slo $slo_artifacts; "
            'else echo "(no committed SLO reports)"; fi'
        ),
        inputs=(
            "pvraft_tpu/obs/**/*.py",
            "artifacts/*.slo.json",
        ),
        doc="pvraft_slo/v1: loadgen client latencies joined to span trees by "
            "trace id, with the stage-p99-sum/e2e-p99 honesty ratio checked "
            "at the report's declared band.",
    ),
    GateStage(
        name="validate-profile",
        command="python -m pvraft_tpu.profiling validate artifacts/step_profile.json",
        inputs=(
            "pvraft_tpu/profiling/*.py",
            "artifacts/step_profile.json",
        ),
        doc="pvraft_step_profile/v1: the committed per-stage train-step "
            "breakdown must telescope to the measured total (host-fetch "
            "synced). Previously only pinned by tests; now a gate stage so "
            "the artifact is validator-covered (GE002).",
    ),
    GateStage(
        name="validate-gate-report",
        command="python -m pvraft_tpu.analysis gate --check artifacts/gate_cold.json artifacts/gate_warm.json",
        inputs=(
            "pvraft_tpu/analysis/gate/*.py",
            "artifacts/gate_cold.json",
            "artifacts/gate_warm.json",
        ),
        doc="pvraft_gate/v1: the committed cold/warm gate reports BENCHMARKS "
            "cites must validate — full (not --changed-only) runs, every "
            "stage ok or cached, stage set identical to this registry. "
            "Timings are wall-clock records, never regenerate-compared.",
    ),
)


def stage_names() -> List[str]:
    return [s.name for s in GATE_STAGES]


def stage_problems(stages: Tuple[GateStage, ...] = GATE_STAGES) -> List[str]:
    """Structural problems of a stage registry ([] = well-formed).

    Exactly-once names, deps resolve, no dependency cycles.
    """
    problems: List[str] = []
    seen = set()
    for s in stages:
        if s.name in seen:
            problems.append(f"stage {s.name!r} declared more than once")
        seen.add(s.name)
    names = {s.name for s in stages}
    for s in stages:
        for dep in s.deps:
            if dep not in names:
                problems.append(f"stage {s.name!r} depends on unknown stage {dep!r}")
            if dep == s.name:
                problems.append(f"stage {s.name!r} depends on itself")
    # Cycle check: repeatedly strip stages whose deps are all stripped.
    remaining = {s.name: set(d for d in s.deps if d in names) for s in stages}
    while True:
        free = [n for n, deps in remaining.items() if not deps]
        if not free:
            break
        for n in free:
            del remaining[n]
        for deps in remaining.values():
            deps.difference_update(free)
    for n in sorted(remaining):
        problems.append(f"stage {n!r} is part of a dependency cycle")
    return problems


_MANIFEST_RE = re.compile(r"#\s*gate-stage:\s*(?P<name>[A-Za-z0-9_-]+)")


def parse_manifest(text: str) -> List[Tuple[int, str]]:
    """``# gate-stage: <name>`` lines of a shim/CI file -> [(line, name)]."""
    out: List[Tuple[int, str]] = []
    for i, line in enumerate(text.splitlines(), start=1):
        m = _MANIFEST_RE.search(line)
        if m:
            out.append((i, m.group("name")))
    return out
