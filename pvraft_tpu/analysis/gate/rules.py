"""The GE rules: evidence/claims discipline, machine-checked.

Repo-level rules (one :class:`GateContext` per run, not per file) over
the declared evidence tables (``evidence.VALIDATORS``,
``stages.GATE_STAGES``) and the built :class:`EvidenceModel`. Findings
share the one Diagnostic type and the ``# graftlint: disable=GExxx --
reason`` pragma grammar with the other six engines; GE000 is the
model-build error diagnostic (unreadable doc, unparseable artifact).

Zero findings on the clean tree — real violations get fixed (the
deepcheck precedent), not pragma'd.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import glob as _glob
import json
import os
import re
from typing import Iterator, List, Tuple

from pvraft_tpu.analysis.engine import Diagnostic
from pvraft_tpu.analysis.gate.evidence import (
    EPHEMERAL_PATHS,
    ValidatorSpec,
    apply_unit,
    claim_matches,
    resolve_field,
)
from pvraft_tpu.analysis.gate.model import EvidenceModel, first_match
from pvraft_tpu.analysis.gate.stages import GateStage, stage_problems


@dataclasses.dataclass
class GateContext:
    model: EvidenceModel
    validators: Tuple[ValidatorSpec, ...]
    stages: Tuple[GateStage, ...]
    # Manifest paths the repo is EXPECTED to carry (a deleted shim must
    # not silently drop the GE005 identity check).
    expected_manifests: Tuple[str, ...] = ()


def _anchor_in(root: str, rel: str, needle: str) -> int:
    """First line of ``needle`` in a file (1 when absent/unreadable) —
    registry findings anchor at the declaring row, not the file top."""
    try:
        with open(os.path.join(root, rel), "r", encoding="utf-8") as fh:
            for i, line in enumerate(fh, start=1):
                if needle in line:
                    return i
    except OSError:
        pass
    return 1


_EVIDENCE_PY = "pvraft_tpu/analysis/gate/evidence.py"
_STAGES_PY = "pvraft_tpu/analysis/gate/stages.py"


class GateRule:
    id = "GE000"
    title = "gate-rule"

    def check(self, ctx: GateContext) -> Iterator[Diagnostic]:
        raise NotImplementedError


class DanglingEvidence(GateRule):
    """Cited evidence must exist; committed evidence must be indexed.

    Forward: every ``artifacts/...`` path cited in a claim doc must
    resolve — as an existing file/directory, a glob over the tracked
    set, or a declared-ephemeral subtree (caches and raw logs are
    citable as directories without existing on a fresh checkout).
    Reverse: every tracked ``artifacts/*`` file must be covered by an
    artifacts/README index row (the "numbers without an artifact don't
    count" ledger, enforced both ways).
    """

    id = "GE001"
    title = "dangling-evidence"

    def check(self, ctx: GateContext) -> Iterator[Diagnostic]:
        model = ctx.model
        tracked = set(model.tracked)
        for cite in model.citations:
            if self._resolves(model.root, cite.patterns, tracked):
                continue
            yield Diagnostic(
                cite.doc, cite.line, 0, self.id,
                f"cited evidence {cite.raw!r} matches no existing file "
                f"(tracked artifacts, on-disk paths and declared-ephemeral "
                f"subtrees all checked)",
            )
        if "artifacts/README.md" in model.docs:
            patterns = [p for _, p in model.index_patterns]
            for rel in model.tracked:
                base = rel[len("artifacts/"):]
                if any(
                    fnmatch.fnmatch(base, p)
                    or fnmatch.fnmatch(os.path.basename(base), p)
                    for p in patterns
                ):
                    continue
                yield Diagnostic(
                    "artifacts/README.md", 1, 0, self.id,
                    f"tracked artifact {rel!r} has no index row "
                    f"(every committed evidence file needs one)",
                )

    @staticmethod
    def _resolves(root: str, patterns, tracked) -> bool:
        for pattern in patterns:
            if any(
                pattern == e or pattern.startswith(e + "/")
                for e in EPHEMERAL_PATHS
            ):
                return True
            if "*" in pattern or "?" in pattern:
                if any(fnmatch.fnmatch(t, pattern) for t in tracked):
                    return True
                if sorted(_glob.glob(os.path.join(root, pattern))):
                    return True
            elif os.path.exists(os.path.join(root, pattern)):
                return True
        return False


class UnvalidatedArtifact(GateRule):
    """Every committed artifact is covered by a registered validator row.

    The silent-drift class: an artifact no gate stage validates can rot
    green forever. Coverage is first-match over ``VALIDATORS`` globs;
    pre-schema evidence is covered by explicit note rows naming the pin
    that replaces a validator (tests, generator gates).
    """

    id = "GE002"
    title = "unvalidated-artifact"

    def check(self, ctx: GateContext) -> Iterator[Diagnostic]:
        for rel in ctx.model.tracked:
            if first_match(rel, ctx.validators) is None:
                yield Diagnostic(
                    rel, 1, 0, self.id,
                    f"committed artifact {rel!r} is matched by no "
                    f"VALIDATORS glob — add a validator stage row, or a "
                    f"note row naming the pin that covers it",
                )


class StaleClaim(GateRule):
    """Annotated headline numbers must equal their artifact field.

    The ``<!-- claim: artifacts/x.json#dotted.path -->`` convention: the
    last numeric token before the comment is compared (at the prose's
    own printed precision) against the artifact field. A claim whose
    artifact is missing, whose field doesn't resolve, or whose number
    drifted is a finding — the machine-checked half of BENCHMARKS.md
    "Provenance".
    """

    id = "GE003"
    title = "stale-claim"

    def check(self, ctx: GateContext) -> Iterator[Diagnostic]:
        model = ctx.model
        cache: dict = {}
        for claim in model.claims:
            where = f"{claim.src}#{claim.field}" + (
                f"@{claim.unit}" if claim.unit else ""
            )
            path = os.path.join(model.root, claim.src)
            if claim.src not in cache:
                cache[claim.src] = self._load(path)
            doc_obj = cache[claim.src]
            if doc_obj is None:
                yield Diagnostic(
                    claim.doc, claim.line, 0, self.id,
                    f"claim {where} cites a missing or unparseable artifact",
                )
                continue
            found, value = resolve_field(doc_obj, claim.field)
            if not found:
                yield Diagnostic(
                    claim.doc, claim.line, 0, self.id,
                    f"claim {where}: field does not resolve in the artifact",
                )
                continue
            ok, value = apply_unit(value, claim.unit)
            if not ok:
                yield Diagnostic(
                    claim.doc, claim.line, 0, self.id,
                    f"claim {where}: unit {claim.unit!r} does not apply to "
                    f"the artifact value {value!r}",
                )
                continue
            if claim.quoted is None:
                yield Diagnostic(
                    claim.doc, claim.line, 0, self.id,
                    f"claim {where}: no numeric value precedes the claim "
                    f"comment on this line (artifact value: {value!r})",
                )
                continue
            if not claim_matches(claim.quoted, value):
                yield Diagnostic(
                    claim.doc, claim.line, 0, self.id,
                    f"stale claim {where}: prose says {claim.quoted!r}, "
                    f"artifact says {value!r}",
                )

    @staticmethod
    def _load(path: str):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                if path.endswith(".jsonl"):
                    first = fh.readline()
                    return json.loads(first) if first.strip() else None
                return json.load(fh)
        except (OSError, ValueError):
            return None


class SchemaExactlyOnce(GateRule):
    """Every ``pvraft_*/vN`` schema string has exactly one validator row.

    Duplicated ownership, an artifact whose ``schema`` field resolves to
    no registered validator, a first-match row whose declared schema
    disagrees with the artifact's own field, and a schema literal in
    package/scripts source the registry doesn't know are all findings —
    the schema namespace stays a closed, declared set.
    """

    id = "GE004"
    title = "schema-exactly-once"

    def check(self, ctx: GateContext) -> Iterator[Diagnostic]:
        root = ctx.model.root
        owners: dict = {}
        for spec in ctx.validators:
            if spec.schema:
                owners.setdefault(spec.schema, []).append(spec)
        for schema, specs in sorted(owners.items()):
            if len(specs) > 1:
                yield Diagnostic(
                    _EVIDENCE_PY, _anchor_in(root, _EVIDENCE_PY, schema),
                    0, self.id,
                    f"schema {schema!r} is declared by {len(specs)} "
                    f"VALIDATORS rows (exactly one owns a schema)",
                )
        known = set(owners)
        for rel, schema in sorted(ctx.model.artifact_schemas.items()):
            if schema is None:
                continue
            if schema not in known:
                yield Diagnostic(
                    rel, 1, 0, self.id,
                    f"artifact declares schema {schema!r} which resolves "
                    f"to no registered validator",
                )
                continue
            spec = first_match(rel, ctx.validators)
            if spec is not None and spec.schema and spec.schema != schema:
                yield Diagnostic(
                    rel, 1, 0, self.id,
                    f"artifact declares schema {schema!r} but its "
                    f"first-match validator row owns {spec.schema!r} "
                    f"(glob order routes it to the wrong validator)",
                )
        for path, line, schema in ctx.model.source_schemas:
            if schema not in known:
                yield Diagnostic(
                    path, line, 0, self.id,
                    f"schema literal {schema!r} is not declared by any "
                    f"VALIDATORS row",
                )


class StageCoverage(GateRule):
    """The gate stage set is declared exactly once, everywhere.

    The registry must be well-formed (unique names, resolving deps, no
    cycles), every ``stage=`` reference in VALIDATORS must name a
    declared stage, and the ``# gate-stage:`` manifests in the lint.sh
    shim and ci.yml must equal the registry's stage set both ways — so
    bash, CI and the declared data cannot drift apart.
    """

    id = "GE005"
    title = "stage-coverage"

    def check(self, ctx: GateContext) -> Iterator[Diagnostic]:
        root = ctx.model.root
        for problem in stage_problems(ctx.stages):
            m = re.search(r"'([^']+)'", problem)
            needle = f'name="{m.group(1)}"' if m else ""
            yield Diagnostic(
                _STAGES_PY,
                _anchor_in(root, _STAGES_PY, needle) if needle else 1,
                0, self.id, problem,
            )
        declared = {s.name for s in ctx.stages}
        for spec in ctx.validators:
            if spec.stage and spec.stage not in declared:
                yield Diagnostic(
                    _EVIDENCE_PY,
                    _anchor_in(root, _EVIDENCE_PY, f'stage="{spec.stage}"'),
                    0, self.id,
                    f"VALIDATORS row {spec.globs!r} names undeclared gate "
                    f"stage {spec.stage!r}",
                )
        for expected in ctx.expected_manifests:
            if expected not in ctx.model.manifests:
                yield Diagnostic(
                    expected, 1, 0, self.id,
                    f"expected gate-stage manifest {expected!r} is missing "
                    f"(the stage-set identity check cannot run without it)",
                )
        for mpath, entries in sorted(ctx.model.manifests.items()):
            named = {}
            for line, name in entries:
                if name in named:
                    yield Diagnostic(
                        mpath, line, 0, self.id,
                        f"manifest names stage {name!r} more than once",
                    )
                named.setdefault(name, line)
            for name, line in sorted(named.items()):
                if name not in declared:
                    yield Diagnostic(
                        mpath, line, 0, self.id,
                        f"manifest names stage {name!r} which the registry "
                        f"does not declare",
                    )
            for name in sorted(declared - set(named)):
                yield Diagnostic(
                    mpath, 1, 0, self.id,
                    f"registry stage {name!r} is missing from this "
                    f"gate-stage manifest",
                )


def all_gate_rules() -> List[type]:
    return [
        DanglingEvidence,
        UnvalidatedArtifact,
        StaleClaim,
        SchemaExactlyOnce,
        StageCoverage,
    ]
