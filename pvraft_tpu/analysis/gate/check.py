"""gatecheck driver: build the evidence model, run the GE rules.

Mirrors the other engines' check.py shape (``check_repo`` instead of
``check_paths`` — the evidence discipline is a repo-level property, not
a per-file one). Suppressions use the one shared pragma grammar; in
markdown docs a pragma rides inside an HTML comment
(``<!-- # graftlint: disable=GE003 -- reason -->``) on the finding's
line. The clean tree carries zero GE pragmas — findings get fixed.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pvraft_tpu.analysis.engine import Diagnostic, _parse_pragma, _suppressions
from pvraft_tpu.analysis.gate.evidence import CLAIM_DOCS, VALIDATORS
from pvraft_tpu.analysis.gate.model import (
    DEFAULT_MANIFESTS,
    EvidenceModel,
    build_evidence_model,
)
from pvraft_tpu.analysis.gate.rules import GateContext, all_gate_rules
from pvraft_tpu.analysis.gate.stages import GATE_STAGES


def _file_suppressions(path: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """(per-line ids, file-level ids) for any text file.

    Python files get the real tokenizer treatment (docstring examples
    never suppress); other files are scanned line-wise for the pragma —
    in markdown that means inside an HTML comment.
    """
    try:
        with open(path, "r", encoding="utf-8-sig") as fh:
            source = fh.read()
    except OSError:
        return {}, set()
    if path.endswith(".py"):
        per_line, file_ids = _suppressions(source)
        return {k: set(v) for k, v in per_line.items()}, set(file_ids)
    per_line: Dict[int, Set[str]] = {}
    file_ids: Set[str] = set()
    for i, line in enumerate(source.splitlines(), start=1):
        parsed = _parse_pragma(line)
        if parsed is None:
            continue
        kind, ids, _reason = parsed
        if kind == "file":
            file_ids.update(ids)
        elif kind == "next":
            per_line.setdefault(i + 1, set()).update(ids)
        else:
            per_line.setdefault(i, set()).update(ids)
    return per_line, file_ids


def _apply_suppressions(
    diags: List[Diagnostic], root: str
) -> List[Diagnostic]:
    cache: Dict[str, Tuple[Dict[int, Set[str]], Set[str]]] = {}
    out: List[Diagnostic] = []
    for d in diags:
        if d.path not in cache:
            cache[d.path] = _file_suppressions(os.path.join(root, d.path))
        per_line, file_ids = cache[d.path]
        if "all" in file_ids or d.rule_id in file_ids:
            continue
        ids = per_line.get(d.line, set())
        if "all" in ids or d.rule_id in ids:
            continue
        out.append(d)
    return out


def check_repo(
    root: Optional[str] = None,
    rule_ids: Sequence[str] = (),
    validators=VALIDATORS,
    stages=GATE_STAGES,
    docs: Sequence[str] = CLAIM_DOCS,
    manifest_paths: Sequence[str] = DEFAULT_MANIFESTS,
    expected_manifests: Optional[Sequence[str]] = None,
    use_git: bool = True,
) -> Tuple[List[Diagnostic], EvidenceModel]:
    """Run the GE rules over a repo tree.

    ``expected_manifests`` defaults to ``manifest_paths`` — a missing
    shim/CI manifest is a GE005 finding, not a silent skip. Fixture
    tests pass their own tables and ``use_git=False`` (fixture trees are
    subtrees of this repo, not repos of their own).
    """
    root = os.path.abspath(root or os.getcwd())
    model = build_evidence_model(
        root, docs=docs, manifest_paths=manifest_paths, use_git=use_git
    )
    if expected_manifests is None:
        expected_manifests = manifest_paths
    ctx = GateContext(
        model=model,
        validators=tuple(validators),
        stages=tuple(stages),
        expected_manifests=tuple(expected_manifests),
    )
    diags: List[Diagnostic] = [
        Diagnostic(path, line, 0, "GE000", msg)
        for path, line, msg in model.errors
    ]
    for rule_cls in all_gate_rules():
        if rule_ids and rule_cls.id not in rule_ids:
            continue
        diags.extend(rule_cls().check(ctx))
    if rule_ids:
        diags = [d for d in diags if d.rule_id in rule_ids or d.rule_id == "GE000"]
    diags = _apply_suppressions(diags, root)
    diags.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id, d.message))
    return diags, model
