"""gatecheck: evidence/claims static analysis + the declared gate runner.

The SEVENTH analysis engine. Two halves:

* The GE rules (``rules.py``, driven by ``check.py``) machine-check the
  repo's evidence discipline: every cited artifact path exists and every
  committed artifact is indexed (GE001), every committed artifact is
  covered by a registered validator (GE002), every annotated headline
  number still equals its artifact field (GE003, the
  ``<!-- claim: artifacts/x.json#dotted.path -->`` convention), every
  ``pvraft_*/v1`` schema string resolves to exactly one registered
  validator (GE004), and the gate stage set is declared exactly once and
  identical across the registry, ``scripts/lint.sh`` and CI (GE005).

* The gate RUNNER (``stages.py`` + ``runner.py``): the old lint.sh bash
  stage list as declared :class:`GateStage` data, executed by
  ``python -m pvraft_tpu.analysis gate`` with a dependency-aware
  parallel scheduler, content-hash caching over each stage's input
  files, ``--changed-only`` for local dev, per-stage timing and a
  validated ``pvraft_gate/v1`` report.
"""

from pvraft_tpu.analysis.gate.evidence import (  # noqa: F401
    CLAIM_DOCS,
    EPHEMERAL_PATHS,
    VALIDATORS,
    ValidatorSpec,
)
from pvraft_tpu.analysis.gate.stages import (  # noqa: F401
    GATE_STAGES,
    GateStage,
    parse_manifest,
    stage_names,
    stage_problems,
)
