"""The gate runner: execute the declared stage registry.

Dependency-aware parallel scheduler over ``stages.GATE_STAGES`` with
content-hash caching: a stage whose command, environment pins and input
file contents are unchanged since its last green run is recorded as
``cached`` and skipped. ``--changed-only`` additionally skips stages
whose input globs intersect no file changed vs git HEAD (local dev
loop). Every run emits a validated ``pvraft_gate/v1`` report with
per-stage timing; the committed ``artifacts/gate_cold.json`` /
``gate_warm.json`` snapshots BENCHMARKS.md cites are checked by
``check_report_file`` (full run, every stage ok or cached, stage set
identical to the registry, and per-stage ``input_hash``/``n_inputs``
provenance present — a synthesized report fails).

Timings are wall-clock records of a real run — never regenerate-and-
compared (they are not reproducible functions of the tree).
"""

from __future__ import annotations

import concurrent.futures
import glob as _glob
import hashlib
import json
import os
import re
import subprocess
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pvraft_tpu.analysis.gate.stages import GATE_STAGES, GateStage, stage_problems

SCHEMA_VERSION = "pvraft_gate/v1"
CACHE_DIR = ".gate_cache"
CACHE_FILE = "cache.json"
_STATUSES = ("ok", "cached", "failed", "skipped")

# The one skip reason that SATISFIES dependents: --changed-only found no
# changed input, so the stage's previous green result still stands (like
# "cached"). Every other skip means the dependency never went green.
_CHANGED_ONLY_SKIP = "no changed input (vs git HEAD)"

# Pruned from input-glob expansion: ephemeral caches would churn the
# content hash (costs-smoke writes xla_cache) without being evidence.
_PRUNE_PARTS = ("/artifacts/xla_cache/", "/__pycache__/", "/.gate_cache/")


def expand_inputs(root: str, patterns: Sequence[str]) -> List[str]:
    """Input globs -> sorted repo-relative file list (ephemeral pruned)."""
    out: Set[str] = set()
    for pattern in patterns:
        for hit in sorted(_glob.glob(os.path.join(root, pattern), recursive=True)):
            if not os.path.isfile(hit):
                continue
            probe = hit.replace(os.sep, "/")
            if any(part in "/" + probe + "/" for part in _PRUNE_PARTS):
                continue
            out.add(os.path.relpath(hit, root).replace(os.sep, "/"))
    return sorted(out)


def _matches_any(rel: str, patterns: Sequence[str]) -> bool:
    import fnmatch

    for pattern in patterns:
        if fnmatch.fnmatch(rel, pattern):
            return True
        # glob's ``**/`` may match zero directories; fnmatch's cannot.
        if "**/" in pattern and fnmatch.fnmatch(rel, pattern.replace("**/", "")):
            return True
    return False


def _file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def stage_cache_key(
    root: str,
    stage: GateStage,
    files: Sequence[str],
    digest_cache: Optional[Dict[str, str]] = None,
) -> str:
    """Content hash of everything a stage's verdict is a function of.

    ``digest_cache`` memoizes per-file digests across stages within one
    run — the package globs overlap heavily, and hashing each file once
    instead of once per stage is pure savings (files are not expected to
    change mid-run; the cache is per-run, never persisted).
    """
    h = hashlib.sha256()
    h.update(stage.command.encode())
    h.update(repr(sorted(stage.env)).encode())
    h.update(str(stage.virtual_devices).encode())
    for rel in files:
        h.update(rel.encode())
        digest = digest_cache.get(rel) if digest_cache is not None else None
        if digest is None:
            try:
                digest = _file_digest(os.path.join(root, rel))
            except OSError:
                digest = "<unreadable>"
            if digest_cache is not None:
                digest_cache[rel] = digest
        h.update(digest.encode())
    return h.hexdigest()


def _stage_environ(stage: GateStage) -> Dict[str, str]:
    env = dict(os.environ)
    env.update(dict(stage.env))
    if stage.virtual_devices:
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{stage.virtual_devices}"
        ).strip()
    return env


def _changed_files(root: str) -> Optional[Set[str]]:
    """Files changed vs HEAD (tracked diffs + untracked), or None when
    git is unavailable — the caller then treats everything as changed."""
    changed: Set[str] = set()
    for args in (
        ["git", "-C", root, "diff", "--name-only", "HEAD"],
        ["git", "-C", root, "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            out = subprocess.run(
                args, capture_output=True, text=True, timeout=30, check=False
            )
        except OSError:
            return None
        if out.returncode != 0:
            return None
        changed.update(l.strip() for l in out.stdout.splitlines() if l.strip())
    return changed


def _load_cache(root: str) -> Dict[str, str]:
    path = os.path.join(root, CACHE_DIR, CACHE_FILE)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict):
        return {}
    return {str(k): str(v) for k, v in doc.get("stages", {}).items()}


def _save_cache(root: str, cache: Dict[str, str]) -> None:
    cache_dir = os.path.join(root, CACHE_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, CACHE_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"schema": SCHEMA_VERSION, "stages": cache}, fh, indent=1)
    os.replace(tmp, path)


def _dep_satisfied(record: dict) -> bool:
    """Does a completed dependency unblock its dependents?

    ok/cached do; a --changed-only skip also does (nothing the dep
    watches changed, so its last green result is still in force). A
    failed dep, or one skipped because its OWN dependency was not
    green, does not.
    """
    if record["status"] in ("ok", "cached"):
        return True
    return (
        record["status"] == "skipped"
        and record.get("reason") == _CHANGED_ONLY_SKIP
    )


def run_gate(
    root: Optional[str] = None,
    stages: Sequence[GateStage] = GATE_STAGES,
    only: Sequence[str] = (),
    jobs: Optional[int] = None,
    use_cache: bool = True,
    changed_only: bool = False,
    verbose: bool = False,
    echo=print,
) -> dict:
    """Execute the gate; returns the ``pvraft_gate/v1`` report dict.

    Scheduling: every stage whose deps have completed satisfied
    (ok/cached, or skipped under --changed-only with no changed input —
    the previous green result stands) is eligible; eligible stages run
    concurrently up to ``jobs``. A failed or dep-cascade-skipped
    dependency skips its dependents (recorded, never silently dropped).
    Output of each stage is buffered and echoed serialized on
    completion, so parallel stages cannot interleave.
    """
    root = os.path.abspath(root or os.getcwd())
    problems = stage_problems(tuple(stages))
    if problems:
        raise ValueError("; ".join(problems))
    if only:
        wanted = set(only)
        unknown = wanted - {s.name for s in stages}
        if unknown:
            raise ValueError(f"unknown stage(s): {sorted(unknown)}")
        # Keep declared order; deps outside the selection are not run
        # (the caller asked for exactly these stages).
        stages = [s for s in stages if s.name in wanted]
    if jobs is None:
        jobs = max(2, min(4, os.cpu_count() or 1))

    cache = _load_cache(root) if use_cache else {}
    changed = _changed_files(root) if changed_only else None
    digest_cache: Dict[str, str] = {}
    by_name = {s.name: s for s in stages}
    selected = {s.name for s in stages}
    done: Dict[str, dict] = {}
    lock = threading.Lock()
    new_cache = dict(cache)
    t0 = time.monotonic()

    def run_one(stage: GateStage) -> dict:
        files = expand_inputs(root, stage.inputs)
        record = {
            "name": stage.name,
            "status": "ok",
            "duration_s": 0.0,
            "n_inputs": len(files),
            "deps": list(stage.deps),
            "command": stage.command,
        }
        if changed_only and changed is not None:
            touched = [
                c for c in changed
                if _matches_any(c, stage.inputs) or c in files
            ]
            if not touched:
                record["status"] = "skipped"
                record["reason"] = _CHANGED_ONLY_SKIP
                return record
        with lock:
            key = stage_cache_key(root, stage, files, digest_cache)
        record["input_hash"] = key[:16]
        if use_cache and cache.get(stage.name) == key:
            record["status"] = "cached"
            return record
        start = time.monotonic()
        proc = subprocess.run(
            ["bash", "-c", stage.command],
            cwd=root,
            env=_stage_environ(stage),
            capture_output=True,
            text=True,
        )
        record["duration_s"] = round(time.monotonic() - start, 3)
        record["output"] = proc.stdout[-20000:] + (
            ("\n[stderr]\n" + proc.stderr[-20000:]) if proc.stderr.strip() else ""
        )
        if proc.returncode == 0:
            record["status"] = "ok"
            with lock:
                new_cache[stage.name] = key
        else:
            record["status"] = "failed"
            record["returncode"] = proc.returncode
        return record

    def report_done(record: dict) -> None:
        name, status = record["name"], record["status"]
        dur = record["duration_s"]
        mark = {"ok": "ok", "cached": "cached", "failed": "FAILED",
                "skipped": "skipped"}[status]
        line = f"[gate] {name:<22} {mark:<8} {dur:8.1f}s"
        if record.get("reason"):
            line += f"  ({record['reason']})"
        echo(line)
        output = record.pop("output", "")
        if output and (status == "failed" or verbose):
            for out_line in output.splitlines():
                echo(f"    {out_line}")

    pending = [by_name[n] for n in by_name]
    futures = {}
    with concurrent.futures.ThreadPoolExecutor(max_workers=jobs) as pool:
        while pending or futures:
            progressed = False
            for stage in list(pending):
                deps = [d for d in stage.deps if d in selected]
                if any(d not in done for d in deps):
                    continue
                bad = [d for d in deps if not _dep_satisfied(done[d])]
                pending.remove(stage)
                progressed = True
                if bad:
                    record = {
                        "name": stage.name, "status": "skipped",
                        "duration_s": 0.0, "n_inputs": 0,
                        "deps": list(stage.deps), "command": stage.command,
                        "reason": f"dependency not green: {', '.join(bad)}",
                    }
                    done[stage.name] = record
                    report_done(record)
                else:
                    futures[pool.submit(run_one, stage)] = stage.name
            if not futures:
                if not progressed and pending:
                    raise RuntimeError("scheduler stalled (dependency cycle?)")
                continue
            finished, _ = concurrent.futures.wait(
                futures, return_when=concurrent.futures.FIRST_COMPLETED
            )
            for fut in finished:
                name = futures.pop(fut)
                record = fut.result()
                done[name] = record
                report_done(record)

    total = round(time.monotonic() - t0, 3)
    if use_cache:
        # Failed stages drop out of the cache so a re-run retries them.
        for name, record in done.items():
            if record["status"] == "failed":
                new_cache.pop(name, None)
        _save_cache(root, new_cache)

    records = [done[s.name] for s in stages]
    counts = {status: 0 for status in _STATUSES}
    for record in records:
        counts[record["status"]] += 1
    report = {
        "schema": SCHEMA_VERSION,
        "jobs": jobs,
        "changed_only": changed_only,
        "only": sorted(only) if only else [],
        "stages": records,
        "counts": counts,
        "total_s": total,
        "ok": counts["failed"] == 0,
    }
    return report


# --- pvraft_gate/v1 validation ---------------------------------------------

def validate_gate_report(doc: dict) -> List[str]:
    """Structural problems of a gate report ([] = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["report is not an object"]
    for key in ("schema", "jobs", "changed_only", "stages", "counts",
                "total_s", "ok"):
        if key not in doc:
            problems.append(f"missing key {key!r}")
    if problems:
        return problems
    if doc["schema"] != SCHEMA_VERSION:
        problems.append(f"schema {doc['schema']!r} != {SCHEMA_VERSION!r}")
    names = []
    max_duration = 0.0
    counts = {status: 0 for status in _STATUSES}
    for record in doc["stages"]:
        name = record.get("name")
        names.append(name)
        status = record.get("status")
        if status not in _STATUSES:
            problems.append(f"stage {name!r}: invalid status {status!r}")
            continue
        counts[status] += 1
        dur = record.get("duration_s")
        if not isinstance(dur, (int, float)) or dur < 0:
            problems.append(f"stage {name!r}: bad duration_s {dur!r}")
        else:
            max_duration = max(max_duration, float(dur))
        deps = record.get("deps")
        if not isinstance(deps, list):
            problems.append(f"stage {name!r}: deps must be a list")
    if len(set(names)) != len(names):
        problems.append("duplicate stage names in report")
    if doc["counts"] != counts:
        problems.append(
            f"counts {doc['counts']!r} do not recompute from the stage "
            f"rows ({counts!r})"
        )
    if doc["ok"] != (counts["failed"] == 0):
        problems.append("ok flag disagrees with the failure count")
    total = doc["total_s"]
    if not isinstance(total, (int, float)) or total < 0:
        problems.append(f"bad total_s {total!r}")
    elif total + 0.5 < max_duration:
        problems.append(
            f"total_s {total} is less than the longest stage "
            f"({max_duration}) — wall clock cannot beat its parts"
        )
    return problems


def check_report_file(
    path: str, stages: Sequence[GateStage] = GATE_STAGES
) -> List[str]:
    """Committed-report discipline on top of the structural validation.

    A committed snapshot must be a FULL, green run the shipped runner
    actually produced: not --changed-only, no stage selection, every
    stage ok or cached, the stage set identical to the current registry
    (a report from a different stage era may not back today's claims),
    and every ok/cached record carrying the provenance the runner
    always writes — ``input_hash`` and a positive ``n_inputs`` (every
    registry stage hashes real input files before any cache decision),
    with a positive overall ``total_s`` (even a fully cached run spends
    wall clock hashing its inputs). A synthesized report that skips the
    work fails here instead of backing a timing claim.
    """
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as exc:
        return [f"unreadable report ({exc})"]
    problems = validate_gate_report(doc)
    if problems:
        return problems
    if doc["changed_only"]:
        problems.append("committed report is a --changed-only run")
    if doc.get("only"):
        problems.append("committed report ran a stage selection, not the gate")
    if not (isinstance(doc["total_s"], (int, float)) and doc["total_s"] > 0):
        problems.append(
            f"committed report has total_s {doc['total_s']!r} — a real run "
            f"spends wall clock even when every stage is cached"
        )
    for record in doc["stages"]:
        if record["status"] not in ("ok", "cached"):
            problems.append(
                f"stage {record['name']!r} is {record['status']!r} "
                f"(committed reports must be green)"
            )
            continue
        n_inputs = record.get("n_inputs")
        if not (isinstance(n_inputs, int) and not isinstance(n_inputs, bool)
                and n_inputs > 0):
            problems.append(
                f"stage {record['name']!r}: n_inputs {n_inputs!r} — the "
                f"runner records the expanded input count for every "
                f"ok/cached stage, and no registry stage has zero inputs"
            )
        input_hash = record.get("input_hash")
        if not (isinstance(input_hash, str)
                and re.fullmatch(r"[0-9a-f]{16,64}", input_hash)):
            problems.append(
                f"stage {record['name']!r}: missing or malformed "
                f"input_hash {input_hash!r} — the runner hashes a stage's "
                f"inputs before any cache decision"
            )
    report_set = {record["name"] for record in doc["stages"]}
    registry_set = {s.name for s in stages}
    for name in sorted(registry_set - report_set):
        problems.append(f"registry stage {name!r} missing from the report")
    for name in sorted(report_set - registry_set):
        problems.append(f"report stage {name!r} is not in the registry")
    return problems
