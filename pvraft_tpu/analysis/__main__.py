"""CLI: ``python -m pvraft_tpu.analysis {lint,trace} ...``.

``lint`` is pure stdlib-AST and never initializes a jax backend.
``trace`` imports jax and abstractly traces every registered op with
``jax.eval_shape`` (zero FLOPs — shape propagation only), reporting any
concretization / shape errors a TPU run would hit at compile time.
"""

from __future__ import annotations

import argparse
import sys


def _cmd_lint(args) -> int:
    from pvraft_tpu.analysis.engine import all_rules, lint_paths

    if args.list_rules:
        for rule in all_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id}  {rule.title:<26} {doc}")
        return 0
    if not args.paths:
        print("usage: python -m pvraft_tpu.analysis lint PATH [PATH ...]",
              file=sys.stderr)
        return 2
    select = tuple(args.select.split(",")) if args.select else ()
    diags, nfiles = lint_paths(args.paths, rule_ids=select)
    for d in diags:
        print(d.format())
    summary = f"graftlint: {len(diags)} finding(s) in {nfiles} file(s)"
    print(summary, file=sys.stderr)
    return 1 if diags else 0


def _cmd_trace(args) -> int:
    from pvraft_tpu.analysis.audit import run_audit

    results = run_audit(verbose=True)
    bad = [r for r in results if not r.ok]
    print(
        f"trace-compat audit: {len(results) - len(bad)}/{len(results)} "
        "op(s) trace clean", file=sys.stderr,
    )
    return 1 if bad else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pvraft_tpu.analysis",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_lint = sub.add_parser("lint", help="run the AST lint rules")
    p_lint.add_argument("paths", nargs="*", help="files/directories to lint")
    p_lint.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    p_lint.add_argument("--select", default="",
                        help="comma-separated rule ids to run (default all)")
    p_lint.set_defaults(fn=_cmd_lint)

    p_trace = sub.add_parser(
        "trace", help="eval_shape trace-compat audit of registered ops"
    )
    p_trace.set_defaults(fn=_cmd_trace)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
