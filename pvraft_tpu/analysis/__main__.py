"""CLI: ``python -m pvraft_tpu.analysis
{lint,trace,deepcheck,concurrency,kernels,sharding,determinism,gate}``.

``lint`` is pure stdlib-AST and never initializes a jax backend
(``--stats`` prints the suppression-debt report instead of findings).
``trace`` imports jax and abstractly traces every registered op with
``jax.eval_shape`` (zero FLOPs — shape propagation only), reporting any
concretization / shape errors a TPU run would hit at compile time.
``deepcheck`` traces the same registry to ClosedJaxprs and runs the
GJ001+ semantic rules: collective consistency, donation efficacy,
precision flow, retrace hazards.
``concurrency`` (threadcheck) runs the GC001+ rules — guarded-by
discipline, lock-order cycles, check-then-act/TOCTOU shapes, un-joined
threads — over the hand-threaded planes (default scope ``serve/``,
``obs/``, ``data/loader.py``); pure stdlib-AST like ``lint``.
``kernels`` (kernelcheck) runs the GK001+ rules — tile alignment, VMEM
budget, grid coverage, Mosaic lowering hazards, registry coverage,
interpreter escape hatch — over the Pallas plane (``ops/pallas/``);
``--plan`` joins the static models with the committed cost inventory
into the ``pvraft_kernel_plan/v1`` artifact (fused-GRU VMEM residency,
roofline verdicts, static-vs-Mosaic cross-validation).
``sharding`` (shardcheck) runs the GS001+ rules — partition-rule
coverage, mesh-axis discipline, host-materialized sharded batches,
unguarded process-0 I/O, batch-contract confusion — over the
multi-process planes (engine/obs/parallel/programs/models/ops/data);
``--plan`` joins the partition rules, the committed param-tree
inventory and the cost inventory into ``pvraft_pod_plan/v1``
(per-device memory + ring comms verdicts per candidate (dp, sp) mesh).
``determinism`` (detcheck) runs the GD001+ rules — jax PRNG key
reuse/consumed-without-split, host RNG or time-derived seeds outside
the ``rng.derive`` stream contract, nondeterminism-hazard ops on
programs without a ``determinism=`` declaration, backend determinism
flags routed outside ``compat.py``, iteration-order hazards
(set/unsorted-glob ordering feeding traces or checkpoints) — over the
whole package; ``--replay`` builds the registered train step and serve
dispatch twice from the same seed, diffs outputs bitwise, and emits
the ``pvraft_determinism/v1`` artifact (``--check`` pins it).
``gate`` (gatecheck) is two things: with no flags it RUNS the declared
gate — the old lint.sh stage list as ``GateStage`` data, scheduled
dependency-aware in parallel with content-hash caching and a
``pvraft_gate/v1`` report — and with ``--rules`` it runs the GE001+
evidence/claims rules (dangling citations, validator coverage, stale
``<!-- claim: -->`` numbers, schema-exactly-once, stage-set identity
across registry/lint.sh/ci.yml); ``--check`` validates committed gate
reports. Pure stdlib either way (the stages it launches are their own
processes).
"""

from __future__ import annotations

import argparse
import sys


def _cmd_lint(args) -> int:
    from pvraft_tpu.analysis.engine import all_rules, lint_paths

    if args.list_rules:
        for rule in all_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id}  {rule.title:<26} {doc}")
        return 0
    if not args.paths:
        print("usage: python -m pvraft_tpu.analysis lint PATH [PATH ...]",
              file=sys.stderr)
        return 2
    if args.stats:
        return _lint_stats(args.paths)
    select = tuple(args.select.split(",")) if args.select else ()
    diags, nfiles = lint_paths(args.paths, rule_ids=select)
    for d in diags:
        print(d.format())
    summary = f"graftlint: {len(diags)} finding(s) in {nfiles} file(s)"
    print(summary, file=sys.stderr)
    return 1 if diags else 0


def _lint_stats(paths) -> int:
    """Suppression-debt report: what the gate is NOT checking, per rule.

    Exit 1 on any reason-less suppression — a blind spot nobody can
    audit is debt, not configuration."""
    from pvraft_tpu.analysis.engine import (
        collect_suppressions,
        known_rule_ids,
    )

    pragmas = collect_suppressions(paths)
    known = known_rule_ids()
    per_rule: dict = {}
    reasonless = []
    unknown = []
    for p in pragmas:
        for rid in p.ids:
            stats = per_rule.setdefault(
                rid, {"line": 0, "next": 0, "file": 0, "reasonless": 0})
            stats[p.kind] += 1
            if not p.reason:
                stats["reasonless"] += 1
            if rid != "all" and rid not in known:
                unknown.append((p, rid))
        if not p.reason:
            reasonless.append(p)
    for rid in sorted(per_rule):
        s = per_rule[rid]
        total = s["line"] + s["next"] + s["file"]
        print(f"{rid:<7} {total:>3} suppression(s)  "
              f"(line={s['line']} next={s['next']} file={s['file']}, "
              f"{s['reasonless']} without reason)")
    for p, rid in unknown:
        print(f"{p.path}:{p.line}: warning: suppression names unknown "
              f"rule {rid}")
    for p in reasonless:
        print(f"{p.path}:{p.line}: reason-less suppression of "
              f"{','.join(p.ids)} (append `-- why`)")
    print(
        f"graftlint --stats: {len(pragmas)} active pragma(s), "
        f"{len(reasonless)} without reason", file=sys.stderr,
    )
    return 1 if reasonless else 0


def _cmd_trace(args) -> int:
    from pvraft_tpu.analysis.audit import run_audit

    results = run_audit(verbose=True)
    bad = [r for r in results if not r.ok]
    print(
        f"trace-compat audit: {len(results) - len(bad)}/{len(results)} "
        "op(s) trace clean", file=sys.stderr,
    )
    return 1 if bad else 0


def _cmd_deepcheck(args) -> int:
    from pvraft_tpu.analysis.jaxpr import (
        all_jaxpr_rules,
        format_report,
        run_deepcheck,
        summary_line,
    )

    if args.list_rules:
        for rule in all_jaxpr_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id}  {rule.title:<28} {doc}")
        return 0
    select = tuple(args.select.split(",")) if args.select else ()
    report = run_deepcheck(select_rules=select,
                           entry_filter=tuple(args.entries))
    body = format_report(report, verbose=args.verbose)
    if body:
        print(body)
    print(summary_line(report), file=sys.stderr)
    return 0 if report.ok else 1


def _cmd_concurrency(args) -> int:
    from pvraft_tpu.analysis.concurrency.check import (
        check_paths,
        default_scope,
    )
    from pvraft_tpu.analysis.concurrency.rules import all_concurrency_rules

    if args.list_rules:
        for rule in all_concurrency_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id}  {rule.title:<28} {doc}")
        return 0
    paths = args.paths or list(default_scope())
    select = tuple(args.select.split(",")) if args.select else ()
    diags, nfiles = check_paths(paths, rule_ids=select)
    for d in diags:
        print(d.format())
    print(f"threadcheck: {len(diags)} finding(s) in {nfiles} file(s)",
          file=sys.stderr)
    return 1 if diags else 0


def _cmd_kernels(args) -> int:
    from pvraft_tpu.analysis.kernels.check import check_paths, default_scope
    from pvraft_tpu.analysis.kernels.rules import all_kernel_rules

    if args.list_rules:
        for rule in all_kernel_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id}  {rule.title:<28} {doc}")
        return 0
    if args.plan or args.check:
        return _kernels_plan(args)
    paths = args.paths or list(default_scope())
    select = tuple(args.select.split(",")) if args.select else ()
    diags, notes, nfiles = check_paths(paths, rule_ids=select)
    for d in diags:
        print(d.format())
    for d in notes:
        print(f"note: {d.format()}")
    print(f"kernelcheck: {len(diags)} finding(s), {len(notes)} layout "
          f"note(s) in {nfiles} file(s)", file=sys.stderr)
    return 1 if diags else 0


def _kernels_plan(args) -> int:
    """Build (or --check) the pvraft_kernel_plan/v1 artifact: static
    kernel models joined with the committed cost inventory. Exit 1 on
    any plan problem — a failed static-vs-Mosaic cross-validation, a
    kernel-tag spec with no cost record, or (with --check) a committed
    plan that drifted from the regenerated one."""
    import json

    from pvraft_tpu.analysis.kernels.planner import (
        build_plan,
        check_plan_file,
        write_plan,
    )

    if args.check:
        problems = check_plan_file(args.check, args.costs)
        for p in problems:
            print(p, file=sys.stderr)
        if not problems:
            print(f"{args.check}: OK (matches the plan regenerated from "
                  f"{args.costs})")
        return 1 if problems else 0
    try:
        plan = build_plan(args.costs, paths=args.paths or None)
    except (OSError, ValueError) as e:
        print(f"kernels --plan: {e}", file=sys.stderr)
        return 1
    if args.out:
        write_plan(plan, args.out)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(json.dumps(plan, indent=1, sort_keys=True))
    for rec in plan["fused_gru_residency"]:
        print(f"[residency] N={rec['n_points']} K={rec['truncate_k']}: "
              f"{rec['verdict']}", file=sys.stderr)
    return 0


def _cmd_sharding(args) -> int:
    from pvraft_tpu.analysis.sharding.check import check_paths, default_scope
    from pvraft_tpu.analysis.sharding.rules import all_sharding_rules

    if args.list_rules:
        for rule in all_sharding_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id}  {rule.title:<28} {doc}")
        return 0
    if args.plan or args.check:
        return _sharding_plan(args)
    paths = args.paths or list(default_scope())
    select = tuple(args.select.split(",")) if args.select else ()
    diags, nfiles = check_paths(paths, rule_ids=select)
    for d in diags:
        print(d.format())
    print(f"shardcheck: {len(diags)} finding(s) in {nfiles} file(s)",
          file=sys.stderr)
    return 1 if diags else 0


def _sharding_plan(args) -> int:
    """Build (or --check) the pvraft_pod_plan/v1 artifact: partition
    rules x committed inventories x candidate meshes. Exit 1 on any
    plan problem — shardcheck findings, a failed sharded-step
    cross-check, or (with --check) committed-plan drift."""
    import json

    from pvraft_tpu.analysis.sharding.planner import (
        build_plan,
        check_plan_file,
        write_plan,
    )

    if args.check:
        problems = check_plan_file(args.check, args.costs, args.params)
        for p in problems:
            print(p, file=sys.stderr)
        if not problems:
            print(f"{args.check}: OK (matches the plan regenerated from "
                  f"{args.costs} + {args.params})")
        return 1 if problems else 0
    try:
        plan = build_plan(args.costs, args.params)
    except (OSError, ValueError) as e:
        print(f"sharding --plan: {e}", file=sys.stderr)
        return 1
    if args.out:
        write_plan(plan, args.out)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(json.dumps(plan, indent=1, sort_keys=True))
    for n_points, verdict in sorted(plan["scene_verdicts"].items(),
                                    key=lambda kv: int(kv[0])):
        print(f"[pod] {n_points} points: {verdict}", file=sys.stderr)
    return 0


def _cmd_determinism(args) -> int:
    from pvraft_tpu.analysis.determinism.check import (
        check_paths,
        default_scope,
    )
    from pvraft_tpu.analysis.determinism.rules import all_determinism_rules

    if args.list_rules:
        for rule in all_determinism_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id}  {rule.title:<28} {doc}")
        return 0
    if args.replay or args.check:
        return _determinism_replay(args)
    paths = args.paths or list(default_scope())
    select = tuple(args.select.split(",")) if args.select else ()
    diags, nfiles = check_paths(paths, rule_ids=select)
    for d in diags:
        print(d.format())
    print(f"detcheck: {len(diags)} finding(s) in {nfiles} file(s)",
          file=sys.stderr)
    return 1 if diags else 0


def _determinism_replay(args) -> int:
    """Build (or --check) the pvraft_determinism/v1 artifact: the
    registered train step and serve dispatch run twice from the same
    config seed, outputs diffed bitwise. Exit 1 on any divergence or
    (with --check) committed-report drift."""
    import json

    from pvraft_tpu.analysis.determinism.replay import (
        check_report,
        replay_report,
        write_report,
    )

    if args.check:
        problems = check_report(args.check)
        for p in problems:
            print(p, file=sys.stderr)
        if not problems:
            print(f"{args.check}: OK (replay is bitwise and matches the "
                  f"committed report)")
        return 1 if problems else 0
    report = replay_report(seed=args.seed)
    if args.out:
        write_report(args.out, report)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(json.dumps(report, indent=2, sort_keys=True))
    for e in report["programs"]:
        tag = "bitwise" if e["bitwise_identical"] else "DIVERGENT"
        print(f"[replay] {e['name']}: {tag} "
              f"({e['n_output_leaves']} leaves, {e['digest'][:16]})",
              file=sys.stderr)
    return 0 if report["verdict"] == "bitwise" else 1


def _cmd_gate(args) -> int:
    from pvraft_tpu.analysis.gate.rules import all_gate_rules

    if args.list_rules:
        for rule in all_gate_rules():
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.id}  {rule.title:<24} {doc}")
        return 0
    if args.list_stages:
        from pvraft_tpu.analysis.gate.stages import GATE_STAGES

        for stage in GATE_STAGES:
            deps = f"  (after {', '.join(stage.deps)})" if stage.deps else ""
            print(f"{stage.name:<22} {stage.command}{deps}")
        return 0
    if args.check:
        from pvraft_tpu.analysis.gate.runner import check_report_file

        rc = 0
        for path in args.check:
            problems = check_report_file(path)
            for p in problems:
                print(f"{path}: {p}", file=sys.stderr)
            if problems:
                rc = 1
            else:
                print(f"{path}: OK (full green gate run, stage set matches "
                      f"the registry)")
        return rc
    if args.rules:
        from pvraft_tpu.analysis.gate.check import check_repo

        select = tuple(args.select.split(",")) if args.select else ()
        diags, model = check_repo(root=args.root or None, rule_ids=select)
        for d in diags:
            print(d.format())
        print(
            f"gatecheck: {len(diags)} finding(s) over "
            f"{len(model.tracked)} tracked artifact(s), "
            f"{len(model.claims)} claim(s), {len(model.citations)} "
            f"citation(s)",
            file=sys.stderr,
        )
        return 1 if diags else 0

    from pvraft_tpu.analysis.gate.runner import run_gate, validate_gate_report

    try:
        report = run_gate(
            root=args.root or None,
            only=tuple(args.only),
            jobs=args.jobs,
            use_cache=not args.no_cache,
            changed_only=args.changed_only,
            verbose=args.verbose,
        )
    except (ValueError, RuntimeError) as exc:
        print(f"gate: {exc}", file=sys.stderr)
        return 2
    problems = validate_gate_report(report)
    for p in problems:  # pragma: no cover - the runner emits valid reports
        print(f"gate: report invalid: {p}", file=sys.stderr)
    if args.out:
        import json

        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {args.out}", file=sys.stderr)
    counts = report["counts"]
    print(
        f"gate: {counts['ok']} ok, {counts['cached']} cached, "
        f"{counts['failed']} failed, {counts['skipped']} skipped "
        f"in {report['total_s']:.1f}s (jobs={report['jobs']})",
        file=sys.stderr,
    )
    return 0 if report["ok"] and not problems else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pvraft_tpu.analysis",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_lint = sub.add_parser("lint", help="run the AST lint rules")
    p_lint.add_argument("paths", nargs="*", help="files/directories to lint")
    p_lint.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    p_lint.add_argument("--select", default="",
                        help="comma-separated rule ids to run (default all)")
    p_lint.add_argument("--stats", action="store_true",
                        help="suppression-debt report (exit 1 on "
                             "reason-less suppressions)")
    p_lint.set_defaults(fn=_cmd_lint)

    p_trace = sub.add_parser(
        "trace", help="eval_shape trace-compat audit of registered ops"
    )
    p_trace.set_defaults(fn=_cmd_trace)

    p_deep = sub.add_parser(
        "deepcheck",
        help="jaxpr-level semantic analysis (GJ rules) over the audit "
             "registry",
    )
    p_deep.add_argument("--list-rules", action="store_true",
                        help="print the GJ rule table and exit")
    p_deep.add_argument("--select", default="",
                        help="comma-separated GJ rule ids (default all)")
    p_deep.add_argument("--entries", action="append", default=[],
                        metavar="SUBSTR",
                        help="only entries whose name contains SUBSTR "
                             "(repeatable)")
    p_deep.add_argument("-v", "--verbose", action="store_true",
                        help="per-entry program stats (eqn/collective "
                             "counts, precision-flow map)")
    p_deep.set_defaults(fn=_cmd_deepcheck)

    p_conc = sub.add_parser(
        "concurrency",
        help="threadcheck: concurrency static analysis (GC rules) over "
             "the hand-threaded serve/obs/loader planes",
    )
    p_conc.add_argument("paths", nargs="*",
                        help="files/directories to check (default: the "
                             "serve/, obs/, data/loader.py scope)")
    p_conc.add_argument("--list-rules", action="store_true",
                        help="print the GC rule table and exit")
    p_conc.add_argument("--select", default="",
                        help="comma-separated GC rule ids (default all)")
    p_conc.set_defaults(fn=_cmd_concurrency)

    p_kern = sub.add_parser(
        "kernels",
        help="kernelcheck: Pallas/Mosaic static analysis (GK rules) over "
             "ops/pallas/, plus the --plan VMEM/roofline planner",
    )
    p_kern.add_argument("paths", nargs="*",
                        help="files/directories to check (default: the "
                             "ops/pallas scope)")
    p_kern.add_argument("--list-rules", action="store_true",
                        help="print the GK rule table and exit")
    p_kern.add_argument("--select", default="",
                        help="comma-separated GK rule ids (default all)")
    p_kern.add_argument("--plan", action="store_true",
                        help="emit the pvraft_kernel_plan/v1 artifact "
                             "(static models joined with --costs)")
    p_kern.add_argument("--out", default="",
                        help="with --plan: write the artifact here "
                             "instead of stdout")
    p_kern.add_argument("--check", default="", metavar="ARTIFACT",
                        help="regenerate the plan and compare against a "
                             "committed artifact (exit 1 on drift)")
    p_kern.add_argument("--costs", default="artifacts/programs_costs.json",
                        help="the committed pvraft_costs/v1 inventory to "
                             "join against")
    p_kern.set_defaults(fn=_cmd_kernels)

    p_shard = sub.add_parser(
        "sharding",
        help="shardcheck: SPMD/multi-host static analysis (GS rules) over "
             "the multi-process planes, plus the --plan pod "
             "memory/comms planner",
    )
    p_shard.add_argument("paths", nargs="*",
                         help="files/directories to check (default: the "
                              "engine/obs/parallel/programs/models/ops/"
                              "data scope)")
    p_shard.add_argument("--list-rules", action="store_true",
                         help="print the GS rule table and exit")
    p_shard.add_argument("--select", default="",
                         help="comma-separated GS rule ids (default all)")
    p_shard.add_argument("--plan", action="store_true",
                         help="emit the pvraft_pod_plan/v1 artifact "
                              "(partition rules x --costs x --params x "
                              "candidate meshes)")
    p_shard.add_argument("--out", default="",
                         help="with --plan: write the artifact here "
                              "instead of stdout")
    p_shard.add_argument("--check", default="", metavar="ARTIFACT",
                         help="regenerate the plan and compare against a "
                              "committed artifact (exit 1 on drift)")
    p_shard.add_argument("--costs", default="artifacts/programs_costs.json",
                         help="the committed pvraft_costs/v1 inventory to "
                              "join against")
    p_shard.add_argument("--params", default="artifacts/params_tree.json",
                         help="the committed pvraft_params_tree/v1 leaf "
                              "inventory to join against")
    p_shard.set_defaults(fn=_cmd_sharding)

    p_det = sub.add_parser(
        "determinism",
        help="detcheck: seed/RNG-discipline static analysis (GD rules) "
             "over the whole package, plus the --replay bitwise "
             "replay harness",
    )
    p_det.add_argument("paths", nargs="*",
                       help="files/directories to check (default: the "
                            "whole pvraft_tpu package)")
    p_det.add_argument("--list-rules", action="store_true",
                       help="print the GD rule table and exit")
    p_det.add_argument("--select", default="",
                       help="comma-separated GD rule ids (default all)")
    p_det.add_argument("--replay", action="store_true",
                       help="run the registered train step and serve "
                            "dispatch twice from the same seed and emit "
                            "the pvraft_determinism/v1 artifact")
    p_det.add_argument("--seed", type=int, default=0,
                       help="with --replay: the config seed to replay "
                            "from (default 0)")
    p_det.add_argument("--out", default="",
                       help="with --replay: write the artifact here "
                            "instead of stdout")
    p_det.add_argument("--check", default="", metavar="ARTIFACT",
                       help="regenerate the replay and compare against a "
                            "committed artifact (exit 1 on drift)")
    p_det.set_defaults(fn=_cmd_determinism)

    p_gate = sub.add_parser(
        "gate",
        help="gatecheck: run the declared gate (cached, parallel, "
             "per-stage timing) or the GE evidence/claims rules "
             "(--rules); --check validates committed gate reports",
    )
    p_gate.add_argument("--rules", action="store_true",
                        help="run the GE001+ evidence/claims rules "
                             "instead of executing the gate stages")
    p_gate.add_argument("--list-rules", action="store_true",
                        help="print the GE rule table and exit")
    p_gate.add_argument("--select", default="",
                        help="with --rules: comma-separated GE rule ids "
                             "(default all)")
    p_gate.add_argument("--list-stages", action="store_true",
                        help="print the declared stage registry and exit")
    p_gate.add_argument("--only", action="append", default=[],
                        metavar="STAGE",
                        help="run only this stage (repeatable)")
    p_gate.add_argument("--jobs", type=int, default=None,
                        help="parallel stages (default: min(4, cpus), "
                             "at least 2)")
    p_gate.add_argument("--changed-only", action="store_true",
                        help="skip stages whose input globs intersect no "
                             "file changed vs git HEAD")
    p_gate.add_argument("--no-cache", action="store_true",
                        help="ignore and do not write the content-hash "
                             "stage cache (.gate_cache/)")
    p_gate.add_argument("--out", default="",
                        help="write the pvraft_gate/v1 report here")
    p_gate.add_argument("--check", nargs="+", default=[],
                        metavar="REPORT",
                        help="validate committed pvraft_gate/v1 reports "
                             "(full green run, stage set == registry)")
    p_gate.add_argument("--root", default="",
                        help="repo root (default: cwd)")
    p_gate.add_argument("-v", "--verbose", action="store_true",
                        help="echo every stage's buffered output, not "
                             "just failures")
    p_gate.set_defaults(fn=_cmd_gate)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
