"""Typed configuration for pvraft_tpu.

One dataclass consumed by both the train and test entry points, replacing the
duplicated argparse blocks of the reference (``train.py:8-71``,
``test.py:20-67``). Defaults follow the canonical hyperparameters in the
reference ``run.sh:2-8`` and the model-internal constants
(hidden/context = 64 ``model/RAFTSceneFlow.py:13-14``, knn = 32
``model/corr.py:9``, encoder width 32 ``model/extractor.py:10``, graph k = 32
``model/extractor.py:8``, lr = 1e-3 ``tools/engine.py:57``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


# Valid ModelConfig.remat_policy values (mapped to jax.checkpoint policies
# in models/raft.py; "none" defers to the legacy `remat` bool).
REMAT_POLICIES = ("none", "full", "dots", "dots_no_batch", "save_corr")


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters of the PV-RAFT flagship model."""

    # Correlation volume (reference flags: train.py:24-39).
    truncate_k: int = 512          # top-k kept of the all-pairs correlation
    corr_levels: int = 3           # voxel pyramid levels
    base_scale: float = 0.25       # voxel edge at level 0
    resolution: int = 3            # local cube resolution (3x3x3 = 27 bins)
    corr_knn: int = 32             # k of the point-branch knn lookup

    # Encoder / update loop (model/RAFTSceneFlow.py:13-14, extractor.py:8-10).
    graph_k: int = 32              # neighbors of the DGCNN graph
    encoder_width: int = 32        # first SetConv width (doubles per layer)
    hidden_dim: int = 64           # GRU hidden state
    context_dim: int = 64          # context features
    feature_dim: int = 128         # encoder output channels

    # Numerics.
    compute_dtype: str = "float32"   # "bfloat16" for the fast path
    # Pallas voxel/lookup kernels vs the XLA fallback. None = auto: True
    # on TPU (the certified fast path — scripts/tpu_consistency.py), False
    # elsewhere (CPU/GPU run the oracle XLA path; the Pallas kernels are
    # TPU-shaped). Explicit True/False overrides.
    use_pallas: Optional[bool] = None
    corr_chunk: Optional[int] = None  # chunked/streaming top-k over N2 if set
    remat: bool = False              # rematerialize each GRU iteration
    # Checkpointing policy for the rematerialized GRU iteration
    # (models/raft.py). "none" honors the legacy blanket `remat` bool;
    # any other value turns remat ON with that jax.checkpoint policy:
    #   "full"          save nothing — recompute everything (legacy remat)
    #   "dots"          save matmul/contraction results (checkpoint_dots)
    #   "dots_no_batch" save only non-batch-dim contractions
    #   "save_corr"     save the per-iteration corr-lookup output (tagged
    #                   via checkpoint_name) and recompute the rest — the
    #                   gather-heavy lookup never reruns in the backward.
    remat_policy: str = "none"
    # Scatter-free custom VJPs for the gather-dominated backward: neighbor
    # gathers (ops/geometry.gather_neighbors), the knn_lookup candidate
    # selection (ops/corr), and the SetConv k-pool max all swap XLA's
    # default gather-grad -> scatter-add for one-hot-matmul / argmax
    # formulations (ops/scatter_free.py) that run on the MXU instead of
    # serializing. Forward numerics identical; grad parity test-gated
    # (tests/test_scatter_free.py); jaxprs unchanged when False. Only the
    # XLA lookup path is affected (the fused Pallas kernel has its own
    # VJP).
    scatter_free_vjp: bool = False
    # Fused MotionEncoder+ConvGRU update (ops/pallas/gru_iter.py): one
    # Pallas kernel per GRU iteration runs the whole feature update from
    # VMEM-resident point tiles (tile geometry per
    # artifacts/kernel_plan.json) instead of eight separate Dense
    # launches round-tripping every intermediate through HBM. Param tree
    # and checkpoints are identical to the unfused path; forward + grad
    # parity is test-gated within pinned tolerances
    # (tests/test_fused_gru.py); jaxpr byte-identical when False.
    # Orthogonal to use_pallas (which gates the lookup kernels).
    fused_gru: bool = False
    # lax.approx_max_k for the correlation truncation: much faster on TPU
    # (recall ~0.95 by default); exact sort-based top-k when False.
    approx_topk: bool = False
    # Unroll factor of the GRU iteration scan (1 = rolled). Unrolling lets
    # XLA fuse across iterations at the cost of compile time; tune on TPU.
    scan_unroll: int = 1
    # Stream the kNN graph construction over point chunks (avoids the
    # (N, N) distance matrix; needed for 16k+ point clouds).
    graph_chunk: Optional[int] = None
    # lax.approx_max_k for the encoder kNN graph neighbor selection
    # (recall ~0.95): the graph top-k over the (N, N) distance matrix is
    # a TPU sort bottleneck the MXU cannot help with. Approximate
    # neighbors change which edges the SetConvs aggregate — opt-in,
    # perf-path only, like approx_topk.
    approx_knn: bool = False
    # Sequence-parallel correlation: shard both point axes of the
    # correlation volume over the mesh "seq" axis and build the truncated
    # cache with a ppermute ring (parallel/ring.py) instead of the dense
    # (N, N) volume. Requires the model to be constructed with a mesh whose
    # seq axis > 1; the long-context path for 16k+ points across chips
    # (memory wall: reference model/corr.py:96-99).
    seq_shard: bool = False

    def __post_init__(self):
        if self.remat_policy not in REMAT_POLICIES:
            raise ValueError(
                f"remat_policy must be one of {REMAT_POLICIES}, "
                f"got {self.remat_policy!r}"
            )
        if self.corr_knn > self.truncate_k:
            raise ValueError(
                f"corr_knn ({self.corr_knn}) must be <= truncate_k "
                f"({self.truncate_k}): the kNN branch selects among the "
                f"truncated correlation candidates"
            )
        # The three correlation-build strategies (dense, chunked streaming,
        # sequence-parallel ring) honor different knobs; reject
        # contradictory combinations instead of silently ignoring one side
        # (a benchmark labeled "approx + 2-chip SP" must not silently
        # measure exact top-k). Full honor/ignore table: PARITY.md
        # "Correlation-path config matrix".
        if self.approx_topk and self.seq_shard:
            raise ValueError(
                "approx_topk is not supported with seq_shard: the ring "
                "correlation (parallel/ring.py) assembles the EXACT "
                "truncated top-k across seq shards and would silently "
                "ignore approx_topk; benchmark approx_topk on the "
                "unsharded correlation path only"
            )
        if self.corr_chunk is not None and self.seq_shard:
            raise ValueError(
                "corr_chunk is not supported with seq_shard: both knobs "
                "select a correlation-build strategy (chunked streaming "
                "vs ppermute ring); the ring already bounds per-chip "
                "memory by the seq-shard width, so drop corr_chunk on "
                "sharded runs"
            )
        # Same honor/ignore discipline for the GRAPH build strategies
        # (dense, chunked streaming, seq-parallel ring): approx_knn only
        # exists on the dense path.
        if self.approx_knn and self.graph_chunk is not None:
            raise ValueError(
                "approx_knn is not supported with graph_chunk: the "
                "chunked graph build keeps an exact running top-k per "
                "chunk and would silently ignore approx_knn"
            )
        if self.approx_knn and self.seq_shard:
            raise ValueError(
                "approx_knn is not supported with seq_shard: the ring "
                "graph build (parallel/ring.py) assembles EXACT "
                "neighbors across seq shards and would silently ignore "
                "approx_knn"
            )


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset selection and sampling (reference train.py:12-23)."""

    dataset: str = "FT3D"          # FT3D | KITTI | synthetic
    root: str = ""                 # preprocessed dataset root
    max_points: int = 8192         # exact-N sampling target
    num_workers: int = 8           # host-side prefetch threads
    synthetic_size: int = 64       # samples in the synthetic dataset
    # Independently moving rigid objects per synthetic scene (1 = one
    # global transform; >1 = FT3D-like piecewise-rigid flow).
    synthetic_objects: int = 1
    # Use the C++ batch assembler (pvraft_tpu/native) when the dataset
    # supports it and the library builds; falls back to numpy otherwise.
    native_loader: bool = True
    # Enforce the reference's dataset-size integrity asserts (19,640 FT3D
    # train scenes etc.); disable for subset/smoke runs.
    strict_sizes: bool = True


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Optimization schedule (reference train.py:40-67, tools/engine.py:57-58)."""

    batch_size: int = 2
    num_epochs: int = 20
    lr: float = 1e-3
    gamma: float = 0.8             # sequence-loss decay (tools/loss.py:9)
    iters: int = 8                 # GRU iterations during training
    eval_iters: int = 32           # GRU iterations at val/test (engine.py:198)
    # Scenes evaluated concurrently at val/test (Trainer per-epoch val and
    # the standalone test.py eval). The reference protocol is 1
    # (test.py:92); sharding eval_batch scenes over the mesh data axis
    # computes per-scene metrics so the running means match the protocol's
    # up to float reassociation (~1e-6, test-checked at rel 1e-5).
    # 0 (default) = one scene per data-axis device — the per-epoch val
    # loop parallelizes across the mesh instead of replicating bs=1
    # (reference behavior tools/engine.py:197-198 being serial is a
    # torch-era artifact, not part of the protocol).
    eval_batch: int = 0
    # Scan-fuse this many eval batches into ONE compiled dispatch
    # (lax.scan over the eval step — the eval twin of
    # ParallelConfig.steps_per_dispatch). Per-scene metrics and running
    # means are unchanged. The fused program returns metrics only, so a
    # --dump_dir run (which needs per-batch flows) falls back to the
    # per-batch path for that run. 1 disables fusion.
    eval_scan: int = 1
    checkpoint_interval: int = 5
    # "msgpack" (single atomic file) or "orbax" (async multi-host-aware
    # directory checkpoints); loads auto-detect (engine/checkpoint.py).
    ckpt_backend: str = "msgpack"
    refine: bool = False           # stage-2 (frozen backbone) training
    seed: int = 0
    # The reference steps CosineAnnealingLR(T_max=epochs*len(dataset)) once
    # per *epoch* (tools/engine.py:58,168) — effectively a near-constant LR.
    # "parity" reproduces that; "cosine" is the corrected per-step schedule.
    lr_schedule: str = "parity"
    # When set, epoch 0 runs under jax.profiler.trace writing a
    # TensorBoard-viewable profile here (SURVEY.md §5 tracing).
    profile_dir: str = ""
    # Gradient dtype lever (engine/steps.py): "bfloat16" casts the grads
    # once right after value_and_grad — the dtype the cross-device
    # all-reduce and any downstream grad traffic run in — then restores
    # float32 before Adam (optimizer state stays float32). "float32"
    # (default) leaves the step byte-identical to the pre-existing one.
    grad_dtype: str = "float32"
    # Run-health telemetry (pvraft_tpu/obs). When on, the jitted train
    # step also returns in-jit numerics monitors (global + per-group grad
    # norms, update/param ratio, per-GRU-iteration delta_flow norms, a
    # NaN/Inf sentinel — obs/monitors.py) as an extra metrics leaf, the
    # trainer runs trailing-window divergence detection on the loss, and
    # a detector trip dumps the offending (batch, params, opt_state) to
    # <exp_path>/snapshots/ for scripts/run_doctor.py replay. Off
    # (default) leaves the train-step jaxpr byte-identical to the
    # pre-telemetry step (test-gated, like scatter_free_vjp).
    telemetry: bool = False
    # Trailing window (healthy steps) of the loss z-score detector.
    divergence_window: int = 64
    # Trip when loss > mean + zscore * std over the trailing window;
    # 0 disables the z-score trigger (the NaN/Inf sentinel stays armed).
    divergence_zscore: float = 6.0
    # Snapshots dumped per run at most (a persistently sick run must not
    # fill the disk with near-identical state dumps).
    max_snapshots: int = 3
    # Stop training (raise) after the first snapshot instead of running
    # on with corrupt state; off reproduces let-it-run behavior.
    halt_on_divergence: bool = False
    # Retrace watchdog strictness (obs/retrace.py). The watchdog is
    # always armed (one int compare per dispatch, pure host-side): a
    # train-loop program whose jit cache grows after warmup emits a
    # `recompile` event naming the program + arg signature. strict mode
    # additionally raises RetraceError — a silent retrace recompiles a
    # multi-minute program per occurrence, so perf runs should fail
    # loudly rather than record a corrupted measurement.
    strict_retrace: bool = False

    def __post_init__(self):
        # Fail before training, not at the end-of-epoch save.
        if self.ckpt_backend not in ("msgpack", "orbax"):
            raise ValueError(
                f"ckpt_backend must be 'msgpack' or 'orbax', "
                f"got {self.ckpt_backend!r}"
            )
        if self.grad_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"grad_dtype must be 'float32' or 'bfloat16', "
                f"got {self.grad_dtype!r}"
            )
        if self.divergence_window < 2:
            raise ValueError(
                f"divergence_window must be >= 2, "
                f"got {self.divergence_window}"
            )
        if self.divergence_zscore < 0:
            raise ValueError(
                f"divergence_zscore must be >= 0 (0 disables), "
                f"got {self.divergence_zscore}"
            )


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Device-mesh layout. Replaces nn.DataParallel (tools/engine.py:63-64)."""

    data_axis: int = -1            # -1: all devices on the data axis
    seq_axis: int = 1              # sequence-parallel shards of the N2 axis
    donate: bool = True
    # Carry params+opt_state across the step boundary as ONE flat buffer
    # (engine/steps.py:make_packed_train_step). Numerically identical to the
    # pytree step (tests/test_packed_step.py); mitigates per-chained-leaf
    # dispatch overhead on remote-dispatch platforms (BENCHMARKS.md).
    packed_state: bool = False
    # With packed_state: round-trip the flat state buffer through the host
    # between steps (D2H+H2D of a few MB). Strictly slower on a directly
    # attached TPU; on remote-dispatch tunnels whose chained-executable
    # bookkeeping costs seconds per step (BENCHMARKS.md) the round-trip is
    # the fastest TRUE training loop — identical floats, state evolving
    # every step. bench.py auto-tries it; this flag makes the same loop
    # available to real training runs.
    host_roundtrip: bool = False
    # Fuse this many optimizer steps into ONE compiled dispatch via
    # lax.scan over the packed step (engine/steps.py:
    # make_multistep_train_step). Per-step numerics and logging are
    # unchanged (the packed state is the scan carry); dispatch overhead is
    # amortized K-fold — decisive on remote-dispatch tunnels where one
    # full-step dispatch costs seconds (BENCHMARKS.md). 1 disables fusion.
    steps_per_dispatch: int = 1
    # Batches kept in flight to the device (data/loader.py:device_prefetch):
    # H2D transfers overlap compute. 1 disables the pipeline.
    device_prefetch: int = 2

    def __post_init__(self):
        if self.host_roundtrip and not self.packed_state:
            raise ValueError(
                "host_roundtrip requires packed_state (the round-trip "
                "moves the single flat state buffer)"
            )
        if self.steps_per_dispatch < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        if self.steps_per_dispatch > 1 and not self.packed_state:
            raise ValueError(
                "steps_per_dispatch > 1 requires packed_state (the scan "
                "carries the single flat state buffer across fused steps)"
            )
        if self.steps_per_dispatch > 1 and self.host_roundtrip:
            raise ValueError(
                "steps_per_dispatch > 1 already amortizes dispatch "
                "overhead; combining it with host_roundtrip (a per-step "
                "host sync) would reintroduce what it removes"
            )


@dataclasses.dataclass(frozen=True)
class Config:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    exp_path: str = "experiments/default"

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def resolve_remat_policy(cfg: ModelConfig) -> Optional[str]:
    """The effective remat policy name, or None for no remat.

    ``remat_policy`` wins when set; the legacy ``remat`` bool maps to the
    blanket "full" policy it always meant."""
    if cfg.remat_policy != "none":
        return cfg.remat_policy
    return "full" if cfg.remat else None


def resolve_use_pallas(cfg: ModelConfig) -> bool:
    """``use_pallas`` with the auto default resolved: None means "the
    compiled Pallas kernels on TPU, the XLA oracle path elsewhere"."""
    if cfg.use_pallas is None:
        import jax

        return jax.default_backend() == "tpu"
    return cfg.use_pallas


def compute_dtype(cfg: ModelConfig):
    """jnp dtype for matmul compute, or None for full float32."""
    import jax.numpy as jnp

    if cfg.compute_dtype in ("float32", "f32", None):
        return None
    return jnp.dtype(cfg.compute_dtype)


def tiny_config(n_points: int = 256, truncate_k: int = 64, iters: int = 2) -> Config:
    """A small config for tests and CI (the "FT3D tiny" slice)."""
    return Config(
        model=ModelConfig(truncate_k=truncate_k),
        data=DataConfig(dataset="synthetic", max_points=n_points, synthetic_size=8),
        train=TrainConfig(batch_size=2, num_epochs=1, iters=iters, eval_iters=iters),
    )
