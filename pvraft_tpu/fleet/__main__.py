"""CLI: the fleet router + its evidence validator.

``python -m pvraft_tpu.fleet run --target h:p --target h:p [--port N]``
stands the routing/fan-out tier up over already-running serve hosts;
``python -m pvraft_tpu.fleet validate <artifact>...`` validates
committed ``pvraft_fleet_chaos/v1`` evidence (the ``validate-fleet``
gate stage). Jax-free — the fleet tier never imports a backend.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_validate(args) -> int:
    from pvraft_tpu.fleet.artifact import validate_fleet_artifact

    rc = 0
    for path in args.paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"{path}: unreadable: {e}", file=sys.stderr)
            rc = 1
            continue
        problems = validate_fleet_artifact(doc, path=path)
        if problems:
            for p in problems:
                print(f"{path}: {p}", file=sys.stderr)
            rc = 1
        else:
            print(f"{path}: ok (pvraft_fleet_chaos/v1)")
    return rc


def _cmd_run(args) -> int:
    import threading

    from pvraft_tpu.fleet.router import build_fleet

    telemetry = None
    if args.events:
        from pvraft_tpu.serve.events import ServeTelemetry

        telemetry = ServeTelemetry(args.events)
    surface = None
    if args.cost_surface:
        from pvraft_tpu.programs.costs import CostSurface

        # Arming is an explicit opt-in (the serve --cost_surface
        # discipline): a bad path fails loudly here, never silently
        # routes unpriced.
        surface = CostSurface.load(args.cost_surface)
    router = build_fleet(args.target, telemetry=telemetry,
                         cost_surface=surface, host=args.host,
                         port=args.port, quiet=not args.verbose)
    router.start()
    print(f"fleet router on {router.host}:{router.port} over "
          f"{[b.client.endpoint for b in router.backends]} "
          f"(cost surface {'armed' if surface else 'off'})",
          file=sys.stderr)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        router.shutdown()
        if telemetry is not None:
            telemetry.close()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m pvraft_tpu.fleet")
    sub = ap.add_subparsers(dest="cmd", required=True)
    val = sub.add_parser(
        "validate", help="validate pvraft_fleet_chaos/v1 artifacts")
    val.add_argument("paths", nargs="+")
    val.set_defaults(fn=_cmd_validate)
    run = sub.add_parser(
        "run", help="run the fleet router over N serve hosts")
    run.add_argument("--target", action="append", required=True,
                     metavar="HOST:PORT",
                     help="a backend serve host (repeatable)")
    run.add_argument("--host", default="127.0.0.1")
    run.add_argument("--port", type=int, default=0,
                     help="router port (0 = ephemeral)")
    run.add_argument("--events", default="",
                     help="fleet event log path (pvraft_events/v1)")
    run.add_argument("--cost_surface", default="",
                     help="pvraft_costs/v1 inventory to price routing "
                          "decisions with (explicit opt-in)")
    run.add_argument("-v", "--verbose", action="store_true",
                     help="log HTTP requests")
    run.set_defaults(fn=_cmd_run)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
