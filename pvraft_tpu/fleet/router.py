"""Fleet router: thin HTTP fan-out tier over serve replica-pool hosts.

One tier above ``serve/server.py``: N backend hosts (each a full
``serve.build_service`` — engine, micro-batcher, supervisor) behind a
single stdlib ``ThreadingHTTPServer``. The router holds no model, no
jax, no queue of its own; it decides *which pool* answers and the pools
do the serving.

Routing (``POST /predict``): the request's point count picks a bucket
(the backends' own polled bucket table), the cost surface prices it in
predicted device-seconds (when armed — ``CostSurface.estimate_serve``,
the serve dispatch pricing one tier down), and the request goes to the
in-rotation backend with the least predicted outstanding work (router-
side open dispatches plus the polled backend queue, priced). A shed
(503) or unreachable backend spills the request to the next candidate;
only when EVERY candidate shed does the client see a 503 — with a
``Retry-After`` no shorter than the backends' own hint. Client errors
(400/413) never spill: they are deterministic and re-sending them to a
second pool would just fail twice.

Health: a poll loop GETs each backend's ``/healthz`` every
``poll_interval_s`` and drives the supervisor state machine one tier up
(healthy -> degraded -> quarantined -> probing, ``fleet/backend.py``).
Quarantined backends leave the rotation until a probe poll succeeds.

Weight hot-swap (``POST /admin/reload``): fans the body out to the
backends SEQUENTIALLY — N-1 pools keep serving while one swaps, so the
fleet never has zero capacity during a rollout. The swap itself is the
engine's drain-aware pointer swap (AOT executables take params as
arguments — zero recompiles, the sealed watchdog's counter proves it).
``"canary": true`` restricts the swap to one backend and arms the
canary controller on it.

Canary (``POST /admin/canary`` / the ``canary`` reload flag): the
router interleaves ``canary_fraction`` of live traffic onto the new-
weight backend, shadow-mirrors those requests to the incumbent, and
gates promotion on the pinned EPE bounds (``fleet/canary.py``).

Observability: every client dispatch emits a ``fleet_route`` event
(reason vocabulary ``least_loaded``/``spillover``/``canary``/
``shadow``), ``GET /healthz`` aggregates per-backend rows + the canary
block, ``GET /metrics`` serves the ``pvraft_fleet_*`` ledger as JSON or
Prometheus. All counter mutations sit under single locks so the
request identity holds at every snapshot (``fleet/metrics.py``).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from pvraft_tpu.fleet.backend import Backend, BackendClient
from pvraft_tpu.fleet.canary import CanaryController
from pvraft_tpu.fleet.metrics import PROM_CONTENT_TYPE, FleetMetrics
from pvraft_tpu.programs.geometries import FLEET_DEFAULTS

__all__ = ["FleetConfig", "FleetRouter", "build_fleet"]

JSON_CT = "application/json"

# Body cap before the first successful poll reveals the real bucket
# table (then: the serve formula over the largest polled bucket). 64 B
# bounds any JSON float spelling per coordinate.
_FALLBACK_MAX_BUCKET = 8192


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet-tier thresholds; defaults are the declared geometry data
    (``programs/geometries.FLEET_DEFAULTS`` — the SUPERVISOR_DEFAULTS
    discipline one tier up)."""

    poll_interval_s: float = FLEET_DEFAULTS["poll_interval_s"]
    poll_timeout_s: float = FLEET_DEFAULTS["poll_timeout_s"]
    degraded_after: int = FLEET_DEFAULTS["degraded_after"]
    quarantine_after: int = FLEET_DEFAULTS["quarantine_after"]
    retry_after_s: int = FLEET_DEFAULTS["retry_after_s"]
    predict_timeout_s: float = FLEET_DEFAULTS["predict_timeout_s"]
    canary_fraction: float = FLEET_DEFAULTS["canary_fraction"]
    canary_min_samples: int = FLEET_DEFAULTS["canary_min_samples"]
    canary_epe_bound: float = FLEET_DEFAULTS["canary_epe_bound"]
    canary_rel_epe_bound: float = FLEET_DEFAULTS["canary_rel_epe_bound"]


class _FleetHandler(BaseHTTPRequestHandler):
    """Bound per-router via ``type()`` (the serve/server.py idiom)."""

    router: "FleetRouter"
    quiet: bool = True
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        if not self.quiet:
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload: bytes, content_type: str,
               extra: Optional[List[Tuple[str, str]]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        for name, value in extra or ():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(payload)

    def _reply_json(self, code: int, doc: Dict[str, Any],
                    extra: Optional[List[Tuple[str, str]]] = None) -> None:
        self._reply(code, json.dumps(doc).encode("utf-8"), JSON_CT,
                    extra=extra)

    def _reply_error(self, code: int, error: str, detail: str = "") -> None:
        self._reply_json(code, {"error": error, "detail": detail})

    def _read_body(self) -> Optional[bytes]:
        """Bounded body read; None (after replying 400/413) when the
        Content-Length is missing, malformed or over the cap — the
        serve handler's keep-alive discipline (an unread body would
        desync the connection, so these close it)."""
        raw = self.headers.get("Content-Length")
        try:
            length = int(raw if raw is not None else "")
        except ValueError:
            length = -1
        if length < 0:
            self.close_connection = True
            self._reply_error(400, "bad_request",
                              "missing or invalid Content-Length")
            return None
        if length > self.router.max_body_bytes():
            self.close_connection = True
            self._reply_error(413, "too_large",
                              f"body {length} B exceeds the cap")
            return None
        return self.rfile.read(length)

    # ------------------------------------------------------------- routes --

    def do_GET(self):  # noqa: N802 — stdlib handler naming
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            self._reply_json(200, self.router.health_doc())
            return
        if path == "/metrics":
            fmt = "prometheus" if "format=prometheus" in query else "json"
            if fmt == "prometheus":
                text = self.router.metrics.prometheus(
                    [b.snapshot() for b in self.router.backends])
                self._reply(200, text.encode("utf-8"), PROM_CONTENT_TYPE)
            else:
                self._reply_json(200, self.router.metrics.snapshot())
            return
        self._reply_error(404, "not_found", self.path)

    def do_POST(self):  # noqa: N802 — stdlib handler naming
        path = self.path.partition("?")[0]
        if path not in ("/predict", "/admin/reload", "/admin/canary"):
            self.close_connection = True
            self._reply_error(404, "not_found", self.path)
            return
        body = self._read_body()
        if body is None:
            return
        try:
            doc = json.loads(body or b"{}")
        except ValueError as e:
            if path == "/predict":
                # Counted: the ledger sees every predict ingress.
                self.router.metrics.record_submit()
                self.router.metrics.record_failure("bad_request")
            self._reply_error(400, "bad_request", f"invalid JSON: {e}")
            return
        if not isinstance(doc, dict):
            if path == "/predict":
                self.router.metrics.record_submit()
                self.router.metrics.record_failure("bad_request")
            self._reply_error(400, "bad_request", "body must be an object")
            return
        if path == "/predict":
            status, out, retry_after = self.router.route_predict(doc)
            extra = ([("Retry-After", str(retry_after))]
                     if retry_after is not None else None)
            self._reply_json(status, out, extra=extra)
            return
        if path == "/admin/reload":
            status, out = self.router.admin_reload_doc(doc)
            self._reply_json(status, out)
            return
        status, out = self.router.admin_canary_doc(doc)
        self._reply_json(status, out)


class FleetRouter:
    """The assembled fan-out tier. ``port=0`` binds ephemeral (tests,
    chaos runs); ``start()``/``shutdown()`` manage the HTTP loop and
    the health poll thread."""

    def __init__(self, targets, cfg: Optional[FleetConfig] = None,
                 telemetry=None, cost_surface=None,
                 host: str = "127.0.0.1", port: int = 0,
                 quiet: bool = True):
        if not targets:
            raise ValueError("a fleet needs at least one backend target")
        self.cfg = cfg or FleetConfig()
        self.telemetry = telemetry
        self.cost_surface = cost_surface
        self.metrics = FleetMetrics()
        self.canary = CanaryController(
            fraction=self.cfg.canary_fraction,
            min_samples=self.cfg.canary_min_samples,
            epe_bound=self.cfg.canary_epe_bound,
            rel_epe_bound=self.cfg.canary_rel_epe_bound)
        self.backends: List[Backend] = []
        for i, target in enumerate(targets):
            client = (target if isinstance(target, BackendClient)
                      else BackendClient.from_target(
                          target,
                          predict_timeout_s=self.cfg.predict_timeout_s,
                          poll_timeout_s=self.cfg.poll_timeout_s))
            self.backends.append(Backend(
                i, client, degraded_after=self.cfg.degraded_after,
                quarantine_after=self.cfg.quarantine_after))
        handler = type("BoundFleetHandler", (_FleetHandler,),
                       {"router": self, "quiet": quiet})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.host = host
        self.port = self.httpd.server_address[1]
        self._http_thread: Optional[threading.Thread] = None
        self._poll_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---------------------------------------------------------- lifecycle --

    def start(self) -> None:
        # Prime health before serving: the first request must not race
        # an empty rotation just because the poll cadence hasn't fired.
        self.poll_once()
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, name="pvraft-fleet-http",
            daemon=True)
        self._http_thread.start()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="pvraft-fleet-poll", daemon=True)
        self._poll_thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(10.0)
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(10.0)

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.cfg.poll_interval_s):
            self.poll_once()

    def poll_once(self) -> None:
        """One health sweep: quarantined backends go probing, every
        backend gets a ``/healthz`` GET, transitions are decided under
        each backend's lock and logged after."""
        for b in self.backends:
            b.begin_probe()
            try:
                health = b.client.healthz()
                if not isinstance(health, dict):
                    raise ValueError("healthz: not a JSON object")
                b.poll_succeeded(health)
            except (OSError, ValueError):
                b.poll_failed()

    # ------------------------------------------------------------ geometry --

    def buckets(self) -> Optional[List[int]]:
        for b in self.backends:
            table = b.buckets()
            if table:
                return table
        return None

    def bucket_for(self, n_points: int) -> Optional[int]:
        table = self.buckets()
        if not table:
            return None
        for b in table:
            if n_points <= b:
                return b
        return None

    def max_body_bytes(self) -> int:
        table = self.buckets()
        largest = max(table) if table else _FALLBACK_MAX_BUCKET
        return 2 * largest * 3 * 64 + 65536

    def predict_seconds(self, bucket: Optional[int]) -> float:
        """Cost-surface price of one request in this bucket (0.0 when
        the surface is disarmed or the geometry is unknown — routing
        degrades to raw queue counts, never blocks on pricing)."""
        if self.cost_surface is None or bucket is None:
            return 0.0
        dtype = None
        for b in self.backends:
            dtype = b.dtype()
            if dtype:
                break
        est = self.cost_surface.estimate_serve(bucket, 1,
                                               dtype or "bfloat16")
        return est.device_seconds if est is not None else 0.0

    # ------------------------------------------------------------- predict --

    def route_predict(self, doc: Dict[str, Any]
                      ) -> Tuple[int, Dict[str, Any], Optional[float]]:
        """Route one predict body; returns ``(status, body,
        retry_after)``. Pure function of router state + backend HTTP —
        tests drive it without a client socket."""
        self.metrics.record_submit()
        pc1 = doc.get("pc1")
        n = len(pc1) if isinstance(pc1, list) else 0
        bucket = self.bucket_for(n)
        predicted_s = self.predict_seconds(bucket)

        cst = self.canary.status()
        take_canary = False
        canary_backend: Optional[Backend] = None
        if cst["armed"] and cst["verdict"] is None:
            canary_backend = self.backends[cst["canary_backend"]]
            if canary_backend.in_rotation and self.canary.take():
                take_canary = True
        normal = sorted(
            (b for b in self.backends
             if b.in_rotation and not b.is_canary()),
            key=lambda b: b.load_score(predicted_s))
        order = [canary_backend] if take_canary else normal
        if take_canary is False and not normal and canary_backend is not None \
                and canary_backend.in_rotation:
            # Degenerate fleet: the canary is the only live backend —
            # serving beats shedding, interleave bookkeeping aside.
            order = [canary_backend]

        served: Optional[Backend] = None
        resp: Optional[Dict[str, Any]] = None
        attempts = 0
        retry_hint: Optional[float] = None
        for b in order:
            if attempts > 0:
                self.metrics.record_spillover()
            attempts += 1
            b.begin_dispatch(predicted_s)
            try:
                resp = b.client.predict(doc)
            except (OSError, ValueError):
                resp = None
            finally:
                b.end_dispatch(predicted_s)
            if resp is None:
                continue
            if resp["status"] == 503:
                if resp.get("retry_after") is not None:
                    retry_hint = max(retry_hint or 0.0, resp["retry_after"])
                continue
            served = b
            break

        if served is None:
            # Every candidate shed or died (or none existed).
            reason = "unavailable"
            backend_idx = order[-1].index if order else None
            self.metrics.record_failure(reason, backend=backend_idx)
            retry_after = max(retry_hint or 0.0, float(self.cfg.retry_after_s))
            if attempts and self.telemetry is not None:
                self.telemetry.emit_fleet_route(
                    order[-1].index,
                    "spillover" if attempts > 1 else "least_loaded",
                    bucket=bucket, predicted_s=predicted_s,
                    attempts=attempts, canary=take_canary, status=503)
            return (503, {"error": reason,
                          "detail": f"all {attempts} candidate backend(s) "
                                    f"shed or unreachable"}, retry_after)

        status = resp["status"]
        if status == 200:
            self.metrics.record_response(served.index, predicted_s,
                                         canary=take_canary)
        else:
            body = resp.get("body")
            reason = (body.get("error") if isinstance(body, dict)
                      else None) or f"http_{status}"
            self.metrics.record_failure(reason, backend=served.index)
        if self.telemetry is not None:
            self.telemetry.emit_fleet_route(
                served.index,
                "canary" if take_canary
                else ("spillover" if attempts > 1 else "least_loaded"),
                bucket=bucket, queue_depth=served.snapshot()["queue_depth"],
                predicted_s=predicted_s, attempts=attempts,
                canary=take_canary, status=status)
        if take_canary and status == 200:
            self._shadow_mirror(doc, resp, cst, bucket, predicted_s)
        return (status, resp.get("body") or {}, resp.get("retry_after"))

    def _shadow_mirror(self, doc: Dict[str, Any], resp: Dict[str, Any],
                       cst: Dict[str, Any], bucket: Optional[int],
                       predicted_s: float) -> None:
        """Mirror one canary-served request to the incumbent and feed
        the EPE gate. Router-internal traffic: its own counters and a
        ``shadow`` route event, never the client ledger. Synchronous on
        the canary request's thread — the comparison needs both flows,
        and a canary-fraction latency tax is the honest price of the
        gate."""
        baseline = self.backends[cst["baseline_backend"]]
        if not baseline.in_rotation:
            return
        self.metrics.record_shadow()
        baseline.begin_dispatch(predicted_s)
        try:
            shadow = baseline.client.predict(doc)
        except (OSError, ValueError):
            shadow = None
        finally:
            baseline.end_dispatch(predicted_s)
        if self.telemetry is not None:
            self.telemetry.emit_fleet_route(
                baseline.index, "shadow", bucket=bucket,
                predicted_s=predicted_s, attempts=1, canary=True,
                status=shadow["status"] if shadow else 0)
        if not shadow or shadow["status"] != 200:
            return
        try:
            verdict = self.canary.record(resp["body"]["flow"],
                                         shadow["body"]["flow"])
        except (KeyError, TypeError, ValueError):
            return
        if verdict is not None and self.telemetry is not None:
            self.telemetry.emit_canary_verdict(
                verdict["verdict"], verdict["epe"], verdict["bound"],
                rel_epe=verdict["rel_epe"], rel_bound=verdict["rel_bound"],
                samples=verdict["samples"], fraction=verdict["fraction"],
                canary_backend=verdict["canary_backend"],
                baseline_backend=verdict["baseline_backend"])

    # --------------------------------------------------------------- admin --

    def admin_reload_doc(self, doc: Dict[str, Any]
                         ) -> Tuple[int, Dict[str, Any]]:
        """``POST /admin/reload`` body -> (status, response). Fans the
        swap out sequentially (capacity never hits zero mid-rollout);
        ``"backend": i`` restricts it, ``"canary": true`` additionally
        arms the canary gate on that backend."""
        ckpt = doc.get("ckpt")
        if not isinstance(ckpt, str) or not ckpt:
            return (400, {"error": "bad_request",
                          "detail": "body must carry 'ckpt': <path>"})
        try:
            drain_s = float(doc.get("drain_timeout_s", 30.0))
        except (TypeError, ValueError):
            return (400, {"error": "bad_request",
                          "detail": "drain_timeout_s must be a number"})
        backend = doc.get("backend")
        canary = bool(doc.get("canary", False))
        if backend is not None and not (
                isinstance(backend, int)
                and 0 <= backend < len(self.backends)):
            return (400, {"error": "bad_request",
                          "detail": f"backend must be 0.."
                                    f"{len(self.backends) - 1}"})
        if canary and backend is None:
            return (400, {"error": "bad_request",
                          "detail": "canary swap needs 'backend': <index>"})
        targets = ([self.backends[backend]] if backend is not None
                   else [b for b in self.backends if b.in_rotation])
        if not targets:
            return (503, {"error": "unavailable",
                          "detail": "no backend in rotation to swap"})
        rows = []
        worst = 200
        for b in targets:
            try:
                resp = b.client.admin_reload(ckpt, drain_timeout_s=drain_s)
                rows.append({"backend": b.index, "status": resp["status"],
                             "report": resp["body"]})
                if resp["status"] != 200:
                    worst = max(worst, resp["status"])
            except (OSError, ValueError) as e:
                rows.append({"backend": b.index, "status": 0,
                             "report": {"error": "unreachable",
                                        "detail": str(e)}})
                worst = max(worst, 502)
        out: Dict[str, Any] = {"swapped": rows}
        if canary and worst == 200:
            others = [b.index for b in self.backends
                      if b.index != backend and b.in_rotation]
            if not others:
                out["canary"] = {"armed": False,
                                 "detail": "no incumbent backend to "
                                           "compare against"}
            else:
                self.arm_canary(backend, baseline=others[0])
                out["canary"] = self.canary.status()
        return (worst, out)

    def arm_canary(self, canary_backend: int, baseline: int) -> None:
        self.canary.arm(canary_backend, baseline)
        for b in self.backends:
            b.set_canary(b.index == canary_backend)

    def disarm_canary(self) -> None:
        self.canary.disarm()
        for b in self.backends:
            b.set_canary(False)

    def admin_canary_doc(self, doc: Dict[str, Any]
                         ) -> Tuple[int, Dict[str, Any]]:
        """``POST /admin/canary``: ``{"backend": i}`` arms (baseline =
        lowest-index other in-rotation backend), ``{"disarm": true}``
        disarms; either way the response is the canary status block."""
        if doc.get("disarm"):
            self.disarm_canary()
            return (200, self.canary.status())
        backend = doc.get("backend")
        if not (isinstance(backend, int)
                and 0 <= backend < len(self.backends)):
            return (400, {"error": "bad_request",
                          "detail": f"backend must be 0.."
                                    f"{len(self.backends) - 1}"})
        others = [b.index for b in self.backends
                  if b.index != backend and b.in_rotation]
        if not others:
            return (409, {"error": "no_baseline",
                          "detail": "canary needs an in-rotation "
                                    "incumbent to compare against"})
        self.arm_canary(backend, baseline=others[0])
        return (200, self.canary.status())

    # ------------------------------------------------------------- healthz --

    def health_doc(self) -> Dict[str, Any]:
        rows = [b.snapshot() for b in self.backends]
        in_rotation = [r for r in rows
                       if r["state"] in ("healthy", "degraded")]
        if not in_rotation:
            status = "unavailable"
        elif all(r["state"] == "healthy" for r in rows):
            status = "ok"
        else:
            status = "degraded"
        return {
            "status": status,
            "backends": rows,
            "buckets": self.buckets(),
            "canary": self.canary.status(),
            "fleet": {
                "poll_interval_s": self.cfg.poll_interval_s,
                "retry_after_s": self.cfg.retry_after_s,
                "cost_surface": self.cost_surface is not None,
            },
            # The whole ledger rides along so one poll of one endpoint
            # can check the reconciliation identity mid-chaos.
            "metrics": self.metrics.snapshot(),
        }


def build_fleet(targets, *, cfg: Optional[FleetConfig] = None,
                telemetry=None, cost_surface=None,
                host: str = "127.0.0.1", port: int = 0,
                quiet: bool = True) -> FleetRouter:
    """The one canonical fleet assembly (the ``build_service``
    counterpart): targets may be "host:port" strings, started
    ``ServeHTTPServer`` objects, or :class:`BackendClient` instances.
    Returns an unstarted router (``.start()`` / ``.shutdown()``)."""
    return FleetRouter(targets, cfg=cfg, telemetry=telemetry,
                       cost_surface=cost_surface, host=host, port=port,
                       quiet=quiet)
