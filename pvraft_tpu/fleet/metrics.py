"""Fleet-tier counters: one lock, one reconciliation identity.

The serve-metrics discipline one level up: every mutation of the
request ledger happens under a single lock, so the identity

    requests_total == responses_total + sum(rejected.values()) + in_flight

holds at EVERY snapshot, not just at rest — ``scripts/fleet_chaos.py``
polls it mid-load and refuses to write evidence if it ever breaks.
Requests are counted once at ingress (``record_submit``); each reaches
exactly one terminal record (``record_response`` /
``record_failure``). Spillover attempts and canary shadow mirrors are
*dispatch* facts, counted in their own counters and per-backend rows,
never in the client-facing ledger (a request that spilled over twice is
still one request).

Prometheus exposition reuses the serve renderer's ``_PromDoc`` (HELP/
TYPE once per family) under a ``pvraft_fleet_*`` namespace; per-backend
health renders as the supervisor-style one-hot state gauge over
``REPLICA_STATES``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.obs.events import REPLICA_STATES
from pvraft_tpu.serve.metrics import PROM_CONTENT_TYPE, _PromDoc

__all__ = ["FleetMetrics", "PROM_CONTENT_TYPE"]


class FleetMetrics:
    """Thread-safe fleet request ledger + per-backend dispatch counters."""

    def __init__(self):
        self._lock = ordered_lock("FleetMetrics._lock")
        self.requests_total = 0      # guarded-by: _lock
        self.responses_total = 0     # guarded-by: _lock
        self.in_flight = 0           # guarded-by: _lock
        self.rejected: Dict[str, int] = {}  # guarded-by: _lock
        self.spillovers_total = 0    # guarded-by: _lock
        self.canary_total = 0        # guarded-by: _lock
        self.shadow_total = 0        # guarded-by: _lock
        self.predicted_device_seconds_total = 0.0  # guarded-by: _lock
        # backend index -> {"responses", "failures", "predicted_s"}
        self.per_backend: Dict[int, Dict[str, Any]] = {}  # guarded-by: _lock

    def record_submit(self) -> None:
        with self._lock:
            self.requests_total += 1
            self.in_flight += 1

    def record_response(self, backend: int, predicted_s: float = 0.0,
                        canary: bool = False) -> None:
        with self._lock:
            self.responses_total += 1
            self.in_flight -= 1
            self.predicted_device_seconds_total += predicted_s
            if canary:
                self.canary_total += 1
            slot = self.per_backend.setdefault(
                int(backend),
                {"responses": 0, "failures": 0, "predicted_s": 0.0})
            slot["responses"] += 1
            slot["predicted_s"] += predicted_s

    def record_failure(self, reason: str,
                       backend: Optional[int] = None) -> None:
        """Terminal non-200 outcome for an ACCEPTED request (every
        ingress request was accepted into the ledger — the router has no
        pre-acceptance reject path; a body it cannot parse is a
        ``bad_request`` failure)."""
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1
            self.in_flight -= 1
            if backend is not None:
                slot = self.per_backend.setdefault(
                    int(backend),
                    {"responses": 0, "failures": 0, "predicted_s": 0.0})
                slot["failures"] += 1

    def record_spillover(self) -> None:
        with self._lock:
            self.spillovers_total += 1

    def record_shadow(self) -> None:
        with self._lock:
            self.shadow_total += 1

    def current_in_flight(self) -> int:
        with self._lock:
            return self.in_flight

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "in_flight": self.in_flight,
                "rejected": dict(self.rejected),
                "spillovers_total": self.spillovers_total,
                "canary_total": self.canary_total,
                "shadow_total": self.shadow_total,
                "predicted_device_seconds_total": round(
                    self.predicted_device_seconds_total, 6),
                "per_backend": {
                    str(i): {"responses": s["responses"],
                             "failures": s["failures"],
                             "predicted_s": round(s["predicted_s"], 6)}
                    for i, s in sorted(self.per_backend.items())},
            }

    def prometheus(self, backends: List[Dict[str, Any]]) -> str:
        """The ``pvraft_fleet_*`` exposition. ``backends`` is the list
        of :meth:`Backend.snapshot` rows (sampled by the caller outside
        this lock — backend locks and the metrics lock never nest)."""
        snap = self.snapshot()
        doc = _PromDoc()
        doc.family("pvraft_fleet_requests_total", "counter",
                   "Requests received by the router "
                   "(== responses + rejected + in_flight).")
        doc.sample("pvraft_fleet_requests_total", snap["requests_total"])
        doc.family("pvraft_fleet_responses_total", "counter",
                   "Requests answered 200 via some backend.")
        doc.sample("pvraft_fleet_responses_total", snap["responses_total"])
        doc.family("pvraft_fleet_in_flight", "gauge",
                   "Requests without a recorded terminal outcome yet.")
        doc.sample("pvraft_fleet_in_flight", snap["in_flight"])
        doc.family("pvraft_fleet_rejected_total", "counter",
                   "Terminal non-200 outcomes by reason.")
        for reason, count in sorted(snap["rejected"].items()):
            doc.sample("pvraft_fleet_rejected_total", count,
                       {"reason": reason})
        doc.family("pvraft_fleet_spillovers_total", "counter",
                   "Dispatch attempts re-routed to another backend "
                   "after a shed or connect failure.")
        doc.sample("pvraft_fleet_spillovers_total", snap["spillovers_total"])
        doc.family("pvraft_fleet_canary_requests_total", "counter",
                   "Client requests served by the canary backend.")
        doc.sample("pvraft_fleet_canary_requests_total", snap["canary_total"])
        doc.family("pvraft_fleet_shadow_requests_total", "counter",
                   "Router-internal shadow mirrors to the incumbent "
                   "(the canary EPE comparison traffic).")
        doc.sample("pvraft_fleet_shadow_requests_total", snap["shadow_total"])
        doc.family("pvraft_fleet_predicted_device_seconds_total", "counter",
                   "Cost-surface-predicted device-seconds routed "
                   "(0 while no surface is armed).")
        doc.sample("pvraft_fleet_predicted_device_seconds_total",
                   snap["predicted_device_seconds_total"])
        doc.family("pvraft_fleet_backend_responses_total", "counter",
                   "200s served per backend.")
        for i, slot in sorted(snap["per_backend"].items()):
            doc.sample("pvraft_fleet_backend_responses_total",
                       slot["responses"], {"backend": i})
        doc.family("pvraft_fleet_backend_failures_total", "counter",
                   "Terminal failures attributed per backend.")
        for i, slot in sorted(snap["per_backend"].items()):
            doc.sample("pvraft_fleet_backend_failures_total",
                       slot["failures"], {"backend": i})
        doc.family("pvraft_fleet_backend_queue_depth", "gauge",
                   "Polled backend in-flight count (its /healthz).")
        for row in backends:
            doc.sample("pvraft_fleet_backend_queue_depth",
                       row["queue_depth"], {"backend": row["backend"]})
        doc.family("pvraft_fleet_backend_outstanding", "gauge",
                   "Router-side dispatches currently open per backend.")
        for row in backends:
            doc.sample("pvraft_fleet_backend_outstanding",
                       row["outstanding"], {"backend": row["backend"]})
        doc.family("pvraft_fleet_backend_state", "gauge",
                   "Poll-driven health state per backend: 1 for the "
                   "current state, 0 otherwise (the replica "
                   "supervisor's vocabulary, one tier up).")
        for row in backends:
            for state in REPLICA_STATES:
                doc.sample("pvraft_fleet_backend_state",
                           1 if row["state"] == state else 0,
                           {"backend": row["backend"], "state": state})
        return doc.render()
