"""Live canary: deterministic traffic interleave + EPE promotion gate.

When armed on a backend (one that just hot-swapped to candidate
weights), the router sends a configurable fraction of live traffic to
it. Each canary-served request is also shadow-mirrored to the incumbent
backend, and the two flow fields are compared EPE-style — mean endpoint
error (L2 per point, scene units), absolute and relative to the
incumbent's mean flow magnitude. After ``min_samples`` comparisons the
controller renders a verdict: **promote** iff both means sit inside the
pinned bounds, else **reject**. The bounds default to the bf16-promotion
precedent (``SERVE_BF16_EPE_BOUND`` / ``SERVE_BF16_REL_EPE_BOUND`` in
``programs/geometries.py``): a weight swap that moves predictions more
than a precision change would is not silently promoted.

The interleave is a deterministic stride, not a coin flip: request k is
canary iff ``floor((k+1)*f) > floor(k*f)`` — exactly ``fraction`` of
any long window, no RNG stream (the determinism plane's vocabulary
stays closed; detcheck sees no new entropy source), and replayable in
tests.

Locking: all state under one ``ordered_lock``; verdicts are *decided*
under the lock and *returned* for the caller to emit after release.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.obs.events import CANARY_VERDICTS
from pvraft_tpu.programs.geometries import FLEET_DEFAULTS

__all__ = ["CanaryController", "flow_epe"]


def flow_epe(candidate: List[List[float]],
             baseline: List[List[float]]) -> Dict[str, float]:
    """Mean endpoint error between two flow fields (JSON ``flow``
    payloads: N x 3 nested lists) plus the baseline's mean magnitude —
    the EPE accumulator one comparison contributes. Raises ValueError
    on a shape mismatch (the comparison would be meaningless)."""
    if len(candidate) != len(baseline) or not baseline:
        raise ValueError(
            f"flow shape mismatch: candidate n={len(candidate)} "
            f"baseline n={len(baseline)}")
    epe = mag = 0.0
    for c, b in zip(candidate, baseline):
        epe += math.sqrt(sum((ci - bi) ** 2 for ci, bi in zip(c, b)))
        mag += math.sqrt(sum(bi ** 2 for bi in b))
    n = float(len(baseline))
    return {"epe": epe / n, "mag": mag / n}


class CanaryController:
    """Arms/disarms the canary leg and renders the promotion verdict."""

    def __init__(self, fraction: float = FLEET_DEFAULTS["canary_fraction"],
                 min_samples: int = FLEET_DEFAULTS["canary_min_samples"],
                 epe_bound: float = FLEET_DEFAULTS["canary_epe_bound"],
                 rel_epe_bound: float =
                 FLEET_DEFAULTS["canary_rel_epe_bound"]):
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"canary fraction must be in (0, 1]: {fraction}")
        self.fraction = float(fraction)
        self.min_samples = int(min_samples)
        self.epe_bound = float(epe_bound)
        self.rel_epe_bound = float(rel_epe_bound)
        self._lock = ordered_lock("fleet.CanaryController._lock")
        self.armed = False               # guarded-by: _lock
        self.canary_backend: Optional[int] = None    # guarded-by: _lock
        self.baseline_backend: Optional[int] = None  # guarded-by: _lock
        self._stride = 0                 # guarded-by: _lock
        self._samples = 0                # guarded-by: _lock
        self._epe_sum = 0.0              # guarded-by: _lock
        self._mag_sum = 0.0              # guarded-by: _lock
        self.verdict: Optional[Dict[str, Any]] = None  # guarded-by: _lock

    def arm(self, canary_backend: int, baseline_backend: int) -> None:
        """Start a fresh canary window: counters reset, verdict
        cleared. Arming against itself is a config error."""
        if int(canary_backend) == int(baseline_backend):
            raise ValueError("canary and baseline must be distinct backends")
        with self._lock:
            self.armed = True
            self.canary_backend = int(canary_backend)
            self.baseline_backend = int(baseline_backend)
            self._stride = self._samples = 0
            self._epe_sum = self._mag_sum = 0.0
            self.verdict = None

    def disarm(self) -> None:
        with self._lock:
            self.armed = False
            self.canary_backend = self.baseline_backend = None

    def take(self) -> bool:
        """Deterministic stride decision for the next client request:
        True = route it to the canary backend. Always False once a
        verdict is in (the window is closed; promotion/rollback is the
        operator's move)."""
        with self._lock:
            if not self.armed or self.verdict is not None:
                return False
            k = self._stride
            self._stride += 1
            return (math.floor((k + 1) * self.fraction)
                    > math.floor(k * self.fraction))

    def record(self, canary_flow: List[List[float]],
               baseline_flow: List[List[float]]
               ) -> Optional[Dict[str, Any]]:
        """Accumulate one canary-vs-incumbent comparison; returns the
        verdict dict exactly once — on the call that crosses
        ``min_samples`` — for the caller to emit (after this lock is
        released; telemetry never nests under controller state)."""
        contrib = flow_epe(canary_flow, baseline_flow)
        with self._lock:
            if not self.armed or self.verdict is not None:
                return None
            self._samples += 1
            self._epe_sum += contrib["epe"]
            self._mag_sum += contrib["mag"]
            if self._samples < self.min_samples:
                return None
            epe = self._epe_sum / self._samples
            mean_mag = self._mag_sum / self._samples
            rel = epe / mean_mag if mean_mag > 0 else float("inf")
            verdict = ("promote" if epe <= self.epe_bound
                       and rel <= self.rel_epe_bound else "reject")
            assert verdict in CANARY_VERDICTS
            self.verdict = {
                "verdict": verdict,
                "epe": round(epe, 6),
                "bound": self.epe_bound,
                "rel_epe": round(rel, 6),
                "rel_bound": self.rel_epe_bound,
                "samples": self._samples,
                "fraction": self.fraction,
                "canary_backend": self.canary_backend,
                "baseline_backend": self.baseline_backend,
            }
            return dict(self.verdict)

    def status(self) -> Dict[str, Any]:
        """The /healthz canary block."""
        with self._lock:
            return {
                "armed": self.armed,
                "canary_backend": self.canary_backend,
                "baseline_backend": self.baseline_backend,
                "fraction": self.fraction,
                "min_samples": self.min_samples,
                "epe_bound": self.epe_bound,
                "rel_epe_bound": self.rel_epe_bound,
                "samples": self._samples,
                "verdict": dict(self.verdict) if self.verdict else None,
            }
