"""One fleet backend: HTTP client + polled health state for a serve host.

A backend is a whole ``serve.build_service`` replica pool reachable at
``host:port``. The router never sees its replicas — it sees the pool's
``/healthz`` (supervision summary, weights provenance, in-flight count)
and its ``/predict`` outcomes. Health is driven from the poll loop
(:meth:`FleetRouter.poll_once`), not from dispatch outcomes: a shed
request (503) is a *routing* signal (spill over), while a backend that
stops answering ``/healthz`` is a *health* signal (degrade, quarantine).

The state machine reuses the replica supervisor's vocabulary
(``obs.events.REPLICA_STATES``): healthy -> degraded (``degraded_after``
consecutive poll failures, still routable) -> quarantined
(``quarantine_after``, out of rotation) -> probing (the next poll of a
quarantined backend) -> healthy on a successful probe. Thresholds come
from ``programs/geometries.FLEET_DEFAULTS`` via :class:`FleetConfig`.

Locking: every mutable field is guarded by the per-backend ``_lock``
(``ordered_lock`` — a plain Lock in production, order-checked under
``PVRAFT_CHECKS=1``). Transitions are *decided* under the lock and
*returned* to the caller, which acts on them (logs, events) after
release — the serve/supervisor discipline.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.obs.events import REPLICA_STATES
from pvraft_tpu.serve.loadgen import _endpoints, _get_json, _post_json

__all__ = ["BackendClient", "Backend"]

assert "healthy" in REPLICA_STATES  # the vocabulary this module speaks


class BackendClient:
    """Thin stdlib HTTP client for one serve host.

    Wraps the loadgen client helpers (the one shared HTTP client path —
    loadgen, serve_ab and the fleet router must not grow three subtly
    different readings of ``Retry-After``). No jax, no state: safe to
    call from any router thread concurrently (each call opens its own
    connection)."""

    def __init__(self, host: str, port: int,
                 predict_timeout_s: float = 60.0,
                 poll_timeout_s: float = 5.0):
        self.host = host
        self.port = int(port)
        self.predict_timeout_s = predict_timeout_s
        self.poll_timeout_s = poll_timeout_s

    @classmethod
    def from_target(cls, target: Any, predict_timeout_s: float = 60.0,
                    poll_timeout_s: float = 5.0) -> "BackendClient":
        """Accepts everything ``loadgen._endpoints`` does: "host:port"
        strings (URL spellings included), (host, port) tuples, or an
        object with ``host``/``port`` (e.g. a started server)."""
        (host, port), = _endpoints(None, [target])
        return cls(host, port, predict_timeout_s=predict_timeout_s,
                   poll_timeout_s=poll_timeout_s)

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def predict(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Forward one predict body; returns the loadgen client shape
        ``{"status", "body", "retry_after", "trace_id"}``. Raises
        ``OSError`` on connect/timeout failures — the router's spillover
        signal."""
        return _post_json(self.host, self.port, "/predict", doc,
                          timeout=self.predict_timeout_s)

    def healthz(self) -> Dict[str, Any]:
        return _get_json(self.host, self.port, "/healthz",
                         timeout=self.poll_timeout_s)

    def metrics(self) -> Dict[str, Any]:
        return _get_json(self.host, self.port, "/metrics",
                         timeout=self.poll_timeout_s)

    def admin_reload(self, ckpt: str,
                     drain_timeout_s: float = 30.0) -> Dict[str, Any]:
        """``POST /admin/reload`` against this backend (the zero-
        downtime weight hot-swap). Generous timeout: the backend holds
        the response until in-flight batches drained."""
        return _post_json(self.host, self.port, "/admin/reload",
                          {"ckpt": ckpt, "drain_timeout_s": drain_timeout_s},
                          timeout=self.predict_timeout_s
                          + max(drain_timeout_s, 0.0))


class Backend:
    """Router-side record of one backend: client + health state + load
    accounting."""

    def __init__(self, index: int, client: BackendClient,
                 degraded_after: int = 1, quarantine_after: int = 3):
        self.index = int(index)
        self.client = client
        self.degraded_after = int(degraded_after)
        self.quarantine_after = int(quarantine_after)
        self._lock = ordered_lock("fleet.Backend._lock")
        self.state = "healthy"          # guarded-by: _lock
        self.consecutive_failures = 0   # guarded-by: _lock
        self.polls_total = 0            # guarded-by: _lock
        self.last_poll_ok = None        # guarded-by: _lock (monotonic ts)
        self.last_health: Optional[Dict[str, Any]] = None  # guarded-by: _lock
        # Polled load signal: the backend's /healthz in_flight (accepted
        # requests without a recorded outcome — queued + executing).
        self.queue_depth = 0            # guarded-by: _lock
        # Router-side load accounting: dispatches this router currently
        # has open against the backend, and their cost-surface-predicted
        # device-seconds (0.0 each when no surface is armed).
        self.outstanding = 0            # guarded-by: _lock
        self.outstanding_s = 0.0        # guarded-by: _lock
        # True while this backend serves canary weights (set by the
        # admin plane, read by the routing decision).
        self.canary = False             # guarded-by: _lock

    # ------------------------------------------------------------- health --

    def begin_probe(self) -> Optional[Tuple[str, str]]:
        """Mark a quarantined backend probing (the poll loop calls this
        right before it polls one). Returns the transition or None."""
        with self._lock:
            if self.state != "quarantined":
                return None
            self.state = "probing"
            return ("quarantined", "probing")

    def poll_succeeded(self, health: Dict[str, Any]
                       ) -> Optional[Tuple[str, str]]:
        """Record one successful ``/healthz`` poll; any non-healthy
        state recovers (probing included — a quarantined backend that
        answers its probe rejoins the rotation, the supervisor's revival
        semantics). Returns the transition or None."""
        depth = health.get("in_flight")
        with self._lock:
            self.polls_total += 1
            self.consecutive_failures = 0
            self.last_poll_ok = time.monotonic()
            self.last_health = health
            self.queue_depth = int(depth) if isinstance(depth, int) else 0
            if self.state == "healthy":
                return None
            old, self.state = self.state, "healthy"
            return (old, "healthy")

    def poll_failed(self) -> Optional[Tuple[str, str]]:
        """Record one failed poll (connect error, timeout, non-JSON).
        Returns the transition or None."""
        with self._lock:
            self.polls_total += 1
            self.consecutive_failures += 1
            old = self.state
            if old == "probing":
                # A failed probe re-quarantines; failures keep counting.
                self.state = "quarantined"
            elif self.consecutive_failures >= self.quarantine_after:
                self.state = "quarantined"
            elif self.consecutive_failures >= self.degraded_after:
                self.state = "degraded"
            return (old, self.state) if self.state != old else None

    @property
    def in_rotation(self) -> bool:
        """Routable: healthy or degraded (degraded still serves — the
        supervisor's 'visibly unhealthy, not dead' semantics)."""
        with self._lock:
            return self.state in ("healthy", "degraded")

    # --------------------------------------------------------------- load --

    def begin_dispatch(self, predicted_s: float) -> None:
        with self._lock:
            self.outstanding += 1
            self.outstanding_s += predicted_s

    def end_dispatch(self, predicted_s: float) -> None:
        with self._lock:
            self.outstanding -= 1
            self.outstanding_s = max(0.0, self.outstanding_s - predicted_s)

    def load_score(self, predicted_s: float) -> Tuple[float, int, int]:
        """Sort key for routing: predicted outstanding device-seconds
        (router-side in-flight plus the polled backend queue priced at
        this request's predicted cost), then raw counts, then index (a
        stable tie-break keeps the no-surface path deterministic)."""
        with self._lock:
            priced = self.outstanding_s + self.queue_depth * predicted_s
            return (priced, self.outstanding + self.queue_depth, self.index)

    def snapshot(self) -> Dict[str, Any]:
        """One /healthz row (and the Prometheus gauge source)."""
        with self._lock:
            health = self.last_health or {}
            return {
                "backend": self.index,
                "endpoint": self.client.endpoint,
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "polls_total": self.polls_total,
                "queue_depth": self.queue_depth,
                "outstanding": self.outstanding,
                "outstanding_s": round(self.outstanding_s, 6),
                "canary": self.canary,
                # Pass-through provenance from the backend's own
                # /healthz: weights digest/epoch (the hot-swap evidence)
                # and the pool supervision summary.
                "weights": health.get("weights"),
                "pool": health.get("pool"),
            }

    def set_canary(self, canary: bool) -> None:
        with self._lock:
            self.canary = bool(canary)

    def is_canary(self) -> bool:
        with self._lock:
            return self.canary

    def buckets(self) -> Optional[List[int]]:
        """The backend's bucket table from its last good poll (None
        before the first one)."""
        with self._lock:
            if self.last_health is None:
                return None
            b = self.last_health.get("buckets")
            return list(b) if isinstance(b, list) else None

    def dtype(self) -> Optional[str]:
        with self._lock:
            if self.last_health is None:
                return None
            d = self.last_health.get("dtype")
            return d if isinstance(d, str) else None
