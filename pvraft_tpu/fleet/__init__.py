"""pvraft_tpu.fleet: routing/fan-out tier over serve replica-pool hosts.

The serving story one level up from ``pvraft_tpu.serve``: N backend
hosts (each a full ``build_service`` replica pool) behind one thin HTTP
router with

- **per-bucket least-predicted-load routing** (polled backend queue
  depth + cost-surface-predicted device-seconds) with spillover on
  shed/unreachable backends and supervisor-vocabulary backend health
  (healthy/degraded/quarantined/probing off polled ``/healthz``),
- **zero-downtime weight hot-swap** — ``POST /admin/reload`` fans out
  sequentially; each backend's engine swaps params into its AOT
  executables with no recompile (the sealed retrace watchdog proves
  it) after draining in-flight batches,
- **a live canary** — a deterministic traffic fraction interleaved to
  the new-weight backend, shadow-mirrored to the incumbent, promotion
  gated on the pinned EPE bounds (the bf16-promotion precedent).

Jax-free throughout: the fleet tier talks HTTP, never tensors.
"""

from pvraft_tpu.fleet.artifact import (  # noqa: F401
    FLEET_CHAOS_SCHEMA,
    validate_fleet_artifact,
)
from pvraft_tpu.fleet.backend import Backend, BackendClient  # noqa: F401
from pvraft_tpu.fleet.canary import CanaryController, flow_epe  # noqa: F401
from pvraft_tpu.fleet.metrics import FleetMetrics  # noqa: F401
from pvraft_tpu.fleet.router import (  # noqa: F401
    FleetConfig,
    FleetRouter,
    build_fleet,
)

__all__ = [
    "FLEET_CHAOS_SCHEMA",
    "validate_fleet_artifact",
    "Backend",
    "BackendClient",
    "CanaryController",
    "flow_epe",
    "FleetMetrics",
    "FleetConfig",
    "FleetRouter",
    "build_fleet",
]
