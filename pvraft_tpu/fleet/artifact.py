"""``pvraft_fleet_chaos/v1``: the fleet chaos-run evidence schema.

One committed artifact (``artifacts/fleet_chaos.json``) proves the
fleet tier's three claims on a real 2-backend run:

1. **Fan-out survives backend loss** — a backend is killed mid-load and
   every client request still resolves (spillover + retry), the ledger
   identity holding at every polled snapshot.
2. **Weight hot-swap is zero-downtime and zero-recompile** — a reload
   lands mid-traffic, the sealed retrace watchdog's counter stays 0 and
   the weights digest provably changes.
3. **The canary gate renders a verdict** — interleaved traffic compared
   EPE-style against the incumbent, promote/reject against the pinned
   bounds.

The generator (``scripts/fleet_chaos.py``) REFUSES to write unless all
three hold; this validator re-checks the same structure on the
committed file, so a hand-edited artifact cannot pass the gate
(``validate-fleet`` stage). The embedded ``load`` block is a complete
``pvraft_serve_load/v1`` document and is re-validated through the serve
validator — one measurement discipline, two tiers.
"""

from __future__ import annotations

from typing import Any, Dict, List

from pvraft_tpu.obs.events import CANARY_VERDICTS
from pvraft_tpu.serve.loadgen import validate_load_artifact

__all__ = ["FLEET_CHAOS_SCHEMA", "validate_fleet_artifact"]

FLEET_CHAOS_SCHEMA = "pvraft_fleet_chaos/v1"

# Phase names, in the order the chaos run executes them.
FLEET_CHAOS_PHASES = ("baseline", "backend_loss", "hot_swap", "canary")


def _phase_index(phases: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    return {p.get("phase"): p for p in phases if isinstance(p, dict)}


def validate_fleet_artifact(doc: Any,
                            path: str = "<fleet_chaos>") -> List[str]:
    """Structural problems with one fleet chaos artifact ([] = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"{path}: not a JSON object"]
    if doc.get("schema") != FLEET_CHAOS_SCHEMA:
        problems.append(
            f"schema must be {FLEET_CHAOS_SCHEMA!r}: {doc.get('schema')!r}")
        return problems

    cfg = doc.get("config")
    if not isinstance(cfg, dict):
        problems.append("config: missing or not an object")
        cfg = {}
    backends = cfg.get("backends")
    if not isinstance(backends, int) or backends < 2:
        problems.append(
            f"config.backends: a fleet chaos run needs >= 2 backends "
            f"(got {backends!r})")
    targets = cfg.get("targets")
    if (not isinstance(targets, list) or not targets
            or not all(isinstance(t, str) and t for t in targets)):
        problems.append("config.targets: must be a non-empty string list")
    elif isinstance(backends, int) and len(targets) != backends:
        problems.append(
            f"config.targets: {len(targets)} entries for "
            f"{backends} backends")
    mix = cfg.get("traffic_mix")
    if not isinstance(mix, list) or not mix:
        problems.append("config.traffic_mix: missing (the capacity "
                        "plan's per-bucket fractions drive the run)")
    else:
        total = sum(row.get("fraction", 0) for row in mix
                    if isinstance(row, dict))
        if not 0.99 <= total <= 1.01:
            problems.append(
                f"config.traffic_mix: fractions sum to {total}, not 1")

    load = doc.get("load")
    if not isinstance(load, dict):
        problems.append("load: missing embedded pvraft_serve_load/v1 block")
    else:
        problems.extend(f"load.{p}" for p in validate_load_artifact(
            load, path=f"{path}#load"))

    phases = doc.get("phases")
    if not isinstance(phases, list):
        problems.append("phases: missing or not a list")
        phases = []
    by_name = _phase_index(phases)
    names = [p.get("phase") for p in phases if isinstance(p, dict)]
    if tuple(names) != FLEET_CHAOS_PHASES:
        problems.append(
            f"phases: must be {list(FLEET_CHAOS_PHASES)} in order "
            f"(got {names})")

    loss = by_name.get("backend_loss", {})
    if not isinstance(loss.get("killed_backend"), int):
        problems.append("phases[backend_loss].killed_backend: missing")
    if not (isinstance(loss.get("spillovers"), int)
            and loss["spillovers"] > 0):
        problems.append(
            "phases[backend_loss].spillovers: must be > 0 (losing a "
            "backend mid-load must visibly re-route work)")
    if loss.get("resolved") is not True:
        problems.append(
            "phases[backend_loss].resolved: every request of the loss "
            "phase must have resolved (ok or bounded-retry rejected)")

    swap_phase = by_name.get("hot_swap", {})
    swapped = (swap_phase.get("swap") or {}).get("swapped")
    if not isinstance(swapped, list) or not swapped:
        problems.append("phases[hot_swap].swap.swapped: missing rows")
    else:
        for row in swapped:
            if not isinstance(row, dict) or row.get("status") != 200:
                problems.append(
                    f"phases[hot_swap].swap.swapped: non-200 row {row!r}")
                continue
            report = row.get("report") or {}
            if not report.get("digest"):
                problems.append(
                    "phases[hot_swap]: swap report carries no digest")
            elif report.get("digest") == report.get("previous_digest"):
                problems.append(
                    "phases[hot_swap]: digest unchanged — no swap "
                    "actually happened")

    canary_phase = by_name.get("canary", {})
    verdict = canary_phase.get("verdict")
    if not isinstance(verdict, dict):
        problems.append("phases[canary].verdict: missing")
    else:
        if verdict.get("verdict") not in CANARY_VERDICTS:
            problems.append(
                f"phases[canary].verdict.verdict: "
                f"{verdict.get('verdict')!r} not in {CANARY_VERDICTS}")
        if not (isinstance(verdict.get("samples"), int)
                and verdict["samples"] >= 1):
            problems.append("phases[canary].verdict.samples: must be >= 1")

    rec = doc.get("reconciliation")
    if not isinstance(rec, dict):
        problems.append("reconciliation: missing")
    else:
        if rec.get("holds") is not True:
            problems.append(
                "reconciliation.holds: the request identity must have "
                "held at every polled snapshot")
        if not (isinstance(rec.get("snapshots"), int)
                and rec["snapshots"] >= 3):
            problems.append(
                "reconciliation.snapshots: need >= 3 mid-run polls "
                "(an unpolled identity proves nothing)")

    for key in ("recompiles", "watchdog_trips"):
        if doc.get(key) != 0:
            problems.append(
                f"{key}: must be 0 — the hot-swap claim is zero "
                f"recompiles under the sealed watchdog "
                f"(got {doc.get(key)!r})")
    return problems
