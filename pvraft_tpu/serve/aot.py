"""Ahead-of-time compile + memory-analysis machinery.

One `lower -> compile -> memory_analysis` path shared by the serve
engine (which AOT-compiles every (bucket, batch) predict program at
startup, before the first request can hit a compile stall) and
``scripts/aot_readiness.py`` (which certifies the same programs for the
v5e topology before a TPU claim). Keeping them on one code path means
claim-day readiness and the live service report compile cost and HBM
fit the same way.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple


@dataclasses.dataclass
class AotProgram:
    """One compiled program plus its startup-cost evidence."""

    name: str
    compiled: Any                      # jax.stages.Compiled
    lower_s: float
    compile_s: float
    memory: Optional[Dict[str, Any]]   # memory_analysis() output

    def __call__(self, *args):
        return self.compiled(*args)

    def report(self) -> Dict[str, Any]:
        """JSON-safe record (serve_compile events, /healthz, artifacts)."""
        return {
            "name": self.name,
            "lower_s": round(self.lower_s, 3),
            "compile_s": round(self.compile_s, 3),
            "memory": self.memory,
        }


def memory_analysis(compiled,
                    hbm_limit_bytes: Optional[int] = None
                    ) -> Optional[Dict[str, Any]]:
    """XLA memory analysis of a compiled executable as a plain dict:
    argument/output/temp/generated-code/alias bytes, a live-bytes
    estimate, and (when ``hbm_limit_bytes`` is given) whether that
    estimate fits. Returns an ``{"error": ...}`` dict on builds that
    cannot analyze (some topology executables), never raises."""
    try:
        m = compiled.memory_analysis()
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    if m is None:
        return None
    out: Dict[str, Any] = {}
    for k in ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "generated_code_size_in_bytes",
              "alias_size_in_bytes"):
        v = getattr(m, k, None)
        if v is not None:
            out[k] = int(v)
    total = (out.get("argument_size_in_bytes", 0)
             + out.get("output_size_in_bytes", 0)
             + out.get("temp_size_in_bytes", 0)
             - out.get("alias_size_in_bytes", 0))
    out["live_bytes_estimate"] = total
    if hbm_limit_bytes is not None:
        out["fits_hbm"] = total < hbm_limit_bytes
    return out


def aot_compile(
    name: str,
    fn: Callable,
    args: Tuple,
    donate_argnums: Tuple[int, ...] = (),
    in_shardings=None,
    hbm_limit_bytes: Optional[int] = None,
) -> AotProgram:
    """``jit(fn).lower(*args).compile()`` with per-stage timing and the
    memory analysis attached. ``args`` are ``jax.ShapeDtypeStruct``s (or
    concrete arrays; only shapes/dtypes are read)."""
    import jax

    kwargs: Dict[str, Any] = {"donate_argnums": donate_argnums}
    if in_shardings is not None:
        kwargs["in_shardings"] = in_shardings
    jitted = jax.jit(fn, **kwargs)
    t0 = time.monotonic()
    lowered = jitted.lower(*args)
    lower_s = time.monotonic() - t0
    t1 = time.monotonic()
    compiled = lowered.compile()
    compile_s = time.monotonic() - t1
    return AotProgram(
        name=name,
        compiled=compiled,
        lower_s=lower_s,
        compile_s=compile_s,
        memory=memory_analysis(compiled, hbm_limit_bytes=hbm_limit_bytes),
    )
