"""Stdlib HTTP front-end: JSON/msgpack ``/predict`` + health + metrics.

A ``ThreadingHTTPServer`` (one thread per connection) over the
micro-batcher — no web framework, nothing to install. Handler threads
block on their request's completion event while the batcher workers do
the actual dispatch, so concurrency is bounded by queue depth, not by
the HTTP layer.

Endpoints:

  ``POST /predict``
      JSON body ``{"pc1": [[x,y,z],...], "pc2": [[x,y,z],...]}`` ->
      ``{"flow": [[x,y,z],...], "n": n}``. With ``Content-Type:
      application/msgpack`` the body is a msgpack map whose ``pc1``/
      ``pc2`` values are raw little-endian float32 bytes (n*3 each);
      the response mirrors that (``flow`` as raw f32 bytes) — the
      fast path, no float->decimal round-trips. Sampled requests
      (``--trace_sample``) carry an ``X-Pvraft-Trace`` response header
      with the trace id; their span tree lands on the event stream.
      Errors: 400 contract violations, 413 too large for every bucket,
      503 queue full / shutting down (explicit backpressure),
      504 predict timeout.
  ``GET /healthz``
      ``{"status": "ok", buckets, batch_sizes, programs: [...compile
      report...], telemetry: {events_path, tracing, trace_sample_every,
      trace_dir}}`` — serving readiness including the AOT evidence and
      the live telemetry/tracing configuration (an operator confirms
      tracing is on without grepping logs).
  ``GET /metrics``
      JSON counters (default, shape-frozen): request/response/reject
      counts, per-bucket queue depth, batch-fill ratio, latency
      histogram (serve/metrics.py). ``?format=prometheus`` renders the
      same store as Prometheus text 0.0.4 (``pvraft_serve_*``) plus the
      trace-fed per-(bucket, stage) histograms and the request-size
      histogram.
  ``GET /debug/trace?seconds=N``
      Captures a ``jax.profiler.trace`` window of N seconds to a fresh
      directory under ``trace_dir`` and returns its path — an XLA
      profile from a LIVE server, no restart. One capture at a time
      (409 while busy); start/stop ride the event stream as
      ``trace_window`` records.
  ``POST /admin/reload``
      Zero-downtime weight hot-swap: body ``{"ckpt": "<path>"}`` loads
      the checkpoint (msgpack or orbax) and swaps it into every replica
      with NO recompile (AOT programs take params as arguments; the
      sealed retrace watchdog proves it) while in-flight batches drain
      on the old params. Returns the swap report (digest, epoch,
      drained count, swap_ms) — also a ``weight_swap`` event. 400 bad
      body / unreadable checkpoint, 409 structure mismatch (a tree that
      would recompile is rejected, never swapped). The ``/healthz``
      ``weights`` block (path, digest, epoch, swaps) observes the swap.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.obs.trace import Tracer
from pvraft_tpu.serve import faults
from pvraft_tpu.serve.batcher import (
    BatcherConfig,
    MicroBatcher,
    PoolUnavailableError,
    QueueFullError,
    ShutdownError,
)
from pvraft_tpu.serve.engine import RequestError
from pvraft_tpu.serve.metrics import PROM_CONTENT_TYPE, ServeMetrics

MSGPACK_CT = "application/msgpack"
JSON_CT = "application/json"

# jax.profiler supports ONE active trace per process, so /debug/trace
# captures serialize process-wide — even across multiple embedded
# ServeHTTPServer instances (the loadgen/test pattern). Acquired
# non-blocking only (409 while busy), so it can never complete a
# deadlock cycle; ordered_lock still records it under PVRAFT_CHECKS=1.
_DEBUG_TRACE_LOCK = ordered_lock("serve.server._DEBUG_TRACE_LOCK")


def _decode_json(body: bytes) -> Tuple[np.ndarray, np.ndarray]:
    try:
        doc = json.loads(body)
    except ValueError as e:
        raise RequestError("bad_request", f"invalid JSON: {e}") from None
    if not isinstance(doc, dict) or "pc1" not in doc or "pc2" not in doc:
        raise RequestError("bad_request", "body must carry 'pc1' and 'pc2'")
    try:
        pc1 = np.asarray(doc["pc1"], np.float32)
        pc2 = np.asarray(doc["pc2"], np.float32)
    except (TypeError, ValueError) as e:
        raise RequestError("bad_request", f"non-numeric cloud: {e}") from None
    return pc1, pc2


def _decode_msgpack(body: bytes) -> Tuple[np.ndarray, np.ndarray]:
    import msgpack

    try:
        doc = msgpack.unpackb(body, raw=False)
    except Exception as e:
        raise RequestError("bad_request", f"invalid msgpack: {e}") from None
    if not isinstance(doc, dict) or "pc1" not in doc or "pc2" not in doc:
        raise RequestError("bad_request", "body must carry 'pc1' and 'pc2'")
    out = []
    for name in ("pc1", "pc2"):
        raw = doc[name]
        if not isinstance(raw, (bytes, bytearray)) or len(raw) % 12:
            raise RequestError(
                "bad_request",
                f"{name} must be raw float32 bytes, length divisible by 12")
        out.append(np.frombuffer(bytes(raw), np.float32).reshape(-1, 3))
    return out[0], out[1]


class _Handler(BaseHTTPRequestHandler):
    # Set by ServeHTTPServer below.
    batcher: MicroBatcher = None  # type: ignore[assignment]
    metrics = None
    tracer: Optional[Tracer] = None
    telemetry = None
    trace_dir: str = ""
    events_path: str = ""
    predict_timeout_s: float = 60.0
    max_body_bytes: int = 1 << 24
    quiet: bool = True
    # 503 Retry-After seconds: one supervisor probe cycle when a
    # supervisor is wired (build_service), else the default.
    retry_after_s: int = 1

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # stdlib default prints every hit
        if not self.quiet:
            super().log_message(fmt, *args)

    # ------------------------------------------------------------ replies --

    def _reply(self, code: int, payload: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        for key, value in getattr(self, "_extra_headers", ()):
            self.send_header(key, value)
        if self.close_connection:
            # The stdlib honors the flag by closing the socket but never
            # advertises it; under HTTP/1.1 a pooled client would reuse
            # the connection and hit ECONNRESET without this header.
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(payload)

    def _reply_json(self, code: int, doc: Dict[str, Any]) -> None:
        self._reply(code, json.dumps(doc).encode("utf-8"), JSON_CT)

    def _reply_error(self, code: int, error: str, detail: str = "") -> None:
        self._reply_json(code, {"error": error, "detail": detail})

    # ------------------------------------------------------------- routes --

    def do_GET(self):  # noqa: N802 — stdlib handler naming
        self._extra_headers: List[Tuple[str, str]] = []
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            tracer = self.tracer
            supervisor = self.batcher.supervisor
            self._reply_json(200, {
                "status": "ok",
                "buckets": list(self.batcher.engine.cfg.buckets),
                "batch_sizes": list(self.batcher.engine.cfg.batch_sizes),
                "min_points": self.batcher.engine.cfg.min_points,
                "dtype": getattr(self.batcher.engine.cfg, "dtype",
                                 "float32"),
                # Pool fault-tolerance summary (ISSUE 13): serving
                # replica count + overall state (ok/degraded/
                # unavailable) and the probe cadence behind Retry-After;
                # None when no supervisor is wired.
                "pool": (supervisor.pool_health()
                         if supervisor is not None else None),
                # Armed fault-plan state (chaos runs are operations too:
                # an operator must be able to SEE that failures are
                # injected, not real).
                "faults": faults.plan_snapshot(),
                # Per-replica visibility (ISSUE 9 satellite): device id,
                # in-flight count, served-batch counter per replica —
                # plus the supervisor's health state when wired.
                "replicas": self.batcher.replica_stats(),
                "in_flight": (self.metrics.current_in_flight()
                              if self.metrics is not None else None),
                # Cost-calibration plane (ISSUE 14): predicted vs
                # measured device-seconds per (bucket, batch, dtype),
                # cumulative busy seconds and the rolling utilization
                # per replica — null while no cost surface is armed
                # (the calibration story lives here and on Prometheus;
                # the JSON /metrics shape stays frozen).
                "cost": (self.metrics.cost_snapshot()
                         if self.metrics is not None else None),
                "cost_surface": (self.batcher.costing.coverage()
                                 if self.batcher.costing is not None
                                 else None),
                # Weights provenance (ISSUE 20 satellite): checkpoint
                # path + params-content digest + epoch + hot-swap count,
                # so a /admin/reload is observable and test-pinnable.
                # epoch -1 is the epoch-less sentinel
                # (engine/checkpoint.load_params): random-init or a
                # payload written without an epoch field.
                "weights": self.batcher.engine.weights_info(),
                "programs": self.batcher.engine.compile_report(),
                "telemetry": {
                    "events_path": self.events_path or None,
                    "tracing": bool(tracer is not None and tracer.enabled),
                    "trace_sample_every": (
                        tracer.sample_every if tracer is not None else 0),
                    "trace_dir": self.trace_dir or None,
                },
            })
            return
        if path == "/metrics":
            fmt = urllib.parse.parse_qs(query).get("format", ["json"])[0]
            depths = self.batcher.queue_depths()
            if fmt == "prometheus":
                text = (self.metrics.prometheus(
                    depths,
                    replica_stats=self.batcher.replica_stats(),
                    batch_queue_depth=self.batcher.batch_queue_depth())
                    if self.metrics is not None else "")
                self._reply(200, text.encode("utf-8"), PROM_CONTENT_TYPE)
            elif fmt == "json":
                snap = (self.metrics.snapshot(depths)
                        if self.metrics is not None else {})
                self._reply_json(200, snap)
            else:
                self._reply_error(
                    400, "bad_request",
                    f"unknown format {fmt!r} (json|prometheus)")
            return
        if path == "/debug/trace":
            self._debug_trace(query)
            return
        self._reply_error(404, "not_found", self.path)

    def _debug_trace(self, query: str) -> None:
        """On-demand ``jax.profiler.trace`` window from the live server.
        The handler thread blocks for the window (ThreadingHTTPServer:
        other requests keep flowing, and the captured profile therefore
        contains real serving work)."""
        try:
            seconds = float(
                urllib.parse.parse_qs(query).get("seconds", ["2"])[0])
        except ValueError:
            self._reply_error(400, "bad_request", "seconds must be a number")
            return
        if not 0 < seconds <= 60:
            self._reply_error(400, "bad_request",
                              "seconds must be in (0, 60]")
            return
        if not _DEBUG_TRACE_LOCK.acquire(blocking=False):
            self._reply_error(
                409, "busy", "a trace window is already being captured")
            return
        try:
            import jax

            base = self.trace_dir or os.path.join(
                tempfile.gettempdir(), "pvraft_traces")
            os.makedirs(base, exist_ok=True)
            # mkdtemp, not strftime: two captures inside one wall-clock
            # second must land in distinguishable directories.
            trace_dir = tempfile.mkdtemp(
                prefix=time.strftime("trace_%Y%m%d_%H%M%S_"), dir=base)
            profiling = announced = False
            try:
                jax.profiler.start_trace(trace_dir)
                profiling = True
                # Emit "start" only once the profiler is actually
                # running (a failed start_trace must not leave an
                # unpaired start on the stream — consumers pair
                # start/stop)...
                if self.telemetry is not None:
                    self.telemetry.emit_trace_window("start", trace_dir)
                    announced = True
                time.sleep(seconds)
            finally:
                # ...and stop_trace runs on EVERY exit once started —
                # the profiler is a process-wide singleton, and leaving
                # it running (e.g. because the start emit raised) would
                # 500 every future capture for the life of the process.
                if profiling:
                    jax.profiler.stop_trace()
                    if announced:
                        self.telemetry.emit_trace_window("stop", trace_dir)
        except Exception as e:  # noqa: BLE001 — a handler must answer, not die
            self._reply_error(500, "internal", f"{type(e).__name__}: {e}")
            return
        finally:
            _DEBUG_TRACE_LOCK.release()
        self._reply_json(200, {"trace_dir": trace_dir, "seconds": seconds})

    def _finish_trace(self, trace, status: int,
                      bucket: Optional[int] = None) -> None:
        """Assemble + emit the span tree once the response is on the
        wire (tracing cost sits after the client has its answer). Error
        outcomes emit their partial tree too — a 503's queue state is
        observability data; only 200s feed the per-stage histograms."""
        if trace is None:
            return
        spans = trace.spans(root_attrs={"status": status})
        if self.tracer is not None:
            self.tracer.emit_spans(spans)
        if self.metrics is not None and bucket is not None and status == 200:
            self.metrics.record_stages(bucket, trace.stage_durations_ms())

    def _admin_reload(self) -> None:
        """``POST /admin/reload``: zero-downtime weight hot-swap. The
        engine does the structural work (drain-aware per-replica pointer
        swap, signature check); this handler only decodes the body and
        maps failure classes to status codes."""
        raw_length = self.headers.get("Content-Length")
        try:
            length = int(raw_length if raw_length is not None else "")
        except ValueError:
            length = -1
        if not 0 <= length <= (1 << 20):
            self.close_connection = True
            self._reply_error(400, "bad_request",
                              "missing or invalid Content-Length")
            return
        body = self.rfile.read(length)
        try:
            doc = json.loads(body or b"{}")
        except ValueError as e:
            self._reply_error(400, "bad_request", f"invalid JSON: {e}")
            return
        ckpt = doc.get("ckpt") if isinstance(doc, dict) else None
        if not isinstance(ckpt, str) or not ckpt:
            self._reply_error(
                400, "bad_request", "body must carry 'ckpt': <path>")
            return
        try:
            drain_s = float(doc.get("drain_timeout_s", 30.0))
        except (TypeError, ValueError):
            self._reply_error(400, "bad_request",
                              "drain_timeout_s must be a number")
            return
        try:
            report = self.batcher.engine.reload_checkpoint(
                ckpt, drain_timeout_s=drain_s)
        except ValueError as e:
            # Structure/shape/dtype mismatch: swapping would recompile
            # (or crash mid-dispatch) — rejected, incumbent untouched.
            self._reply_error(409, "swap_rejected", str(e))
            return
        except Exception as e:  # noqa: BLE001 — a handler must answer, not die
            self._reply_error(
                400, "bad_request",
                f"checkpoint unreadable: {type(e).__name__}: {e}")
            return
        self._reply_json(200, report)

    def do_POST(self):  # noqa: N802 — stdlib handler naming
        self._extra_headers = []
        post_path = self.path.partition("?")[0]
        if post_path == "/admin/reload":
            self._admin_reload()
            return
        if post_path != "/predict":
            # The body is left unread: a reused keep-alive connection
            # would parse it as the next request line, so close.
            self.close_connection = True
            self._reply_error(404, "not_found", self.path)
            return
        raw_length = self.headers.get("Content-Length")
        if raw_length is None:
            # Absent header (e.g. Transfer-Encoding: chunked): the body
            # length is unknown, so it would stay unread and desync a
            # reused keep-alive connection — reject and close.
            self.close_connection = True
            self.batcher.record_reject("bad_request")
            self._reply_error(400, "bad_request", "missing Content-Length")
            return
        try:
            length = int(raw_length)
        except ValueError:
            length = -1
        if length < 0:
            # Non-numeric or negative: rfile.read(-1) would block until
            # EOF (a handler thread pinned per request — trivial DoS).
            self.close_connection = True
            self.batcher.record_reject("bad_request")
            self._reply_error(400, "bad_request", "invalid Content-Length")
            return
        if length > self.max_body_bytes:
            # Bound memory BEFORE buffering: the engine's too_large check
            # only runs after a full read + parse. The body was not
            # consumed, so the keep-alive stream is unusable — close it.
            self.close_connection = True
            self.batcher.record_reject("too_large")
            self._reply_error(
                413, "too_large",
                f"body {length} B exceeds the {self.max_body_bytes} B cap")
            return
        # Sampling decision + ingress start BEFORE the body read, so the
        # ingress span covers read + decode. None = unsampled: no stamps,
        # no allocations past this check.
        trace = self.tracer.begin() if self.tracer is not None else None
        if trace is not None:
            self._extra_headers.append(("X-Pvraft-Trace", trace.trace_id))
        t_ingress = time.monotonic()
        body = self.rfile.read(length)
        ctype = (self.headers.get("Content-Type") or JSON_CT).split(";")[0]
        use_msgpack = ctype.strip().lower() == MSGPACK_CT
        try:
            pc1, pc2 = (_decode_msgpack(body) if use_msgpack
                        else _decode_json(body))
        except RequestError as e:
            # Decode failures never reach submit's reject ledger — record
            # them here so /metrics and serve_reject events match the
            # client-observed totals.
            self.batcher.record_reject(e.reason)
            self._reply_error(400, e.reason, str(e))
            self._finish_trace(trace, 400)
            return
        if trace is not None:
            trace.mark("ingress", t_ingress, time.monotonic(),
                       attrs={"bytes": length,
                              "msgpack": use_msgpack,
                              "n1": int(pc1.shape[0]),
                              "n2": int(pc2.shape[0])})
        req = None
        try:
            req = self.batcher.submit(pc1, pc2, trace=trace)
            flow = req.wait(self.predict_timeout_s)
        except RequestError as e:
            code = 413 if e.reason == "too_large" else 400
            self._reply_error(code, e.reason, str(e))
            self._finish_trace(trace, code)
            return
        except QueueFullError as e:
            # Every 503 carries Retry-After (ISSUE 13 satellite): one
            # supervisor probe cycle — the moment pool health can next
            # have changed. Well-behaved clients (loadgen --retries)
            # back off exactly that long.
            self._extra_headers.append(
                ("Retry-After", str(self.retry_after_s)))
            self._reply_error(503, "queue_full", str(e))
            self._finish_trace(trace, 503)
            return
        except PoolUnavailableError as e:
            # Graceful degradation terminal state: every replica
            # quarantined — an explicit, immediate shed instead of
            # accepting work that can only become a queue-timeout 504.
            self._extra_headers.append(
                ("Retry-After", str(self.retry_after_s)))
            self._reply_error(503, "unavailable", str(e))
            self._finish_trace(trace, 503)
            return
        except ShutdownError as e:
            self._extra_headers.append(
                ("Retry-After", str(self.retry_after_s)))
            self._reply_error(503, "shutting_down", str(e))
            self._finish_trace(trace, 503)
            return
        except TimeoutError as e:
            # Accepted-then-failed: counted at submit, so record the
            # outcome (not a fresh request) to keep /metrics reconciled.
            # record_failure_for: if the dispatch loop resolved the
            # request in the same instant, IT already counted the
            # response — recording a timeout too would double-book.
            self.batcher.record_failure_for(req, "timeout")
            self._reply_error(504, "timeout", str(e))
            self._finish_trace(trace, 504)
            return
        except Exception as e:  # noqa: BLE001 — a handler must answer, not die
            if req is not None:
                self.batcher.record_failure_for(req, "internal")
            else:
                # submit itself blew up before accepting the request:
                # nothing was counted yet, so this is a fresh reject,
                # not an accepted-request outcome.
                self.batcher.record_reject("internal")
            self._reply_error(500, "internal", f"{type(e).__name__}: {e}")
            self._finish_trace(trace, 500)
            return
        t_serialize = time.monotonic()
        if use_msgpack:
            import msgpack

            payload = msgpack.packb({
                "flow": np.ascontiguousarray(flow, np.float32).tobytes(),
                "n": int(flow.shape[0]),
            })
            content_type = MSGPACK_CT
        else:
            payload = json.dumps({"flow": flow.tolist(),
                                  "n": int(flow.shape[0])}).encode("utf-8")
            content_type = JSON_CT
        if trace is not None:
            t_respond = time.monotonic()
            trace.mark("serialize", t_serialize, t_respond)
            self._reply(200, payload, content_type)
            trace.mark("respond", t_respond, time.monotonic())
            self._finish_trace(trace, 200, bucket=req.bucket)
        else:
            self._reply(200, payload, content_type)


class ServeHTTPServer:
    """The assembled service: engine + batcher behind HTTP.

    ``port=0`` binds an ephemeral port (tests, load generator); the
    bound port is ``self.port`` after construction. ``start()`` serves
    on a background thread; ``shutdown()`` stops intake, drains the
    batcher, then stops the HTTP loop."""

    def __init__(self, batcher: MicroBatcher, host: str = "127.0.0.1",
                 port: int = 8000, metrics=None,
                 predict_timeout_s: float = 60.0, quiet: bool = True,
                 tracer: Optional[Tracer] = None, telemetry=None,
                 trace_dir: str = "", devmem_monitor=None,
                 supervisor=None):
        self.batcher = batcher
        self.tracer = tracer
        # Performance-plane hooks (build_service wires them): the
        # device-memory sampler thread and — via the batcher — the
        # sealed retrace watchdog; shutdown() releases both. The
        # replica supervisor's probe loop rides the same lifecycle.
        self.devmem_monitor = devmem_monitor
        self.supervisor = supervisor
        # 64 B/coordinate bounds any JSON float spelling (msgpack raw f32
        # is 4 B); anything past this cannot fit the largest bucket and
        # would only be buffered to be 413'd after parsing.
        largest = max(batcher.engine.cfg.buckets)
        max_body = 2 * largest * 3 * 64 + 65536
        events_path = ""
        if telemetry is not None and getattr(telemetry, "events", None):
            events_path = getattr(telemetry.events, "path", "") or ""
        handler = type("BoundHandler", (_Handler,), {
            "batcher": batcher,
            "metrics": metrics,
            "tracer": tracer,
            "telemetry": telemetry,
            "trace_dir": trace_dir,
            "events_path": events_path,
            "predict_timeout_s": predict_timeout_s,
            "max_body_bytes": max_body,
            "quiet": quiet,
            "retry_after_s": (supervisor.cfg.retry_after_s
                              if supervisor is not None else 1),
        })
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="pvraft-serve-http",
            daemon=True)
        self._thread.start()
        if self.devmem_monitor is not None:
            self.devmem_monitor.start()
        if self.supervisor is not None:
            self.supervisor.start()

    def shutdown(self, drain: bool = True) -> None:
        # Stop the probe loop FIRST: a probe mid-drain would race the
        # batcher's inline sweep for the same replica (harmless but
        # noisy — probes during teardown prove nothing).
        if self.supervisor is not None:
            self.supervisor.stop()
        self.batcher.shutdown(drain=drain)
        if self.devmem_monitor is not None:
            self.devmem_monitor.stop()
        if self.batcher.watchdog is not None:
            # Unhook the process-wide compile listener: tests (and
            # embedded servers) build services repeatedly in one
            # process, and a dead server must not keep watching.
            self.batcher.watchdog.close()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(10.0)


def build_service(engine, *, max_wait_ms: float = 5.0,
                  queue_depth: int = 64, host: str = "127.0.0.1",
                  port: int = 0, telemetry=None,
                  predict_timeout_s: float = 60.0,
                  quiet: bool = True, trace_sample_every: int = 16,
                  trace_dir: str = "",
                  eager_when_idle: bool = True,
                  strict_retrace: bool = False,
                  devmem_interval_s: float = 10.0,
                  supervise: bool = True,
                  supervisor_cfg=None,
                  cost_surface=None) -> ServeHTTPServer:
    """The one canonical engine -> metrics -> batcher -> HTTP assembly,
    shared by ``python -m pvraft_tpu.serve`` and the load generator so
    the two serving surfaces cannot drift: ``max_batch`` is always the
    largest compiled batch size, and one :class:`ServeMetrics` reaches
    both the batcher and the HTTP layer. ``trace_sample_every`` traces
    1-in-N requests (1 = every request — what loadgen uses; 0 = off);
    sampled spans go to ``telemetry`` when present and always feed the
    per-stage Prometheus histograms. ``eager_when_idle=False`` restores
    the PR-7 always-wait straggler window (the A/B baseline leg).

    Performance plane: the retrace watchdog seals the AOT program set
    here — any later backend compile becomes a ``recompile`` event +
    ``pvraft_serve_recompiles_total`` bump, and ``strict_retrace`` makes
    it fail the dispatch (HTTP 500) instead; a
    :class:`~pvraft_tpu.obs.device_memory.DeviceMemoryMonitor` samples
    ``device.memory_stats()`` every ``devmem_interval_s`` seconds into
    ``device_memory`` events and the ``pvraft_device_hbm_bytes{device}``
    gauge (0 disables; CPU backends sample to nothing either way).

    Fault tolerance (ISSUE 13): ``supervise=True`` (the default) wires a
    :class:`~pvraft_tpu.serve.supervisor.ReplicaSupervisor` — per-replica
    health state machine, quarantine + background probe revival,
    retry-once-on-another-replica, admission capacity scaled to the
    healthy count, 503s with ``Retry-After``; ``supervisor_cfg``
    overrides the declared thresholds
    (``programs/geometries.SUPERVISOR_DEFAULTS``). ``supervise=False``
    restores the pre-supervision pool bit-for-bit.

    Cost calibration (ISSUE 14): ``cost_surface`` — a
    :class:`~pvraft_tpu.programs.costs.CostSurface` or a path to a
    committed ``pvraft_costs/v1`` artifact — arms the pricing plane:
    every dispatch is priced in predicted device-seconds and measured
    against the price (``pvraft_serve_predicted_device_seconds_total``,
    ``pvraft_serve_device_busy_seconds_total{replica}``, the per-
    (bucket, batch, dtype) calibration summary, ``cost_calibration``
    events, the /healthz ``cost`` block). None (the default) leaves the
    dispatch path with exactly one attribute check and the exposition
    byte-identical to pre-surface builds.
    Returns an unstarted server (``.start()`` / ``.shutdown()``)."""
    from pvraft_tpu.obs.device_memory import DeviceMemoryMonitor
    from pvraft_tpu.obs.retrace import RetraceWatchdog
    from pvraft_tpu.serve.supervisor import ReplicaSupervisor

    metrics = ServeMetrics(engine.cfg.buckets)
    costing = None
    if cost_surface is not None:
        from pvraft_tpu.programs.costs import CostSurface
        from pvraft_tpu.serve.costing import ServeCostModel

        surface = (CostSurface.load(cost_surface)
                   if isinstance(cost_surface, str) else cost_surface)
        costing = ServeCostModel(
            surface, buckets=engine.cfg.buckets,
            batch_sizes=engine.cfg.batch_sizes, dtype=engine.cfg.dtype,
            platform=getattr(engine, "platform", "cpu"),
            metrics=metrics, telemetry=telemetry)
        metrics.arm_cost()
    supervisor = (ReplicaSupervisor(engine, cfg=supervisor_cfg,
                                    telemetry=telemetry)
                  if supervise else None)
    watchdog = RetraceWatchdog(
        emit=telemetry.emit_recompile if telemetry is not None else None,
        strict=strict_retrace, context="serve")
    # Seal BEFORE the batcher's executors exist: every AOT program is
    # already compiled (engine construction), so from here on a compile
    # DURING a dispatch is always a bug worth an event (the executors
    # scope each check to its dispatch window via global_compiles()).
    if not watchdog.seal():
        # No monitoring API on this jax: the watchdog cannot observe
        # compiles at all. Say so — especially under strict_retrace,
        # where the operator believes recompiles fail loudly.
        print("[serve] retrace watchdog DISARMED: this jax exposes no "
              "compile-monitoring API (compat.register_compile_listener)"
              + (" — --strict_retrace will never fire"
                 if strict_retrace else ""), flush=True)
    batcher = MicroBatcher(
        engine,
        BatcherConfig(max_batch=max(engine.cfg.batch_sizes),
                      max_wait_ms=max_wait_ms, queue_depth=queue_depth,
                      eager_when_idle=eager_when_idle),
        telemetry=telemetry, metrics=metrics, watchdog=watchdog,
        supervisor=supervisor, costing=costing)
    tracer = Tracer(
        sample_every=trace_sample_every,
        emit=telemetry.emit_span if telemetry is not None else None)
    devmem = DeviceMemoryMonitor(
        emit=telemetry.emit_device_memory if telemetry is not None else None,
        metrics=metrics, interval_s=devmem_interval_s, context="serve")
    return ServeHTTPServer(batcher, host=host, port=port, metrics=metrics,
                           predict_timeout_s=predict_timeout_s, quiet=quiet,
                           tracer=tracer, telemetry=telemetry,
                           trace_dir=trace_dir, devmem_monitor=devmem,
                           supervisor=supervisor)
