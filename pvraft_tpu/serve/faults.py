"""Deterministic fault injection for the serve plane.

Named **fault points** are threaded through the replica executor, the
batcher and the server (``FAULT_POINTS``, vocabulary shared with the
event schema in ``obs/events.py``):

  ``replica_predict_error``   raise from a replica's dispatch/probe path
  ``replica_latency_ms``      sleep ``value`` ms inside the dispatch
  ``replica_wedge``           block the dispatch until the plan clears
  ``queue_stall``             sleep ``value`` ms in a bucket collector
  ``compile_trip``            simulate a post-seal backend compile
                              (the call site bumps the retrace watchdog)

A point is **armed** only by an explicitly installed :class:`FaultPlan`
— a deterministic schedule of :class:`FaultRule` records: fire on the
``nth`` traversal of the named point (per-replica when the rule names a
replica, else on the global traversal count), optionally repeating
``every`` k traversals, capped at ``max_fires``, and only ``after_s``
seconds past install. Determinism is the whole design: a chaos test
states *which* dispatch fails, runs real threads, and asserts the
recovery story — no random sleeps, no flaky kill -9.

Zero-cost when disarmed: :func:`fire` is one attribute read and a
``None`` check — no counters are allocated, nothing is locked, and no
fault point lives inside jitted code, so the default path's jaxprs,
the frozen JSON ``/metrics`` shape and the sanitizer's lock graph are
untouched (``tests/test_supervisor.py`` gates the zero-residue claim;
this module never imports jax).

Install/clear are process-global (``install_plan`` / ``clear_plan`` /
the ``injected`` context manager): the chaos suite arms a plan, builds
the service, drives load, clears the plan, and watches the supervisor's
probe revive the quarantined replica.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.obs.events import FAULT_POINTS


class InjectedFaultError(RuntimeError):
    """The effect of a fired ``replica_predict_error`` fault point —
    a distinct type so tests (and the supervisor's failure ledger) can
    tell an injected failure from a real one."""


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One deterministic firing schedule for one fault point.

    ``nth`` is 1-based: the rule first fires on the nth traversal of
    its point (counted per replica when ``replica`` is set, globally
    otherwise). ``every=0`` fires exactly once; ``every=k`` re-fires on
    every k-th traversal after the nth, up to ``max_fires`` total
    (0 = unlimited). ``after_s`` keeps the rule dormant for that many
    seconds past plan install. ``value`` is the point's magnitude:
    milliseconds for ``replica_latency_ms``/``queue_stall``, max block
    seconds for ``replica_wedge`` (0 = until the plan clears)."""

    point: str
    nth: int = 1
    every: int = 0
    after_s: float = 0.0
    replica: Optional[int] = None
    value: float = 0.0
    max_fires: int = 0

    def __post_init__(self):
        if self.point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {self.point!r}; known points: "
                f"{FAULT_POINTS}")
        if self.nth < 1:
            raise ValueError("nth is 1-based and must be >= 1")
        if self.every < 0 or self.max_fires < 0 or self.after_s < 0:
            raise ValueError("every/max_fires/after_s must be >= 0")
        if self.value < 0:
            raise ValueError("value must be >= 0")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An immutable set of fault rules, installed as one unit."""

    rules: Tuple[FaultRule, ...]

    def __init__(self, rules):
        object.__setattr__(self, "rules", tuple(rules))
        if not self.rules:
            raise ValueError("a FaultPlan needs at least one rule")
        for rule in self.rules:
            if not isinstance(rule, FaultRule):
                raise TypeError(f"not a FaultRule: {rule!r}")

    def describe(self) -> List[Dict[str, Any]]:
        """Plain-data rendering for /healthz and event payloads."""
        return [dataclasses.asdict(r) for r in self.rules]


class _Injector:
    """Process-global fault-point state. ``_plan`` is the armed flag:
    written only under ``_lock`` (install/clear), read unlocked on the
    hot path — the benign-racy-flag idiom (threadcheck GC001 inferred-
    guard read exemption): a traversal racing a concurrent clear either
    sees the plan (and fires one last time) or misses it; both are
    legitimate schedules."""

    def __init__(self):
        self._lock = ordered_lock("serve.faults._Injector._lock")
        self._plan: Optional[FaultPlan] = None
        self._installed_at = 0.0  # guarded-by: _lock
        # Traversal counts per (point, replica) and (point, None); only
        # allocated while a plan is armed — the disarmed path never
        # touches them (the zero-residue guarantee).
        self._counts: Dict[Tuple[str, Optional[int]], int] = {}  # guarded-by: _lock
        self._rule_fires: List[int] = []  # guarded-by: _lock
        self._fired_total = 0  # guarded-by: _lock
        # Wedge release: replaced at install, set at clear so wedged
        # threads resume the moment the fault window closes.
        self._release = threading.Event()

    # ------------------------------------------------------------ arming --

    def install(self, plan: FaultPlan) -> None:
        with self._lock:
            if self._plan is not None:
                raise RuntimeError(
                    "a FaultPlan is already installed; clear_plan() first "
                    "(plans are installed as one unit so the schedule "
                    "stays deterministic)")
            self._counts = {}
            self._rule_fires = [0] * len(plan.rules)
            self._fired_total = 0
            self._installed_at = time.monotonic()
            self._release = threading.Event()
            self._plan = plan

    def clear(self) -> None:
        with self._lock:
            self._plan = None
            # The schedule state dies with its plan: a disarmed injector
            # is indistinguishable from one that never fired (capture
            # plan_snapshot() BEFORE clearing when the counts are
            # evidence — scripts/serve_chaos.py does).
            self._counts = {}
            self._rule_fires = []
            self._fired_total = 0
            release = self._release
        release.set()  # unblock any wedged traversal

    def snapshot(self) -> Dict[str, Any]:
        """Armed state + fire counts for /healthz (plain data)."""
        with self._lock:
            plan = self._plan
            return {
                "armed": plan is not None,
                "rules": plan.describe() if plan is not None else [],
                "fired_total": self._fired_total,
                "rule_fires": list(self._rule_fires),
            }

    # ------------------------------------------------------------- firing --

    def _fire(self, point: str, replica: Optional[int],
              bucket: Optional[int],
              on_fire: Optional[Callable[[Dict[str, Any]], None]],
              ) -> Tuple[Dict[str, Any], ...]:
        now = time.monotonic()
        fired: List[Tuple[FaultRule, Dict[str, Any]]] = []
        with self._lock:
            plan = self._plan
            if plan is None:  # cleared between the fast check and here
                return ()
            release = self._release
            self._counts[(point, None)] = \
                self._counts.get((point, None), 0) + 1
            if replica is not None:
                self._counts[(point, replica)] = \
                    self._counts.get((point, replica), 0) + 1
            for idx, rule in enumerate(plan.rules):
                if rule.point != point:
                    continue
                if rule.replica is not None and rule.replica != replica:
                    continue
                n = self._counts[(point, rule.replica
                                  if rule.replica is not None else None)]
                if rule.after_s and now - self._installed_at < rule.after_s:
                    continue
                if n < rule.nth:
                    continue
                if rule.every == 0:
                    if n != rule.nth:
                        continue
                elif (n - rule.nth) % rule.every != 0:
                    continue
                if rule.max_fires and self._rule_fires[idx] >= rule.max_fires:
                    continue
                self._rule_fires[idx] += 1
                self._fired_total += 1
                fired.append((rule, {
                    "point": point,
                    "traversal": n,
                    "fires": self._fired_total,
                    **({"replica": replica} if replica is not None else {}),
                    **({"bucket": bucket} if bucket is not None else {}),
                    **({"value": rule.value} if rule.value else {}),
                }))
        # Effects OUTSIDE the lock: a sleeping/wedged fault must not
        # stall unrelated fault points (or the install/clear path).
        records = tuple(rec for _, rec in fired)
        for _, rec in fired:
            if on_fire is not None:
                on_fire(rec)
        for rule, rec in fired:
            if point in ("replica_latency_ms", "queue_stall"):
                time.sleep(rule.value / 1000.0)
            elif point == "replica_wedge":
                # Block until the plan clears (or the rule's own bound);
                # 60 s hard ceiling so a forgotten plan cannot hang a
                # test session forever.
                release.wait(rule.value if rule.value > 0 else 60.0)
            elif point == "replica_predict_error":
                raise InjectedFaultError(
                    f"injected fault: {point} (traversal "
                    f"{rec['traversal']}, replica {replica})")
            # compile_trip has no intrinsic effect: the call site bumps
            # the retrace watchdog so the trip flows through the real
            # recompile-observability path.
        return records


_INJECTOR = _Injector()


def fire(point: str, replica: Optional[int] = None,
         bucket: Optional[int] = None,
         on_fire: Optional[Callable[[Dict[str, Any]], None]] = None,
         ) -> Tuple[Dict[str, Any], ...]:
    """Traverse one named fault point. Disarmed (the default): one
    attribute read + ``None`` check, returns ``()`` — nothing counted,
    nothing locked. Armed: counts the traversal, fires every matching
    rule (``on_fire(record)`` per fire, then the effect — which for
    ``replica_predict_error`` is raising :class:`InjectedFaultError`)."""
    if _INJECTOR._plan is None:
        return ()
    return _INJECTOR._fire(point, replica, bucket, on_fire)


def replica_faults(replica: int, bucket: Optional[int] = None,
                   on_fire: Optional[Callable[[Dict[str, Any]], None]] = None,
                   ) -> None:
    """The replica-executor fault points, in deterministic order:
    latency (sleep) -> wedge (block) -> error (raise). Shared by the
    batcher's dispatch AND the supervisor's probe, so an armed replica
    fault fails the probe too — a quarantined replica is only revived
    once the fault actually clears."""
    if _INJECTOR._plan is None:
        return
    fire("replica_latency_ms", replica=replica, bucket=bucket,
         on_fire=on_fire)
    fire("replica_wedge", replica=replica, bucket=bucket, on_fire=on_fire)
    fire("replica_predict_error", replica=replica, bucket=bucket,
         on_fire=on_fire)


def install_plan(plan: FaultPlan) -> None:
    """Arm the process-global fault plan (exactly one at a time)."""
    _INJECTOR.install(plan)


def clear_plan() -> None:
    """Disarm: traversals stop counting, wedged threads release."""
    _INJECTOR.clear()


def plan_snapshot() -> Dict[str, Any]:
    """Armed state + fire counts (surfaced on ``/healthz``)."""
    return _INJECTOR.snapshot()


@contextlib.contextmanager
def injected(plan: FaultPlan):
    """``with injected(FaultPlan([...])):`` — install for the block,
    always clear (tests must not leak an armed plan into the next)."""
    install_plan(plan)
    try:
        yield
    finally:
        clear_plan()
