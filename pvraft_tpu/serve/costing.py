"""Dispatch pricing: the serve plane's live view of the cost surface.

ISSUE 14's feedback loop: every dispatched micro-batch is PRICED
through the committed cost inventory
(:class:`~pvraft_tpu.programs.costs.CostSurface`) and MEASURED against
that price — the predicted device-seconds land on the
``pvraft_serve_predicted_device_seconds_total`` counter, the measured
dispatch wall on ``pvraft_serve_device_busy_seconds_total{replica}``,
and their per-(bucket, batch, dtype) ratio is the calibration summary
that says whether the cost model is honest (``cost_calibration``
events + ``/healthz`` snapshot + Prometheus).

Platform honesty is first-class (the ``pvraft_bench/v1`` lesson): a
calibration record is ``comparable`` ONLY when the engine executes on a
real TPU *and* the prediction came from a TPU-topology record — a CPU
wall clock next to an XLA optimal-seconds estimate is recorded (the
machinery must be exercised everywhere) but can never be enforced, and
the schema makes the distinction unrepresentable to forget
(``obs/events.py`` rejects ``comparable: true`` off-TPU).

The price table is computed ONCE at construction (the serve program
table is a small static product), so the per-dispatch hook is a dict
read plus two counter bumps — and a disarmed service carries no model
at all (``costing is None`` in the batcher: one attribute check, the
``faults.py`` zero-residue discipline, test-gated).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

from pvraft_tpu.programs.costs import CostEstimate, CostSurface


class ServeCostModel:
    """One serve pool's price table + calibration sink."""

    def __init__(self, surface: CostSurface, buckets: Sequence[int],
                 batch_sizes: Sequence[int], dtype: str, platform: str,
                 metrics=None, telemetry=None):
        self.surface = surface
        self.dtype = dtype
        self.platform = platform
        self.metrics = metrics
        self.telemetry = telemetry
        # Immutable after construction: read-only from the executor
        # threads, so no lock is needed on the dispatch path.
        self._prices: Dict[Tuple[int, int], Optional[CostEstimate]] = {
            (int(b), int(bs)): surface.estimate_serve(b, bs, dtype)
            for b in buckets for bs in batch_sizes}

    def price(self, bucket: int, batch: int) -> Optional[CostEstimate]:
        """The predicted cost of one (bucket, batch) dispatch (None when
        the surface has no serve records for the dtype at all)."""
        return self._prices.get((int(bucket), int(batch)))

    def coverage(self) -> Dict[str, Any]:
        """What the table knows — the /healthz arming report."""
        priced = {k: v for k, v in self._prices.items() if v is not None}
        return {
            "surface": self.surface.path,
            "dtype": self.dtype,
            "platform": self.platform,
            "programs": len(self.surface),
            "priced_geometries": len(priced),
            "extrapolated_geometries": sorted(
                f"b{b}_bs{bs}" for (b, bs), v in priced.items()
                if v.extrapolated),
        }

    def observe_dispatch(self, bucket: int, batch: int, replica: int,
                         t_start: float, t_end: float) -> None:
        """Price + measure one successful dispatch. Called by the
        batcher's executor after the engine call returns; ``t_start``/
        ``t_end`` bracket exactly the device_execute window the trace
        plane marks, so the busy-seconds ledger and the span plane tell
        one story."""
        est = self.price(bucket, batch)
        if est is None:
            return
        measured_s = max(0.0, t_end - t_start)
        comparable = self.platform == "tpu" and est.comparable
        if self.metrics is not None:
            self.metrics.record_cost(
                bucket=bucket, batch=batch, dtype=self.dtype,
                replica=replica, predicted_s=est.device_seconds,
                measured_s=measured_s, t_start=t_start, t_end=t_end,
                comparable=comparable, extrapolated=est.extrapolated)
        if self.telemetry is not None:
            self.telemetry.emit_cost_calibration(
                bucket=bucket, batch=batch, dtype=self.dtype,
                predicted_s=round(est.device_seconds, 9),
                measured_s=round(measured_s, 6),
                platform=self.platform, comparable=comparable,
                replica=replica, basis=est.basis,
                extrapolated=est.extrapolated, program=est.name)
