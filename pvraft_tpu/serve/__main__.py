"""CLI: run the scene-flow service / validate load artifacts.

    # serve a checkpoint (msgpack file or orbax directory)
    python -m pvraft_tpu.serve serve --ckpt experiments/exp/checkpoints/\
best_checkpoint.msgpack --port 8000 --buckets 2048,4096,8192

    # validate a pvraft_serve_load/v1 artifact (wired into scripts/lint.sh)
    python -m pvraft_tpu.serve validate-load artifacts/serve_cpu_synthetic.json
"""

from __future__ import annotations

import argparse
import sys

from pvraft_tpu import parse_int_list as _parse_ints
from pvraft_tpu.programs.geometries import (
    SERVE_DEFAULT_BATCH_SIZES,
    SERVE_DEFAULT_BUCKETS,
    SERVE_DEFAULT_DTYPE,
    SERVE_DEFAULT_ITERS,
    SERVE_DEFAULT_REPLICAS,
    SERVE_DTYPES,
)


def _cmd_serve(args) -> int:
    # Pin the platform before any jax import commits to a backend (the
    # config API, not the env var: jax may already be imported).
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.serve import (
        InferenceEngine,
        ServeConfig,
        ServeTelemetry,
        build_service,
    )

    model = ModelConfig(
        truncate_k=args.truncate_k,
        corr_knn=args.corr_knn,
        graph_k=args.graph_k,
    )
    cfg = ServeConfig(
        model=model,
        buckets=_parse_ints(args.buckets),
        batch_sizes=_parse_ints(args.batch_sizes),
        num_iters=args.iters,
        refine=args.refine,
        dtype=args.dtype,
        replicas=args.replicas,
    )
    telemetry = (ServeTelemetry(args.events, cfg=cfg)
                 if args.events else None)
    # Load the cost surface BEFORE the engine compiles its program
    # table: a typo'd --cost_surface path must fail in milliseconds,
    # not after minutes of AOT compiles.
    cost_surface = None
    if args.cost_surface:
        from pvraft_tpu.programs.costs import CostSurface

        cost_surface = CostSurface.load(args.cost_surface)
        print(f"[serve] cost surface armed: {args.cost_surface} "
              f"({len(cost_surface)} program records)", flush=True)
    print(f"[serve] compiling {len(cfg.buckets) * len(cfg.batch_sizes)} "
          f"predict programs (buckets={cfg.buckets}, "
          f"batch_sizes={cfg.batch_sizes}, dtype={cfg.dtype}, "
          f"replicas={cfg.replicas or 'all'})...", flush=True)
    engine = InferenceEngine.from_checkpoint(args.ckpt, cfg,
                                             telemetry=telemetry)
    print(f"[serve] replica pool: "
          f"{[r.device_id for r in engine.replicas]} (device ids)",
          flush=True)
    for rec in engine.compile_report():
        print(f"[serve]   {rec['name']}: lower {rec['lower_s']}s "
              f"compile {rec['compile_s']}s", flush=True)
    from pvraft_tpu.serve.supervisor import SupervisorConfig

    supervisor_cfg = None
    if args.probe_interval is not None:
        supervisor_cfg = SupervisorConfig(
            probe_interval_s=args.probe_interval)
    server = build_service(engine, max_wait_ms=args.max_wait_ms,
                           queue_depth=args.queue_depth, host=args.host,
                           port=args.port, telemetry=telemetry,
                           quiet=not args.verbose,
                           trace_sample_every=args.trace_sample,
                           trace_dir=args.trace_dir,
                           strict_retrace=args.strict_retrace,
                           devmem_interval_s=args.devmem_interval,
                           supervise=not args.no_supervise,
                           supervisor_cfg=supervisor_cfg,
                           cost_surface=cost_surface)
    server.start()
    print(f"[serve] listening on http://{server.host}:{server.port} "
          f"(/predict /healthz /metrics /debug/trace); tracing "
          f"{'1-in-' + str(args.trace_sample) if args.trace_sample else 'off'}",
          flush=True)
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("[serve] draining...", flush=True)
        server.shutdown(drain=True)
        if telemetry is not None:
            telemetry.close()
    return 0


def _cmd_validate_load(args) -> int:
    from pvraft_tpu.serve.loadgen import validate_load_artifact_file

    failed = 0
    for path in args.paths:
        problems = validate_load_artifact_file(path)
        if problems:
            failed += 1
            for p in problems:
                print(p, file=sys.stderr)
        else:
            print(f"{path}: OK")
    return 1 if failed else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("python -m pvraft_tpu.serve")
    sub = parser.add_subparsers(dest="cmd", required=True)

    srv = sub.add_parser("serve", help="run the inference service")
    srv.add_argument("--ckpt", required=True,
                     help="checkpoint (.msgpack file or .orbax directory)")
    srv.add_argument("--host", default="127.0.0.1")
    srv.add_argument("--port", type=int, default=8000)
    # Geometry defaults come from the program registry's declarations
    # (pvraft_tpu/programs/geometries.py), the same table the engine
    # compiles and aot_readiness certifies.
    srv.add_argument("--buckets",
                     default=",".join(map(str, SERVE_DEFAULT_BUCKETS)),
                     help="comma-separated point-count buckets (ascending)")
    srv.add_argument("--batch_sizes",
                     default=",".join(map(str, SERVE_DEFAULT_BATCH_SIZES)),
                     help="comma-separated compiled batch sizes (ascending)")
    srv.add_argument("--iters", type=int, default=SERVE_DEFAULT_ITERS,
                     help="GRU refinement iterations per predict")
    srv.add_argument("--truncate_k", type=int, default=512)
    srv.add_argument("--corr_knn", type=int, default=32)
    srv.add_argument("--graph_k", type=int, default=32)
    srv.add_argument("--refine", action="store_true",
                     help="serve a stage-2 (PVRaftRefine) checkpoint")
    srv.add_argument("--dtype", default=SERVE_DEFAULT_DTYPE,
                     choices=sorted(SERVE_DTYPES),
                     help="serving compute dtype (params stay float32); "
                          "bfloat16 is the default, accuracy-bound-gated "
                          "vs float32")
    srv.add_argument("--replicas", type=int, default=SERVE_DEFAULT_REPLICAS,
                     help="replica pool size (0 = one per local device)")
    srv.add_argument("--max_wait_ms", type=float, default=5.0)
    srv.add_argument("--queue_depth", type=int, default=64)
    srv.add_argument("--events", default="",
                     help="pvraft_events/v1 JSONL path for serve telemetry")
    srv.add_argument("--trace_sample", type=int, default=16,
                     help="trace 1-in-N requests (1 = all, 0 = off); "
                          "spans ride the --events stream")
    srv.add_argument("--trace_dir", default="",
                     help="base directory for /debug/trace XLA profile "
                          "windows (default: a temp dir)")
    srv.add_argument("--strict_retrace", "--strict-retrace",
                     dest="strict_retrace", action="store_true",
                     help="fail a dispatch (HTTP 500) when any backend "
                          "compile is observed after AOT startup sealed "
                          "the program set; without it the retrace "
                          "watchdog only emits `recompile` events + the "
                          "pvraft_serve_recompiles_total counter")
    srv.add_argument("--no-supervise", dest="no_supervise",
                     action="store_true",
                     help="disable the replica supervisor (health state "
                          "machine, quarantine + probe revival, "
                          "retry-once, degraded admission) — the "
                          "pre-fault-tolerance pool semantics")
    srv.add_argument("--probe_interval", type=float, default=None,
                     help="supervisor probe cadence in seconds (default: "
                          "the declared "
                          "geometries.SUPERVISOR_DEFAULTS value); also "
                          "drives the 503 Retry-After header")
    srv.add_argument("--devmem_interval", type=float, default=10.0,
                     help="seconds between device.memory_stats() samples "
                          "(device_memory events + "
                          "pvraft_device_hbm_bytes gauge; 0 disables)")
    srv.add_argument("--cost_surface", "--cost-surface",
                     dest="cost_surface", default="",
                     help="arm the cost-calibration plane from a "
                          "committed pvraft_costs/v1 inventory (e.g. "
                          "artifacts/programs_costs.json): every "
                          "dispatch is priced in predicted "
                          "device-seconds and measured against the "
                          "price (Prometheus counters, "
                          "cost_calibration events, /healthz cost "
                          "block). Empty (default) = disarmed, "
                          "zero dispatch-path residue")
    srv.add_argument("--platform", default="",
                     help="force a jax platform (e.g. cpu)")
    srv.add_argument("--verbose", action="store_true",
                     help="log every HTTP request")
    srv.set_defaults(fn=_cmd_serve)

    val = sub.add_parser("validate-load",
                         help="validate pvraft_serve_load/v1 artifacts")
    val.add_argument("paths", nargs="+")
    val.set_defaults(fn=_cmd_validate_load)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
