"""In-process load generator + the ``pvraft_serve_load/v1`` artifact.

Drives a real :class:`ServeHTTPServer` (ephemeral port, actual HTTP
round-trips through the stdlib client) with concurrent workers issuing
requests whose point counts spread across the configured buckets, then
writes a latency/throughput artifact:

    {"schema": "pvraft_serve_load/v1",
     "config": {...}, "compile": [...per-program...],
     "requests": {"total", "ok", "rejected", "errors"},
     "latency_ms": {"p50", "p95", "p99", "mean", "max"},
     "throughput_rps": float, "duration_s": float,
     "server_metrics": {...the /metrics snapshot...}}

Client-side latency quantiles are computed from the raw per-request
samples (exact, unlike the server histogram's bucketed upper bounds).
``validate_load_artifact`` is the schema gate for the committed
artifact (wired into ``scripts/lint.sh``).

Schema-additive since the trace plane (``obs/trace.py``): the server's
``X-Pvraft-Trace`` response header is recorded per request, so

    "per_request": [{"status", "ms", "n", "trace_id"}, ...]
    "request_points": {"edges": [...], "counts": [...]}

join the loadgen artifact to span events by trace id —
``scripts/slo_report.py`` builds the ``pvraft_slo/v1`` report from
exactly that join. Both fields are optional for older artifacts."""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pvraft_tpu.rng import host_rng

SCHEMA_VERSION = "pvraft_serve_load/v1"


# Re-exported for the serve CLIs (scripts/serve_*.py): the flag write
# itself now lives with the other backend declarations in compat.py
# (detcheck GD004 — one owner for determinism-relevant XLA_FLAGS).
from pvraft_tpu.compat import force_host_device_count  # noqa: F401


def write_load_and_trace(out_path: str, artifact: Dict[str, Any],
                         events_path: str,
                         log_prefix: str = "loadgen"
                         ) -> Tuple[str, Dict[str, Any]]:
    """Validate + write one ``pvraft_serve_load/v1`` artifact and its
    ``pvraft_trace/v1`` sibling (span trees grouped from the run's
    events stream). The ONE write path for committed serve evidence —
    ``scripts/serve_loadgen.py`` and ``scripts/serve_ab.py`` both call
    it, so a schema change cannot drift between them. Returns
    ``(trace_path, trace_doc)``; raises SystemExit(1) on any schema
    problem (the caller is a CLI)."""
    import sys

    from pvraft_tpu.obs.trace import collect_traces, validate_trace_artifact

    problems = validate_load_artifact(artifact, path=out_path)
    if problems:
        for p in problems:
            print(f"[{log_prefix}] SCHEMA PROBLEM: {p}", file=sys.stderr)
        raise SystemExit(1)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)

    with open(events_path, "r", encoding="utf-8") as f:
        records = [json.loads(line) for line in f if line.strip()]
    trace_doc = collect_traces(records, source=events_path)
    trace_path = os.path.splitext(out_path)[0] + ".trace.json"
    trace_problems = validate_trace_artifact(trace_doc, path=trace_path)
    if trace_problems:
        for p in trace_problems:
            print(f"[{log_prefix}] TRACE SCHEMA PROBLEM: {p}",
                  file=sys.stderr)
        raise SystemExit(1)
    with open(trace_path, "w") as f:
        json.dump(trace_doc, f, indent=2)
    return trace_path, trace_doc

_REQUIRED = ("schema", "config", "compile", "requests", "latency_ms",
             "throughput_rps", "duration_s", "server_metrics")
_LAT_KEYS = ("p50", "p95", "p99", "mean", "max")


def validate_load_artifact(doc: Any,
                           path: str = "<artifact>") -> List[str]:
    """Schema problems of a load artifact ([] = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"{path}: artifact is {type(doc).__name__}, not an object"]
    if doc.get("schema") != SCHEMA_VERSION:
        problems.append(
            f"{path}: schema {doc.get('schema')!r} != {SCHEMA_VERSION!r}")
    for key in _REQUIRED:
        if key not in doc:
            problems.append(f"{path}: missing field {key!r}")
    reqs = doc.get("requests")
    if isinstance(reqs, dict):
        for key in ("total", "ok", "rejected", "errors"):
            if not isinstance(reqs.get(key), int):
                problems.append(
                    f"{path}: requests.{key} must be an int, "
                    f"got {reqs.get(key)!r}")
        if all(isinstance(reqs.get(k), int)
               for k in ("total", "ok", "rejected", "errors")):
            if reqs["ok"] + reqs["rejected"] + reqs["errors"] != reqs["total"]:
                problems.append(
                    f"{path}: requests ok+rejected+errors != total "
                    f"({reqs})")
    elif "requests" in doc:
        problems.append(f"{path}: requests must be an object")
    lat = doc.get("latency_ms")
    if isinstance(lat, dict):
        for key in _LAT_KEYS:
            v = lat.get(key)
            if v is not None and not isinstance(v, (int, float)):
                problems.append(
                    f"{path}: latency_ms.{key} must be a number or null, "
                    f"got {v!r}")
        order = [lat.get(k) for k in ("p50", "p95", "p99")]
        if all(isinstance(v, (int, float)) for v in order):
            if not (order[0] <= order[1] <= order[2]):
                problems.append(
                    f"{path}: latency quantiles must be non-decreasing, "
                    f"got p50={order[0]} p95={order[1]} p99={order[2]}")
    elif "latency_ms" in doc:
        problems.append(f"{path}: latency_ms must be an object")
    if not isinstance(doc.get("compile"), list):
        if "compile" in doc:
            problems.append(f"{path}: compile must be a list")
    for key in ("throughput_rps", "duration_s"):
        if key in doc and not isinstance(doc[key], (int, float)):
            problems.append(f"{path}: {key} must be a number")
    # Additive trace-plane fields (absent in pre-trace artifacts).
    if "per_request" in doc:
        if not isinstance(doc["per_request"], list):
            problems.append(f"{path}: per_request must be a list")
        else:
            for i, r in enumerate(doc["per_request"]):
                if not isinstance(r, dict) or not isinstance(
                        r.get("status"), int):
                    problems.append(
                        f"{path}: per_request[{i}] must carry an int "
                        f"status")
                elif r.get("trace_id") is not None and not isinstance(
                        r["trace_id"], str):
                    problems.append(
                        f"{path}: per_request[{i}].trace_id must be a "
                        f"string or null")
                elif "attempts" in r:
                    # Client-retry evidence (--retries): every attempt's
                    # status/ms, final attempt == the entry's own status.
                    atts = r["attempts"]
                    if (not isinstance(atts, list) or len(atts) < 2
                            or not all(isinstance(a, dict)
                                       and isinstance(a.get("status"), int)
                                       for a in atts)):
                        problems.append(
                            f"{path}: per_request[{i}].attempts must be "
                            f">= 2 objects each carrying an int status")
                    elif atts[-1]["status"] != r["status"]:
                        problems.append(
                            f"{path}: per_request[{i}] status "
                            f"{r['status']} != final attempt status "
                            f"{atts[-1]['status']}")
            if isinstance(reqs, dict) and isinstance(
                    reqs.get("total"), int) and len(
                    doc["per_request"]) != reqs["total"]:
                problems.append(
                    f"{path}: per_request has {len(doc['per_request'])} "
                    f"entries, requests.total is {reqs['total']}")
    # Additive multi-target field (fleet evidence): when the run
    # round-robined several endpoints, config.targets records them.
    cfg = doc.get("config")
    if isinstance(cfg, dict) and "targets" in cfg:
        tg = cfg["targets"]
        if (not isinstance(tg, list) or not tg
                or not all(isinstance(t, str) and t for t in tg)):
            problems.append(
                f"{path}: config.targets must be a non-empty list of "
                f"'host:port' strings")
    if "request_points" in doc:
        rp = doc["request_points"]
        if (not isinstance(rp, dict)
                or not isinstance(rp.get("edges"), list)
                or not isinstance(rp.get("counts"), list)
                or len(rp.get("counts", [])) !=
                len(rp.get("edges", [])) + 1):
            problems.append(
                f"{path}: request_points must carry edges + counts with "
                f"len(counts) == len(edges) + 1")
    return problems


def validate_load_artifact_file(path: str) -> List[str]:
    from pvraft_tpu.obs.loading import load_json_artifact

    doc, problems = load_json_artifact(path)
    if problems:
        return problems
    return validate_load_artifact(doc, path=path)


def merge_measurements(rounds: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold several :func:`run_load` measurements of ONE server into a
    single artifact-shaped measurement — the interleaved-A/B path
    (``scripts/serve_ab.py``): each leg's rounds alternate with the
    other leg's on the same host, then merge per leg. Latency quantiles
    are recomputed from the concatenated per-request samples (exact,
    same estimator); ``server_metrics`` is the LAST round's snapshot
    (the server's counters are cumulative across its rounds)."""
    if not rounds:
        raise ValueError("no measurements to merge")
    from pvraft_tpu.obs.slo import exact_quantile

    per_request = [r for m in rounds for r in m["per_request"]]
    lat = sorted(r["ms"] for r in per_request
                 if r["status"] == 200 and r["ms"] is not None)
    duration = sum(m["duration_s"] for m in rounds)
    requests = {
        key: sum(m["requests"][key] for m in rounds)
        for key in ("total", "ok", "rejected", "errors")}
    edges = rounds[0]["request_points"]["edges"]
    counts = [0] * len(rounds[0]["request_points"]["counts"])
    for m in rounds:
        if m["request_points"]["edges"] != edges:
            raise ValueError("rounds use different histogram edges")
        counts = [a + b for a, b in
                  zip(counts, m["request_points"]["counts"])]

    def pct(q: float) -> Optional[float]:
        v = exact_quantile(lat, q)
        return None if v is None else round(v, 3)

    return {
        "requests": requests,
        "latency_ms": {
            "p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
            "mean": (round(sum(lat) / len(lat), 3) if lat else None),
            "max": round(lat[-1], 3) if lat else None,
        },
        "throughput_rps": (round(requests["ok"] / duration, 3)
                           if duration else 0.0),
        "duration_s": round(duration, 3),
        "per_request": per_request,
        "request_points": {"edges": edges, "counts": counts},
        "server_metrics": rounds[-1]["server_metrics"],
    }


def _post_json(host: str, port: int, path: str, doc: Dict[str, Any],
               timeout: float = 120.0) -> Dict[str, Any]:
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(doc).encode("utf-8"),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        retry_after = resp.getheader("Retry-After")
        try:
            # The serve stack always sends integer seconds; a foreign
            # proxy could legally send an HTTP-date — treat that as
            # "no hint" rather than crash the client thread.
            retry_after = (float(retry_after)
                           if retry_after is not None else None)
        except ValueError:
            retry_after = None
        return {"status": resp.status, "body": json.loads(body),
                "trace_id": resp.getheader("X-Pvraft-Trace"),
                "retry_after": retry_after}
    finally:
        conn.close()


def _get_json(host: str, port: int, path: str,
              timeout: float = 30.0) -> Dict[str, Any]:
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return json.loads(resp.read())
    finally:
        conn.close()


def _endpoints(server, targets) -> List[Tuple[str, int]]:
    """Resolve the endpoint list a load run round-robins over: the
    in-process ``server`` (the historical single-target path) and/or
    ``targets`` — "host:port" strings, (host, port) tuples, or objects
    with ``host``/``port`` (e.g. another started server)."""
    eps: List[Tuple[str, int]] = []
    if server is not None:
        eps.append((server.host, int(server.port)))
    for t in targets or ():
        if isinstance(t, str):
            host, _, port = t.rpartition(":")
            host = host or "127.0.0.1"
            # Accept URL spellings ("http://h:p/") without pulling in a
            # URL parser: strip scheme prefix and trailing slash.
            if host.startswith(("http://", "https://")):
                host = host.split("://", 1)[1]
            eps.append((host, int(port.rstrip("/"))))
        elif isinstance(t, (tuple, list)):
            eps.append((str(t[0]), int(t[1])))
        else:
            eps.append((t.host, int(t.port)))
    if not eps:
        raise ValueError("run_load needs a server or at least one target")
    return eps


def run_load(
    server=None,                  # a started ServeHTTPServer (or None)
    n_requests: int = 0,
    concurrency: int = 1,
    point_counts: Optional[List[int]] = None,
    seed: int = 0,
    coord_scale: float = 1.0,
    retries: int = 0,
    backoff_ms: float = 50.0,
    targets: Optional[List[Any]] = None,
) -> Dict[str, Any]:
    """Issue ``n_requests`` over ``concurrency`` client threads against a
    running server; returns the raw measurement dict (no schema fields).
    Point counts cycle through ``point_counts`` so every bucket is hit.

    ``retries`` (default 0 — committed pre-chaos artifacts keep their
    exact semantics) bounds client-side re-attempts of 503 responses:
    each retry backs off by the server's ``Retry-After`` when present
    (else an exponential ``backoff_ms`` ladder), jittered 0.5-1.5x with
    a per-request deterministic RNG so two shed clients don't re-arrive
    in lockstep. Every attempt is recorded: a retried request's
    ``per_request`` entry carries an ``attempts`` list (schema-additive)
    and its top-level status/ms are the FINAL attempt's — a request that
    eventually succeeds counts ``ok``.

    ``targets`` (fleet evidence, ISSUE 20): additional/alternative
    endpoints; requests round-robin across the full endpoint list by
    request index, and a retried request rotates to the NEXT endpoint
    (a shed client fails over instead of hammering the host that shed
    it). With several endpoints ``server_metrics`` becomes
    ``{"targets": [{"target": "host:port", ...snapshot...}, ...]}`` —
    schema-additive, the single-target shape is unchanged."""
    eps = _endpoints(server, targets)
    point_counts = point_counts or []
    rng = host_rng(seed, "serve.loadgen")
    # Pre-generate the request payloads so client threads measure the
    # server, not numpy.
    payloads = []
    sizes = []          # recorded at build time: per_request[].n and the
    for i in range(n_requests):  # size histogram report what was DRIVEN
        n = point_counts[i % len(point_counts)]
        pc1 = rng.uniform(-coord_scale, coord_scale, (n, 3)).astype(np.float32)
        flow = rng.normal(0, 0.05 * coord_scale, (n, 3)).astype(np.float32)
        payloads.append({"pc1": pc1.tolist(), "pc2": (pc1 + flow).tolist()})
        sizes.append(n)

    results: List[Dict[str, Any]] = [None] * n_requests  # type: ignore
    cursor = {"i": 0}
    cursor_lock = threading.Lock()

    def client():
        while True:
            with cursor_lock:
                i = cursor["i"]
                if i >= n_requests:
                    return
                cursor["i"] = i + 1
            jitter = host_rng(seed, "serve.retry_jitter", i)
            attempts: List[Dict[str, Any]] = []
            for attempt in range(retries + 1):
                t0 = time.monotonic()
                retry_after = None
                host, port = eps[(i + attempt) % len(eps)]
                try:
                    r = _post_json(host, port, "/predict",
                                   payloads[i])
                    ms = (time.monotonic() - t0) * 1000.0
                    retry_after = r.get("retry_after")
                    attempts.append({"status": r["status"],
                                     "ms": round(ms, 3)})
                    result = {"status": r["status"], "ms": ms,
                              "trace_id": r["trace_id"]}
                except Exception as e:  # noqa: BLE001 — a client error is data
                    attempts.append({"status": -1, "ms": None})
                    result = {"status": -1, "ms": None, "trace_id": None,
                              "error": f"{type(e).__name__}: {e}"}
                if result["status"] != 503 or attempt == retries:
                    break
                # Bounded retry of explicit backpressure only (503):
                # honor Retry-After when the server derives one from its
                # probe cadence, else the exponential ladder; jittered
                # so shed clients spread out, capped so a chaos run's
                # wall clock stays bounded.
                base = (retry_after if retry_after is not None
                        else (backoff_ms / 1000.0) * (2 ** attempt))
                time.sleep(min(base, 5.0) * (0.5 + jitter.random()))
            if len(attempts) > 1:
                result["attempts"] = attempts
            results[i] = result

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(concurrency)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    duration = time.monotonic() - t_start

    ok = [r for r in results if r["status"] == 200]
    rejected = [r for r in results if r["status"] in (400, 413, 503, 504)]
    # Everything else (transport failures recorded as -1, but also any
    # unexpected status such as a 500) counts as an error so the
    # ok+rejected+errors == total schema invariant holds by construction.
    errors = [r for r in results
              if r["status"] not in (200, 400, 413, 503, 504)]
    lat = sorted(r["ms"] for r in ok)

    # The SAME nearest-rank estimator the SLO report uses (its join
    # reconciles client quantiles against span quantiles — reuse, not a
    # parallel implementation that could drift).
    from pvraft_tpu.obs.slo import exact_quantile

    def pct(q: float) -> Optional[float]:
        v = exact_quantile(lat, q)
        return None if v is None else round(v, 3)

    # Client-side request-size histogram on the server's exposed edges
    # (pvraft_serve_request_points): the artifact records what sizes
    # were DRIVEN, the server's histogram what it SAW — the pair must
    # reconcile (same histogram class, same bucketing rule), and either
    # seeds adaptive bucket geometry offline.
    from pvraft_tpu.serve.metrics import POINT_EDGES, LatencyHistogram

    size_hist = LatencyHistogram(edges=POINT_EDGES)
    for n in sizes:
        size_hist.observe(float(n))

    return {
        "requests": {"total": n_requests, "ok": len(ok),
                     "rejected": len(rejected), "errors": len(errors)},
        "latency_ms": {
            "p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
            "mean": round(float(np.mean(lat)), 3) if lat else None,
            "max": round(lat[-1], 3) if lat else None,
        },
        "throughput_rps": round(len(ok) / duration, 3) if duration else 0.0,
        "duration_s": round(duration, 3),
        "per_request": [
            {"status": r["status"],
             "ms": round(r["ms"], 3) if r["ms"] is not None else None,
             "n": sizes[i],
             "trace_id": r.get("trace_id"),
             # Per-attempt record of retried requests (absent when the
             # request went through in one attempt — schema-additive).
             **({"attempts": r["attempts"]} if "attempts" in r else {})}
            for i, r in enumerate(results)],
        "request_points": {"edges": [int(e) for e in POINT_EDGES],
                           "counts": list(size_hist.counts)},
        "server_metrics": (
            _get_json(*eps[0], "/metrics") if len(eps) == 1 else
            {"targets": [
                {"target": f"{h}:{p}", **_get_json(h, p, "/metrics")}
                for h, p in eps]}),
    }
