"""Bucket-geometry advisor: learn the bucket table from live traffic.

The serve engine's bucket table is a padding/compile trade: every
request pads up to its bucket, so bucket edges far above the typical
request size burn device FLOPs on padding, while too many buckets
multiply AOT compile cost and HBM-resident programs. PR 7 committed the
seed data for closing this loop — the ``pvraft_serve_request_points``
histogram (server-side) and the loadgen artifact's ``request_points``
mirror (client-side) record what sizes production actually sees.

This module turns that histogram into a proposed bucket table:

* a request whose size lands in histogram bin *i* is only known to be
  ``<= edges[i]``, so the bin's UPPER edge is the smallest bucket that
  provably serves it — candidate buckets are exactly the non-empty
  bins' upper edges (anything between two edges is unsupported by the
  data, anything above the top non-empty edge is pure waste);
* choosing ``n_buckets`` of those candidates to minimize the expected
  *cost per request* is a classic contiguous-partition DP, exact in
  O(bins^2 * n_buckets);
* the DP's objective is **predicted device-seconds** when a
  :class:`~pvraft_tpu.programs.costs.CostSurface` covers every
  candidate bucket exactly (ISSUE 14 / ROADMAP items 3+5: an
  8192-point bucket and a 2048-point bucket are not the same unit of
  work, and the certified cost records say by how much) — and falls
  back to the PR-8 *expected device points* proxy with a loud
  ``objective.note`` when the surface does not cover the proposal
  geometry (scoring uncertified buckets in certified seconds would be
  fiction);
* the same cost model scores the CURRENT table
  (``programs/geometries.SERVE_DEFAULT_BUCKETS``) on the same
  histogram, so the report is a cross-check, not just a proposal —
  including the fraction of observed traffic the current table rejects.

``scripts/bucket_advisor.py`` is the CLI; the proposal is advisory
(a human promotes it into ``geometries.py``, where the registry /
deepcheck / AOT evidence pick it up) — this tool never mutates the
declared geometry.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

ADVISOR_SCHEMA = "pvraft_bucket_advisor/v1"


def _bins(edges: Sequence[float],
          counts: Sequence[int]) -> List[Tuple[int, int]]:
    """Non-empty (upper_edge, count) bins, ascending. The overflow bin
    (counts[-1], sizes beyond the last edge) has no upper edge and is
    reported separately — no bucket table derived from this histogram
    can serve it."""
    if len(counts) != len(edges) + 1:
        raise ValueError(
            f"histogram shape mismatch: {len(counts)} counts for "
            f"{len(edges)} edges (want len(edges) + 1)")
    return [(int(edges[i]), int(c))
            for i, c in enumerate(counts[:-1]) if c]


def _cost_keys(bucket_cost: Optional[Dict[int, float]]
               ) -> Tuple[str, str, int]:
    """(per-request key, ideal key, rounding digits) for the active
    objective: device points (the PR-8 proxy) or predicted
    device-seconds (ISSUE 14, when a cost table is supplied)."""
    if bucket_cost is None:
        return "points_per_request", "ideal_points_per_request", 2
    return ("device_seconds_per_request",
            "ideal_device_seconds_per_request", 6)


def propose_buckets(edges: Sequence[float], counts: Sequence[int],
                    n_buckets: int,
                    min_bucket: int = 0,
                    bucket_cost: Optional[Dict[int, float]] = None
                    ) -> Dict[str, Any]:
    """The optimal ``n_buckets``-entry bucket table for this histogram
    (exact DP). The objective is expected device POINTS per request by
    default; ``bucket_cost`` (candidate bucket -> predicted
    device-seconds one request costs there, from
    ``CostSurface.serve_seconds_per_request``) swaps it to expected
    device-SECONDS — it must cover every candidate value, which the
    caller guarantees (``build_advisor_report`` falls back to points
    otherwise). Buckets below ``min_bucket`` (the engine's
    ``min_points`` floor or a hardware tile constraint) are disallowed;
    bins below it are served by the smallest legal bucket."""
    if n_buckets < 1:
        raise ValueError("n_buckets must be >= 1")
    bins = _bins(edges, counts)
    overflow = int(counts[-1])
    if not bins:
        raise ValueError("histogram has no in-range samples")
    # Respect the floor: candidate bucket values below min_bucket are
    # illegal, so merge their bins into the first legal candidate.
    candidates = sorted({max(edge, min_bucket) for edge, _ in bins})
    weight = {c: 0 for c in candidates}
    for edge, count in bins:
        weight[max(edge, min_bucket)] += count
    values = candidates
    if bucket_cost is not None:
        missing = [v for v in values if v not in bucket_cost]
        if missing:
            raise ValueError(
                f"bucket_cost does not cover candidate buckets "
                f"{missing} — fall back to the device-points objective "
                "instead of pricing uncertified geometry")
    w = [weight[v] for v in values]
    n = len(values)
    k_max = min(n_buckets, n)
    # dp[k][i]: min cost serving bins[0..i] with k buckets, the last
    # bucket being values[i] (a bucket table must include the largest
    # non-empty candidate or it rejects observed traffic).
    inf = float("inf")
    # prefix weights for O(1) range sums
    prefix = [0]
    for x in w:
        prefix.append(prefix[-1] + x)

    def unit_cost(v: int) -> float:
        return float(v) if bucket_cost is None else float(bucket_cost[v])

    def seg(j: int, i: int) -> float:
        """Cost of bins j..i all served by values[i]."""
        return (prefix[i + 1] - prefix[j]) * unit_cost(values[i])

    dp = [[inf] * n for _ in range(k_max + 1)]
    choice = [[-1] * n for _ in range(k_max + 1)]
    for i in range(n):
        dp[1][i] = seg(0, i)
    for k in range(2, k_max + 1):
        for i in range(k - 1, n):
            for j in range(k - 2, i):
                cost = dp[k - 1][j] + seg(j + 1, i)
                if cost < dp[k][i]:
                    dp[k][i] = cost
                    choice[k][i] = j
    best_k = min(k_max, n)
    cost = dp[best_k][n - 1]
    # Walk the choices back into the bucket list.
    buckets: List[int] = []
    k, i = best_k, n - 1
    while k >= 1 and i >= 0:
        buckets.append(values[i])
        i = choice[k][i]
        k -= 1
    buckets.reverse()
    total = sum(w)
    ideal = sum(cw * unit_cost(v)                    # one bucket per bin
                for v, cw in zip(values, w))
    per_key, ideal_key, digits = _cost_keys(bucket_cost)
    return {
        "buckets": buckets,
        per_key: round(cost / total, digits),
        ideal_key: round(ideal / total, digits),
        "overhead_vs_ideal": round(cost / ideal - 1.0, 4) if ideal else None,
        "requests": total,
        "overflow_requests": overflow,
    }


def score_buckets(buckets: Sequence[int], edges: Sequence[float],
                  counts: Sequence[int],
                  bucket_cost: Optional[Dict[int, float]] = None
                  ) -> Dict[str, Any]:
    """Expected cost per request of an EXISTING bucket table on this
    histogram (same objective switch as :func:`propose_buckets` —
    device points, or device-seconds when ``bucket_cost`` covers the
    table), plus the fraction of observed traffic it rejects (bins
    whose upper edge exceeds the largest bucket, and the overflow
    bin)."""
    bins = _bins(edges, counts)
    overflow = int(counts[-1])
    table = sorted(buckets)
    if bucket_cost is not None:
        missing = [b for b in table if int(b) not in bucket_cost]
        if missing:
            raise ValueError(
                f"bucket_cost does not cover table buckets {missing}")
    served_cost = 0.0
    served = rejected = 0
    per_bucket = {int(b): 0 for b in table}
    for edge, count in bins:
        bucket = next((b for b in table if edge <= b), None)
        if bucket is None:
            rejected += count
            continue
        served += count
        served_cost += count * (float(bucket) if bucket_cost is None
                                else float(bucket_cost[int(bucket)]))
        per_bucket[bucket] += count
    rejected += overflow
    total = served + rejected
    per_key, _, digits = _cost_keys(bucket_cost)
    return {
        "buckets": [int(b) for b in table],
        per_key: (round(served_cost / served, digits)
                  if served else None),
        "requests": total,
        "served_requests": served,
        "rejected_requests": rejected,
        "rejected_fraction": round(rejected / total, 4) if total else None,
        "per_bucket_requests": per_bucket,
    }


def candidate_buckets(edges: Sequence[float], counts: Sequence[int],
                      min_bucket: int = 0) -> List[int]:
    """The candidate bucket values :func:`propose_buckets` will choose
    from (non-empty bins' upper edges, min_bucket-folded) — exposed so
    the cost-surface coverage check and the DP agree on the exact set."""
    return sorted({max(edge, min_bucket)
                   for edge, _ in _bins(edges, counts)})


def build_advisor_report(edges: Sequence[float], counts: Sequence[int],
                         current_buckets: Sequence[int],
                         n_buckets: Optional[int] = None,
                         min_bucket: int = 0,
                         source: str = "<histogram>",
                         cost_surface=None,
                         dtype: str = "bfloat16") -> Dict[str, Any]:
    """The full advisory: proposed table (same size as the current one
    unless ``n_buckets`` overrides), current-table score, and the
    improvement — all from one committed histogram.

    ``cost_surface`` (a :class:`~pvraft_tpu.programs.costs.CostSurface`)
    promotes the objective from expected device points to PREDICTED
    DEVICE-SECONDS when the surface's certified serve records cover
    every candidate bucket AND the current table exactly; otherwise the
    report falls back to points with a loud ``objective.note`` naming
    the uncovered buckets (pricing uncertified geometry in certified
    seconds would be fiction — the registry certifies a proposal first,
    then the seconds objective scores it)."""
    k = n_buckets or len(current_buckets)
    bucket_cost = None
    objective: Dict[str, Any] = {"unit": "device_points"}
    if cost_surface is not None:
        need = sorted(set(candidate_buckets(edges, counts, min_bucket))
                      | {int(b) for b in current_buckets})
        costs = {b: cost_surface.serve_seconds_per_request(b, dtype)
                 for b in need}
        uncovered = sorted(b for b, c in costs.items() if c is None)
        if uncovered:
            objective["note"] = (
                f"cost surface has no certified serve record for "
                f"buckets {uncovered} (dtype {dtype}) — scoring in "
                "expected device points instead of predicted "
                "device-seconds")
        else:
            bucket_cost = costs
            objective = {"unit": "device_seconds", "dtype": dtype,
                         "surface": getattr(cost_surface, "path", None)}
    per_key, _, _ = _cost_keys(bucket_cost)
    proposed = propose_buckets(edges, counts, k, min_bucket=min_bucket,
                               bucket_cost=bucket_cost)
    current = score_buckets(current_buckets, edges, counts,
                            bucket_cost=bucket_cost)
    improvement = None
    if current[per_key] and current["served_requests"]:
        # Compare on the SAME population: the proposed table serves all
        # in-range traffic while the current one may reject part of it,
        # and per-request costs over different populations are not
        # comparable (a more-capable table would look like a regression
        # because it serves the big requests the current table bounces).
        # Re-score the proposal on exactly the bins the current table
        # serves; the extra traffic the proposal unlocks is reported as
        # the rejected fraction next to it, not folded into the cost.
        largest_current = max(current_buckets)
        served_counts = [
            c if i < len(edges) and edges[i] <= largest_current else 0
            for i, c in enumerate(counts)]
        proposed_on_served = score_buckets(
            proposed["buckets"], edges, served_counts,
            bucket_cost=bucket_cost)
        saved = current[per_key] - proposed_on_served[per_key]
        _, _, digits = _cost_keys(bucket_cost)
        improvement = {
            f"{per_key}_saved": round(saved, digits),
            "relative": round(saved / current[per_key], 4),
            "population": "traffic served by the current table",
        }
    return {
        "schema": ADVISOR_SCHEMA,
        "source": source,
        "histogram": {"edges": [int(e) for e in edges],
                      "counts": [int(c) for c in counts]},
        "min_bucket": int(min_bucket),
        "objective": objective,
        "proposed": proposed,
        "current": current,
        "improvement": improvement,
    }
