"""Low-latency scene-flow inference service.

A trained checkpoint becomes an HTTP service through four layers:

  * :mod:`pvraft_tpu.serve.engine` — AOT-bucketed
    :class:`InferenceEngine`: pads variable-N requests into a fixed
    bucket set, compiles one donated predict program per (bucket, batch
    size) at startup, and guarantees padded predictions match unpadded
    inference (masked GroupNorm/correlation + far padding);
  * :mod:`pvraft_tpu.serve.batcher` — :class:`MicroBatcher`: bounded
    per-bucket queues, straggler-bounded grouping, explicit
    backpressure (raise, never block), graceful drain;
  * :mod:`pvraft_tpu.serve.server` — :class:`ServeHTTPServer`: stdlib
    JSON/msgpack HTTP API (``/predict``, ``/healthz``, ``/metrics``);
  * :mod:`pvraft_tpu.serve.events` — :class:`ServeTelemetry`: serve
    lifecycle on the ``pvraft_events/v1`` stream (one validator for
    training AND serving);
  * :mod:`pvraft_tpu.serve.supervisor` — :class:`ReplicaSupervisor`:
    per-replica health state machine (healthy/degraded/quarantined/
    probing), background probe revival, retry-once-on-another-replica
    and healthy-count-scaled admission (graceful degradation);
  * :mod:`pvraft_tpu.serve.faults` — deterministic fault injection:
    named fault points armed by an explicit :class:`FaultPlan`
    (zero-cost when disarmed) — the chaos harness that PROVES the
    fault-tolerance layer instead of asserting it.

CLI: ``python -m pvraft_tpu.serve serve --ckpt ...`` runs the service;
``scripts/serve_loadgen.py`` measures it; ``scripts/serve_chaos.py``
commits the chaos evidence.
"""

from pvraft_tpu.serve.batcher import (          # noqa: F401
    BatcherConfig,
    MicroBatcher,
    PoolUnavailableError,
    QueueFullError,
    ShutdownError,
)
from pvraft_tpu.serve.engine import (           # noqa: F401
    InferenceEngine,
    RequestError,
    ServeConfig,
)
from pvraft_tpu.serve.costing import ServeCostModel         # noqa: F401
from pvraft_tpu.serve.events import ServeTelemetry          # noqa: F401
from pvraft_tpu.serve.faults import (                       # noqa: F401
    FaultPlan,
    FaultRule,
    InjectedFaultError,
)
from pvraft_tpu.serve.metrics import ServeMetrics           # noqa: F401
from pvraft_tpu.serve.server import (                       # noqa: F401
    ServeHTTPServer,
    build_service,
)
from pvraft_tpu.serve.supervisor import (                   # noqa: F401
    ReplicaSupervisor,
    SupervisorConfig,
)
