"""Bounded-queue micro-batcher with explicit backpressure.

One worker thread per bucket pulls requests off that bucket's bounded
queue, groups them up to ``max_batch`` (waiting at most ``max_wait_ms``
for stragglers once the first request is in hand), and dispatches the
group through the engine's AOT program for that (bucket, batch size).

Backpressure is explicit, never implicit blocking: a full queue raises
:class:`QueueFullError` at ``submit`` time (the HTTP layer maps it to
503) instead of stalling the caller — under sustained overload the
client sees load-shedding immediately, and queue depth (not client
sockets) bounds the in-flight work.

Shutdown drains: ``shutdown(drain=True)`` stops intake, lets every
queued request finish, then joins the workers; ``drain=False`` fails
queued requests with :class:`ShutdownError` instead. Both are
test-gated under real thread concurrency (``tests/test_serve.py``).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from pvraft_tpu.serve.engine import InferenceEngine, RequestError


class QueueFullError(RuntimeError):
    """The bucket's queue is at capacity — shed load (HTTP 503)."""


class ShutdownError(RuntimeError):
    """The batcher is no longer accepting requests (HTTP 503)."""


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    max_batch: int = 4        # largest group per dispatch
    max_wait_ms: float = 5.0  # straggler wait once a group has a member
    queue_depth: int = 64     # per-bucket bounded queue capacity

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if self.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")


class _Request:
    __slots__ = ("pc1", "pc2", "result", "error", "done", "t_enqueue",
                 "abandoned", "trace", "bucket", "t_dequeue")

    def __init__(self, pc1: np.ndarray, pc2: np.ndarray):
        self.pc1 = pc1
        self.pc2 = pc2
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.abandoned = False
        # Trace plane (obs/trace.py): the handler attaches a
        # RequestTrace for sampled requests; workers stamp dequeue /
        # dispatch times on it. None = unsampled (the common case) —
        # every hook below is a single attribute check.
        self.trace = None
        self.bucket: Optional[int] = None
        self.t_dequeue: Optional[float] = None

    def resolve(self, result: np.ndarray) -> None:
        self.result = result
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.done.wait(timeout):
            # The waiter is gone (HTTP 504 already sent): mark the
            # request so a worker that later pulls it off the queue
            # skips the dispatch instead of computing an answer nobody
            # reads. Benign race: a concurrent resolve just wastes the
            # one result.
            self.abandoned = True
            raise TimeoutError("predict did not complete in time")
        if self.error is not None:
            raise self.error
        assert self.result is not None
        return self.result


class MicroBatcher:
    """Per-bucket bounded queues + worker threads over an engine."""

    def __init__(self, engine: InferenceEngine, cfg: BatcherConfig,
                 telemetry=None, metrics=None):
        largest = max(engine.cfg.batch_sizes)
        if cfg.max_batch > largest:
            raise ValueError(
                f"max_batch={cfg.max_batch} exceeds the largest compiled "
                f"batch size {largest}: the engine has no AOT program for a "
                f"bigger group and _dispatch never splits, so every "
                f"oversized group would fail wholesale")
        self.engine = engine
        self.cfg = cfg
        self.telemetry = telemetry
        self.metrics = metrics
        self._queues: Dict[int, "queue.Queue[_Request]"] = {
            b: queue.Queue(maxsize=cfg.queue_depth)
            for b in engine.cfg.buckets}
        self._stopping = threading.Event()
        # Serializes the submit-side {stopping check -> enqueue} against
        # shutdown setting the flag: without it a submit could pass the
        # check, lose the CPU while shutdown joins the workers AND runs
        # its sweep, then enqueue into a queue nobody will ever read —
        # stranding an accepted request (504/hang instead of 503).
        self._intake_lock = threading.Lock()
        self._drain = True
        self._served = 0
        self._rejected = 0
        self._drained = 0
        self._count_lock = threading.Lock()
        self._workers = [
            threading.Thread(target=self._worker, args=(b,),
                             name=f"pvraft-serve-b{b}", daemon=True)
            for b in engine.cfg.buckets
        ]
        for w in self._workers:
            w.start()

    # ------------------------------------------------------------- intake --

    def submit(self, pc1: np.ndarray, pc2: np.ndarray,
               trace=None) -> _Request:
        """Validate and enqueue one request; returns a handle whose
        ``wait()`` yields the un-padded (n1, 3) flow. Raises
        :class:`RequestError` (contract), :class:`QueueFullError`
        (backpressure) or :class:`ShutdownError` (draining). ``trace``
        is an optional ``obs.trace.RequestTrace``: the validate stage is
        marked here, the queue/dispatch stages by the workers."""
        t_validate = time.monotonic()
        try:
            bucket = self.engine.validate_request(pc1, pc2)
        except RequestError as e:
            if trace is not None:
                trace.mark("validate", t_validate, time.monotonic(),
                           attrs={"rejected": e.reason})
            self._reject(e.reason)
            raise
        if trace is not None:
            trace.mark("validate", t_validate, time.monotonic())
        req = _Request(np.asarray(pc1, np.float32),
                       np.asarray(pc2, np.float32))
        req.trace = trace
        req.bucket = bucket
        n_points = max(pc1.shape[0], pc2.shape[0])
        req.t_enqueue = time.monotonic()
        # Check-and-enqueue is atomic w.r.t. shutdown (see _intake_lock):
        # an enqueue here happens-before the stop flag is set, so the
        # workers (or the drain sweep) are guaranteed to see it. The lock
        # covers ONLY that pair — reject accounting does telemetry file
        # I/O and must not serialize intake across buckets under the
        # exact overload that makes rejects frequent.
        reject = None
        with self._intake_lock:
            if self._stopping.is_set():
                reject = "shutdown"
            elif self._queues[bucket].full():
                # Submitters are serialized by _intake_lock and workers
                # only remove, so a not-full queue here cannot fill
                # before the put below — the full() check IS the
                # admission decision.
                reject = "queue_full"
            else:
                # Count the submit BEFORE the enqueue becomes visible to
                # a worker: otherwise a dispatched response could reach
                # record_batch first and a concurrent /metrics snapshot
                # would see responses_total > requests_total. Counter
                # increments only — no telemetry I/O under the lock.
                if self.metrics is not None:
                    self.metrics.record_submit(bucket, n_points=n_points)
                self._queues[bucket].put_nowait(req)
        if reject == "shutdown":
            self._reject("shutdown")
            raise ShutdownError("server is shutting down")
        if reject == "queue_full":
            self._reject("queue_full", bucket=bucket,
                         queue_depth=self.cfg.queue_depth)
            raise QueueFullError(
                f"bucket {bucket} queue is full "
                f"({self.cfg.queue_depth} pending)") from None
        return req

    def record_reject(self, reason: str) -> None:
        """Count a reject that never reached ``submit`` (e.g. the HTTP
        layer's body decode / body-size failures) so ``/metrics`` and
        the ``serve_reject`` event stream agree with what clients saw."""
        self._reject(reason)

    def record_failure(self, reason: str) -> None:
        """Count an ACCEPTED request that never produced a response
        (504 predict timeout, 500 engine failure): already counted at
        submit, so only the outcome is recorded — otherwise /metrics
        totals never reconcile under sustained slowness and the
        load-gen artifact's client counts contradict server_metrics."""
        with self._count_lock:
            self._rejected += 1
        if self.metrics is not None:
            self.metrics.record_failure(reason)
        if self.telemetry is not None:
            self.telemetry.emit_reject(reason)

    def _reject(self, reason: str, bucket: Optional[int] = None,
                queue_depth: Optional[int] = None) -> None:
        with self._count_lock:
            self._rejected += 1
        if self.metrics is not None:
            self.metrics.record_reject(reason)
        if self.telemetry is not None:
            self.telemetry.emit_reject(reason, bucket=bucket,
                                       queue_depth=queue_depth)

    def queue_depths(self) -> Dict[int, int]:
        return {b: q.qsize() for b, q in self._queues.items()}

    # ------------------------------------------------------------- worker --

    def _collect(self, q: "queue.Queue[_Request]") -> List[_Request]:
        """One group: block briefly for a first request (so the stop flag
        is polled), then gather up to max_batch until max_wait_ms."""
        try:
            first = q.get(timeout=0.05)
        except queue.Empty:
            return []
        first.t_dequeue = time.monotonic()
        group = [first]
        deadline = first.t_dequeue + self.cfg.max_wait_ms / 1000.0
        while len(group) < self.cfg.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                req = q.get(timeout=remaining)
            except queue.Empty:
                break
            req.t_dequeue = time.monotonic()
            group.append(req)
        return group

    def _worker(self, bucket: int) -> None:
        q = self._queues[bucket]
        while True:
            group = self._collect(q)
            if not group:
                if self._stopping.is_set():
                    if not self._drain:
                        break
                    if q.empty():
                        break
                continue
            if self._stopping.is_set() and not self._drain:
                for req in group:
                    self.record_failure("shutdown")
                    req.fail(ShutdownError("server stopped without drain"))
                continue
            self._dispatch(bucket, group)

    def _dispatch(self, bucket: int, group: List[_Request]) -> None:
        # Drop requests whose waiter already timed out (504 sent): the
        # engine time would buy an answer nobody reads, and counting
        # them as served would report success for client-visible
        # failures.
        group = [r for r in group if not r.abandoned]
        if not group:
            return
        t0 = time.monotonic()
        try:
            flows = self.engine.predict_batch(
                [(r.pc1, r.pc2) for r in group], bucket)
        except BaseException as e:  # noqa: BLE001 — fail the group, not the worker
            for req in group:
                req.fail(e)
            return
        now = time.monotonic()
        # Re-check abandonment AFTER the engine call: a waiter can 504
        # while predict runs (seconds), and its request must not be
        # counted as served or have its (by-definition over-deadline)
        # latency skew the histogram. The remaining race — a timeout
        # between this check and the waiter reading the result — is the
        # benign one noted in _Request.wait.
        live = [(r, f) for r, f in zip(group, flows) if not r.abandoned]
        bs = self.engine.batch_size_for(len(group))
        for r, _ in live:
            # Re-read trace/abandoned per request: a waiter that 504'd
            # since `live` was computed is assembling its (partial) span
            # tree RIGHT NOW — skip marking it rather than race the
            # iteration. (The residual window — abandonment landing
            # mid-loop — only under-fills an error trace's tree, which
            # is the documented shape of error-outcome traces.)
            tr = r.trace
            if tr is None or r.abandoned:
                continue
            # queue_wait: enqueue -> dequeue; batch_form: dequeue ->
            # dispatch (straggler wait + grouping); device_execute: the
            # AOT program incl. host fetch. For served requests the
            # marks land before resolve() below, so the handler thread
            # (which assembles spans after wait() returns) is
            # ordered-after them.
            t_dq = r.t_dequeue if r.t_dequeue is not None else t0
            tr.mark("queue_wait", r.t_enqueue, t_dq)
            tr.mark("batch_form", t_dq, t0)
            tr.mark("device_execute", t0, now,
                    attrs={"bucket": bucket, "batch": bs,
                           "n": len(group)})
        latencies = [(now - r.t_enqueue) * 1000.0 for r, _ in live]
        # Account BEFORE resolving: resolve() unblocks the HTTP replies,
        # and a client that immediately polls /metrics must see counts
        # covering every response it has already received.
        with self._count_lock:
            self._served += len(live)
            if self._stopping.is_set():
                self._drained += len(live)
        # Fill reflects the dispatch itself (how full the AOT program's
        # slots were), so it stays keyed on the dispatched group size.
        fill = len(group) / bs
        if self.metrics is not None:
            self.metrics.record_batch(len(live), fill, latencies)
        if self.telemetry is not None:
            self.telemetry.emit_batch(
                bucket=bucket, batch=bs, n=len(live),
                fill=round(fill, 4),
                latency_ms=round((now - t0) * 1000.0, 3),
                queue_depth=self._queues[bucket].qsize())
        for req, flow in live:
            req.resolve(flow)

    # ----------------------------------------------------------- shutdown --

    def shutdown(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop intake; ``drain=True`` finishes queued work first. Safe
        to call twice. Emits the ``serve_shutdown`` summary event."""
        with self._intake_lock:
            already = self._stopping.is_set()
            self._drain = drain
            self._stopping.set()
        for w in self._workers:
            w.join(timeout)
        if drain:
            # Defense-in-depth: _intake_lock guarantees every accepted
            # enqueue happens-before the stop flag, and a worker only
            # exits on (stopping AND empty), so nothing should be left.
            # Serve any stragglers inline anyway so a drained shutdown
            # can never strand an accepted request.
            for bucket, q in self._queues.items():
                while True:
                    group: List[_Request] = []
                    while len(group) < self.cfg.max_batch:
                        try:
                            group.append(q.get_nowait())
                        except queue.Empty:
                            break
                    if not group:
                        break
                    self._dispatch(bucket, group)
        if not drain:
            # Fail anything the workers didn't pick up.
            for q in self._queues.values():
                while True:
                    try:
                        req = q.get_nowait()
                    except queue.Empty:
                        break
                    self.record_failure("shutdown")
                    req.fail(ShutdownError("server stopped without drain"))
        if self.telemetry is not None and not already:
            with self._count_lock:
                self.telemetry.emit_shutdown(
                    served=self._served, rejected=self._rejected,
                    drained=self._drained)

    @property
    def counts(self) -> Dict[str, int]:
        with self._count_lock:
            return {"served": self._served, "rejected": self._rejected,
                    "drained": self._drained}
