"""Continuous micro-batching across a replica pool, with backpressure.

Three thread tiers replace PR 5's one-blocking-worker-per-bucket:

  * per-bucket **collector** threads pull requests off their bucket's
    bounded queue and form groups (up to ``max_batch``, straggler window
    ``max_wait_ms``) — but never execute;
  * one bounded **batch queue** hands formed groups to the pool
    (capacity = replica count: batches beyond the pool's concurrency
    stay as *requests* in their bucket queue, where ``queue_depth``
    backpressure still governs intake);
  * per-replica **executor** threads take the next formed group —
    whichever bucket it came from — and run it on their replica
    (work-stealing: a slow large-bucket batch occupies one replica
    while the other executors keep draining the small buckets; nothing
    head-of-line-blocks, test-gated in ``tests/test_serve_pool.py``).

Continuous-batching rule: a collector waits out the straggler window
ONLY while every replica is busy. When capacity sits idle the group
dispatches immediately — holding work to fill a batch is a throughput
trade that only pays when the device is the bottleneck (the measured
CPU A/B win in BENCHMARKS.md; ``eager_when_idle=False`` restores the
PR-7 always-wait behavior for baselines).

Backpressure is explicit, never implicit blocking: a full bucket queue
raises :class:`QueueFullError` at ``submit`` time (the HTTP layer maps
it to 503) instead of stalling the caller — under sustained overload
the client sees load-shedding immediately, and queue depth (not client
sockets) bounds the in-flight work.

Shutdown drains: ``shutdown(drain=True)`` stops intake, lets every
queued request finish, then joins the threads; ``drain=False`` fails
queued requests with :class:`ShutdownError` instead. Both are
test-gated under real thread concurrency (``tests/test_serve.py``).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.serve import faults
from pvraft_tpu.serve.engine import InferenceEngine, RequestError


class QueueFullError(RuntimeError):
    """The bucket's queue is at capacity — shed load (HTTP 503)."""


class ShutdownError(RuntimeError):
    """The batcher is no longer accepting requests (HTTP 503)."""


class PoolUnavailableError(RuntimeError):
    """Every replica is quarantined: graceful degradation rejects at
    admission (HTTP 503 ``unavailable`` + ``Retry-After``) instead of
    accepting work that can only become queue-timeout 504s."""


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    max_batch: int = 4        # largest group per dispatch
    max_wait_ms: float = 5.0  # straggler wait once a group has a member
    queue_depth: int = 64     # per-bucket bounded queue capacity
    # Continuous batching: dispatch a partial group immediately when a
    # replica is idle and no formed batch is waiting (the straggler
    # window only pays when it buys utilization). False = PR-7 baseline
    # semantics: always wait out max_wait_ms (the A/B control leg).
    eager_when_idle: bool = True

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if self.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")


class _Request:
    __slots__ = ("pc1", "pc2", "result", "error", "done", "t_enqueue",
                 "abandoned", "trace", "bucket", "t_dequeue", "_final")

    def __init__(self, pc1: np.ndarray, pc2: np.ndarray):
        self.pc1 = pc1
        self.pc2 = pc2
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.abandoned = False
        # Outcome-recording token: exactly ONE party (the dispatch loop
        # counting a response, or the failure path counting a reject)
        # may record this request's ledger outcome. Without it, a
        # waiter timing out in the window between the dispatch loop's
        # liveness check and its accounting gets counted TWICE (a
        # response AND a timeout), permanently skewing the in_flight
        # gauge and the reconciliation identity.
        self._final = threading.Lock()
        # Trace plane (obs/trace.py): the handler attaches a
        # RequestTrace for sampled requests; workers stamp dequeue /
        # dispatch times on it. None = unsampled (the common case) —
        # every hook below is a single attribute check.
        self.trace = None
        self.bucket: Optional[int] = None
        self.t_dequeue: Optional[float] = None

    def finalize(self) -> bool:
        """True exactly once, for the party that gets to record this
        request's metrics outcome (non-blocking test-and-set)."""
        return self._final.acquire(blocking=False)

    def resolve(self, result: np.ndarray) -> None:
        self.result = result
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.done.wait(timeout):
            # The waiter is gone (HTTP 504 already sent): mark the
            # request so an executor that later pulls its group
            # skips the dispatch instead of computing an answer nobody
            # reads. Benign race: a concurrent resolve just wastes the
            # one result.
            self.abandoned = True
            raise TimeoutError("predict did not complete in time")
        if self.error is not None:
            raise self.error
        assert self.result is not None
        return self.result


class MicroBatcher:
    """Bucket collectors -> batch queue -> per-replica executors."""

    def __init__(self, engine: InferenceEngine, cfg: BatcherConfig,
                 telemetry=None, metrics=None, watchdog=None,
                 supervisor=None, costing=None):
        largest = max(engine.cfg.batch_sizes)
        if cfg.max_batch > largest:
            raise ValueError(
                f"max_batch={cfg.max_batch} exceeds the largest compiled "
                f"batch size {largest}: the engine has no AOT program for a "
                f"bigger group and _dispatch never splits, so every "
                f"oversized group would fail wholesale")
        self.engine = engine
        self.cfg = cfg
        self.telemetry = telemetry
        self.metrics = metrics
        # Retrace watchdog (obs/retrace.py), sealed by build_service
        # after AOT startup: the executors check it per dispatch — in
        # strict mode a post-seal compile raises inside the dispatch try
        # and fails the batch loudly (HTTP 500) instead of silently
        # paying a compile stall per request.
        self.watchdog = watchdog
        # Replica supervisor (serve/supervisor.py), wired by
        # build_service: dispatch outcomes feed its state machine,
        # quarantined replicas leave the work-stealing rotation, a
        # failed batch gets one retry on a different replica, and
        # admission capacity shrinks with the healthy count. None =
        # pre-supervision semantics, bit-for-bit (every hook below is a
        # None check).
        self.supervisor = supervisor
        # Cost-calibration plane (serve/costing.py), wired by
        # build_service when a cost surface is armed: every successful
        # dispatch is priced in predicted device-seconds and measured
        # against the price. None = disarmed, and the dispatch path
        # carries exactly one attribute check (the faults.py
        # zero-residue discipline, test-gated).
        self.costing = costing
        # The executor pool: the engine's replicas, or the engine itself
        # as a single executor (test doubles without a pool).
        self.replicas = list(getattr(engine, "replicas", ()) or ()) \
            or [engine]
        self._queues: Dict[int, "queue.Queue[_Request]"] = {
            b: queue.Queue(maxsize=cfg.queue_depth)
            for b in engine.cfg.buckets}
        # Formed groups awaiting an executor. Capacity = pool size:
        # batches beyond the pool's concurrency stay as requests in the
        # bucket queues (where queue_depth bounds intake); a collector
        # holding a formed group blocks on put, not the submitters.
        self._batchq: "queue.Queue[Tuple[int, List[_Request]]]" = \
            queue.Queue(maxsize=len(self.replicas))
        self._stopping = threading.Event()
        # Serializes the submit-side {stopping check -> enqueue} against
        # shutdown setting the flag: without it a submit could pass the
        # check, lose the CPU while shutdown joins the workers AND runs
        # its sweep, then enqueue into a queue nobody will ever read —
        # stranding an accepted request (504/hang instead of 503).
        # ordered_lock: under PVRAFT_CHECKS=1 the lock-order sanitizer
        # records every acquisition (threadcheck's dynamic half); plain
        # threading.Lock otherwise.
        self._intake_lock = ordered_lock("MicroBatcher._intake_lock")
        self._drain = True
        # Pool occupancy + per-replica accounting, all under _count_lock
        # (the `# guarded-by:` annotations are machine-checked by
        # threadcheck GC001 — an access outside the lock fails lint.sh):
        # _busy = executors currently inside predict (the eager-dispatch
        # idleness signal); per-replica in-flight requests and
        # served-batch counters feed /healthz and Prometheus.
        self._count_lock = ordered_lock("MicroBatcher._count_lock")
        self._served = 0    # guarded-by: _count_lock
        self._rejected = 0  # guarded-by: _count_lock
        self._drained = 0   # guarded-by: _count_lock
        self._busy = 0      # guarded-by: _count_lock
        self._replica_inflight = [0] * len(self.replicas)  # guarded-by: _count_lock
        self._replica_batches = [0] * len(self.replicas)   # guarded-by: _count_lock
        self._collectors_live = len(engine.cfg.buckets)    # guarded-by: _count_lock
        self._executors_live = len(self.replicas)          # guarded-by: _count_lock
        self._retries = 0                                  # guarded-by: _count_lock
        self._collectors = [
            threading.Thread(target=self._collector, args=(b,),
                             name=f"pvraft-serve-b{b}", daemon=True)
            for b in engine.cfg.buckets
        ]
        self._executors = [
            threading.Thread(target=self._executor, args=(i,),
                             name=f"pvraft-serve-r{i}", daemon=True)
            for i in range(len(self.replicas))
        ]
        for t in (*self._collectors, *self._executors):
            t.start()

    # ------------------------------------------------------------- intake --

    def submit(self, pc1: np.ndarray, pc2: np.ndarray,
               trace=None) -> _Request:
        """Validate and enqueue one request; returns a handle whose
        ``wait()`` yields the un-padded (n1, 3) flow. Raises
        :class:`RequestError` (contract), :class:`QueueFullError`
        (backpressure) or :class:`ShutdownError` (draining). ``trace``
        is an optional ``obs.trace.RequestTrace``: the validate stage is
        marked here, the queue/dispatch stages by the workers."""
        t_validate = time.monotonic()
        try:
            bucket = self.engine.validate_request(pc1, pc2)
        except RequestError as e:
            if trace is not None:
                trace.mark("validate", t_validate, time.monotonic(),
                           attrs={"rejected": e.reason})
            self._reject(e.reason)
            raise
        if trace is not None:
            trace.mark("validate", t_validate, time.monotonic())
        req = _Request(np.asarray(pc1, np.float32),
                       np.asarray(pc2, np.float32))
        req.trace = trace
        req.bucket = bucket
        n_points = max(pc1.shape[0], pc2.shape[0])
        req.t_enqueue = time.monotonic()
        # Check-and-enqueue is atomic w.r.t. shutdown (see _intake_lock):
        # an enqueue here happens-before the stop flag is set, so the
        # workers (or the drain sweep) are guaranteed to see it. The lock
        # covers ONLY that pair — reject accounting does telemetry file
        # I/O and must not serialize intake across buckets under the
        # exact overload that makes rejects frequent.
        reject = None
        effective_depth = self.cfg.queue_depth
        with self._intake_lock:
            if self._stopping.is_set():
                reject = "shutdown"
            else:
                # Graceful degradation: admission capacity scales with
                # the replicas still in the work-stealing rotation —
                # with half the pool quarantined, accepting a full
                # queue's worth of work only converts backlog into
                # queue-timeout 504s. serving_count() is a locked int
                # read (no I/O; the supervisor never calls back into
                # intake, so the edge is one-way).
                serving = (self.supervisor.serving_count()
                           if self.supervisor is not None
                           else len(self.replicas))
                if serving == 0:
                    reject = "unavailable"
                else:
                    effective_depth = max(
                        1, (self.cfg.queue_depth * serving
                            + len(self.replicas) - 1)
                        // len(self.replicas))
                    if self._queues[bucket].qsize() >= effective_depth:
                        # Submitters are serialized by _intake_lock and
                        # workers only remove, so a below-capacity queue
                        # here cannot fill before the put below — this
                        # check IS the admission decision (at full
                        # health it reduces to the old full() check).
                        reject = "queue_full"
                    else:
                        # Count the submit BEFORE the enqueue becomes
                        # visible to a worker: otherwise a dispatched
                        # response could reach record_batch first and a
                        # concurrent /metrics snapshot would see
                        # responses_total > requests_total. Counter
                        # increments only — no telemetry I/O under the
                        # lock.
                        if self.metrics is not None:
                            self.metrics.record_submit(
                                bucket, n_points=n_points)
                        self._queues[bucket].put_nowait(req)
        if reject == "shutdown":
            self._reject("shutdown")
            raise ShutdownError("server is shutting down")
        if reject == "unavailable":
            self._reject("unavailable", bucket=bucket)
            raise PoolUnavailableError(
                "every replica is quarantined; the pool sheds load "
                "until a probe revives one") from None
        if reject == "queue_full":
            self._reject("queue_full", bucket=bucket,
                         queue_depth=effective_depth)
            raise QueueFullError(
                f"bucket {bucket} queue is full "
                f"({effective_depth} of {self.cfg.queue_depth} slots "
                f"admissible at current pool health)") from None
        return req

    def record_reject(self, reason: str) -> None:
        """Count a reject that never reached ``submit`` (e.g. the HTTP
        layer's body decode / body-size failures) so ``/metrics`` and
        the ``serve_reject`` event stream agree with what clients saw."""
        self._reject(reason)

    def record_failure(self, reason: str) -> None:
        """Count an ACCEPTED request that never produced a response
        (504 predict timeout, 500 engine failure): already counted at
        submit, so only the outcome is recorded — otherwise /metrics
        totals never reconcile under sustained slowness and the
        load-gen artifact's client counts contradict server_metrics.
        Callers that hold the request handle must go through
        :meth:`record_failure_for` so a racing dispatch cannot also
        count it as a response."""
        with self._count_lock:
            self._rejected += 1
        if self.metrics is not None:
            self.metrics.record_failure(reason)
        if self.telemetry is not None:
            self.telemetry.emit_reject(reason)

    def record_failure_for(self, req: _Request, reason: str) -> None:
        """Record an accepted request's failure exactly once: the
        dispatch loop may be racing to count the same request as a
        response — whoever wins the request's finalize() token does the
        ledger write, the loser records nothing."""
        if req.finalize():
            self.record_failure(reason)

    def _reject(self, reason: str, bucket: Optional[int] = None,
                queue_depth: Optional[int] = None) -> None:
        with self._count_lock:
            self._rejected += 1
        if self.metrics is not None:
            self.metrics.record_reject(reason)
        if self.telemetry is not None:
            self.telemetry.emit_reject(reason, bucket=bucket,
                                       queue_depth=queue_depth)

    def queue_depths(self) -> Dict[int, int]:
        return {b: q.qsize() for b, q in self._queues.items()}

    def batch_queue_depth(self) -> int:
        """Formed groups awaiting an executor (Prometheus gauge)."""
        return self._batchq.qsize()

    def replica_stats(self) -> List[Dict[str, Any]]:
        """Per-replica visibility for /healthz and Prometheus: device
        id, requests currently executing, served-batch counter — plus
        the supervisor's health state when one is wired. The supervisor
        rows are fetched BEFORE _count_lock (each side locks only its
        own state; never nested)."""
        health = (self.supervisor.states()
                  if self.supervisor is not None else None)
        with self._count_lock:
            rows = [{"replica": i,
                     "device_id": int(getattr(r, "device_id", i)),
                     "in_flight": self._replica_inflight[i],
                     "batches_total": self._replica_batches[i]}
                    for i, r in enumerate(self.replicas)]
        if health is not None:
            for row, h in zip(rows, health):
                row["state"] = h["state"]
        return rows

    # -------------------------------------------------------- collectors --

    def _capacity_idle(self) -> bool:
        """True when a formed group would start executing immediately:
        some in-rotation executor is free AND no earlier group is
        already waiting."""
        with self._count_lock:
            busy = self._busy
        serving = (self.supervisor.serving_count()
                   if self.supervisor is not None else len(self.replicas))
        return busy < serving and self._batchq.empty()

    def _collect(self, q: "queue.Queue[_Request]") -> List[_Request]:
        """One group: block briefly for a first request (so the stop flag
        is polled), then gather up to max_batch. The straggler window is
        honored only while the pool is saturated (eager_when_idle)."""
        try:
            first = q.get(timeout=0.05)
        except queue.Empty:
            return []
        first.t_dequeue = time.monotonic()
        group = [first]
        deadline = first.t_dequeue + self.cfg.max_wait_ms / 1000.0
        while len(group) < self.cfg.max_batch:
            try:
                req = q.get_nowait()
            except queue.Empty:
                if self.cfg.eager_when_idle and self._capacity_idle():
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                # Eager mode polls in short slices so the idleness
                # check above notices an executor freeing up mid-window;
                # baseline mode (eager off) has no such check to re-run,
                # so it sleeps the whole window in one get — no 2 ms
                # wakeup churn on the leg meant to reproduce PR-7.
                wait_s = (min(remaining, 0.002)
                          if self.cfg.eager_when_idle else remaining)
                try:
                    req = q.get(timeout=wait_s)
                except queue.Empty:
                    continue
            req.t_dequeue = time.monotonic()
            group.append(req)
        return group

    def _collector(self, bucket: int) -> None:
        q = self._queues[bucket]
        try:
            while True:
                group = self._collect(q)
                if not group:
                    if self._stopping.is_set():
                        if not self._drain:
                            break
                        if q.empty():
                            break
                    continue
                if self._stopping.is_set() and not self._drain:
                    self._fail_group(group)
                    continue
                # Fault point: a stalled bucket queue (armed FaultPlans
                # only — disarmed this is one attribute check).
                faults.fire("queue_stall", bucket=bucket,
                            on_fire=self._on_fault)
                if not self._enqueue_batch(bucket, group):
                    continue
        finally:
            # Executors poll this to know when the batch queue can no
            # longer grow (their drain-exit condition).
            with self._count_lock:
                self._collectors_live -= 1

    def _enqueue_batch(self, bucket: int,
                       group: List[_Request]) -> bool:
        """Hand a formed group to the pool; blocks while the batch
        queue is at capacity (executors are the consumers, so this
        resolves as replicas free up — it is NOT client-visible
        blocking: submit already returned)."""
        while True:
            try:
                self._batchq.put((bucket, group), timeout=0.05)
                return True
            except queue.Full:
                if self._stopping.is_set() and not self._drain:
                    self._fail_group(group)
                    return False
                if self._stopping.is_set():
                    # Draining, but every executor already exited (all
                    # replicas quarantined park-and-exit at stop): no
                    # consumer will ever free the batch queue — serve
                    # the group inline so the drain contract (every
                    # accepted request resolves) holds.
                    with self._count_lock:
                        executors_done = self._executors_live == 0
                    if executors_done:
                        self._dispatch(0, self.replicas[0], bucket, group)
                        return False

    def _fail_group(self, group: List[_Request]) -> None:
        for req in group:
            # finalize(): a request whose outcome is already recorded
            # (waiter 504'd, or a dispatch resolved it) is skipped —
            # failing it again would double-count the ledger and could
            # clobber a result a waiter is reading right now.
            if req.finalize():
                self.record_failure("shutdown")
                req.fail(ShutdownError("server stopped without drain"))

    # --------------------------------------------------------- executors --

    def _executor(self, index: int) -> None:
        replica = self.replicas[index]
        try:
            while True:
                if (self.supervisor is not None
                        and not self.supervisor.in_rotation(index)):
                    # Quarantined/probing: parked out of the
                    # work-stealing rotation (only the supervisor's
                    # probe touches this replica). At shutdown a parked
                    # executor exits immediately — the drain sweep (or
                    # a live sibling) owns any leftover batches.
                    if self._stopping.is_set():
                        break
                    time.sleep(0.02)
                    continue
                try:
                    bucket, group = self._batchq.get(timeout=0.05)
                except queue.Empty:
                    if self._stopping.is_set():
                        with self._count_lock:
                            collectors_done = self._collectors_live == 0
                        if collectors_done and self._batchq.empty():
                            break
                    continue
                if self._stopping.is_set() and not self._drain:
                    self._fail_group(group)
                    continue
                self._dispatch(index, replica, bucket, group)
        finally:
            # _enqueue_batch's drain fallback polls this: when every
            # executor is gone, collectors dispatch inline instead of
            # blocking on a batch queue nobody reads.
            with self._count_lock:
                self._executors_live -= 1

    def _on_fault(self, record: Dict[str, Any]) -> None:
        """``fault_injected`` telemetry sink for fault points fired on
        the batcher's paths (the supervisor's probe has its own)."""
        if self.telemetry is not None:
            self.telemetry.emit_fault(**record)

    def _dispatch(self, index: int, replica, bucket: int,
                  group: List[_Request], retried: bool = False) -> None:
        # Drop requests whose waiter already timed out (504 sent): the
        # engine time would buy an answer nobody reads, and counting
        # them as served would report success for client-visible
        # failures.
        group = [r for r in group if not r.abandoned]
        if not group:
            return
        t0 = time.monotonic()
        # Sealed-mode window: only compiles landing DURING this dispatch
        # trip (a co-resident engine compiling its startup table between
        # requests — the serve_ab two-leg pattern — is not ours to flag).
        compile_window = (self.watchdog.global_compiles()
                          if self.watchdog is not None else 0)
        with self._count_lock:
            self._busy += 1
            self._replica_inflight[index] += len(group)
        dispatch_token = None
        if self.supervisor is not None:
            # The wedge watch: a dispatch still marked started after
            # wedge_timeout_s quarantines this replica. Tokened: a
            # sibling's retry can run on this replica concurrently with
            # its own executor, and each in-flight dispatch must stay
            # individually visible.
            dispatch_token = self.supervisor.note_dispatch_start(index, t0)
        failure: Optional[BaseException] = None
        try:
            # Replica fault points (latency sleep / wedge block / error
            # raise) — the same traversal the supervisor's probe makes,
            # so an armed fault fails both. Disarmed: one attr check.
            faults.replica_faults(index, bucket=bucket,
                                  on_fire=self._on_fault)
            flows = replica.predict_batch(
                [(r.pc1, r.pc2) for r in group], bucket)
            if self.watchdog is not None:
                if faults.fire("compile_trip", replica=index,
                               bucket=bucket, on_fire=self._on_fault):
                    # The injected "hidden backend compile" flows
                    # through the real sealed-mode observability path
                    # (counter -> check -> recompile event / strict 500).
                    self.watchdog.inject_compile()
                self._watchdog_check(bucket, len(group), compile_window)
        except BaseException as e:  # noqa: BLE001 — fail/retry the group, not the executor
            failure = e
        finally:
            if self.supervisor is not None:
                self.supervisor.note_dispatch_end(index, dispatch_token)
            with self._count_lock:
                self._busy -= 1
                self._replica_inflight[index] -= len(group)
        if failure is not None:
            self._dispatch_failed(index, bucket, group, failure, retried)
            return
        now = time.monotonic()
        if self.supervisor is not None:
            self.supervisor.record_success(index, bucket, now - t0)
        # Re-check abandonment AFTER the engine call: a waiter can 504
        # while predict runs (seconds), and its request must not be
        # counted as served or have its (by-definition over-deadline)
        # latency skew the histogram. finalize() closes the remaining
        # race: a waiter timing out between this line and the
        # accounting below loses the test-and-set and records nothing,
        # so the request is counted exactly once (as the response it
        # actually produced — the client's 504 is the one benign
        # mismatch left, noted in _Request.wait).
        live = [(r, f) for r, f in zip(group, flows)
                if not r.abandoned and r.finalize()]
        bs = self.engine.batch_size_for(len(group))
        device_id = int(getattr(replica, "device_id", index))
        # Price + measure the dispatch against the cost surface, keyed
        # on the DISPATCHED batch slot count (the AOT program that ran,
        # mirroring the fill accounting below).
        if self.costing is not None:
            self.costing.observe_dispatch(bucket, bs, index, t0, now)
        for r, _ in live:
            # Re-read trace/abandoned per request: a waiter that 504'd
            # since `live` was computed is assembling its (partial) span
            # tree RIGHT NOW — skip marking it rather than race the
            # iteration. (The residual window — abandonment landing
            # mid-loop — only under-fills an error trace's tree, which
            # is the documented shape of error-outcome traces.)
            tr = r.trace
            if tr is None or r.abandoned:
                continue
            # queue_wait: enqueue -> dequeue; batch_form: dequeue ->
            # dispatch (straggler wait + grouping + batch-queue wait);
            # device_execute: the AOT program incl. host fetch. For
            # served requests the marks land before resolve() below, so
            # the handler thread (which assembles spans after wait()
            # returns) is ordered-after them.
            t_dq = r.t_dequeue if r.t_dequeue is not None else t0
            tr.mark("queue_wait", r.t_enqueue, t_dq)
            tr.mark("batch_form", t_dq, t0)
            tr.mark("device_execute", t0, now,
                    attrs={"bucket": bucket, "batch": bs,
                           "n": len(group), "replica": index,
                           "device_id": device_id})
        latencies = [(now - r.t_enqueue) * 1000.0 for r, _ in live]
        # Account BEFORE resolving: resolve() unblocks the HTTP replies,
        # and a client that immediately polls /metrics must see counts
        # covering every response it has already received.
        with self._count_lock:
            self._served += len(live)
            self._replica_batches[index] += 1
            if self._stopping.is_set():
                self._drained += len(live)
        # Fill reflects the dispatch itself (how full the AOT program's
        # slots were), so it stays keyed on the dispatched group size.
        fill = len(group) / bs
        if self.metrics is not None:
            self.metrics.record_batch(len(live), fill, latencies)
        if self.telemetry is not None:
            self.telemetry.emit_batch(
                bucket=bucket, batch=bs, n=len(live),
                fill=round(fill, 4),
                latency_ms=round((now - t0) * 1000.0, 3),
                queue_depth=self._queues[bucket].qsize(),
                replica=index, device_id=device_id)
        for req, flow in live:
            req.resolve(flow)

    def _dispatch_failed(self, index: int, bucket: int,
                         group: List[_Request], error: BaseException,
                         retried: bool) -> None:
        """A dispatch raised. Feed the supervisor's failure ledger, then
        retry the batch EXACTLY once on a *different* in-rotation
        replica — still within each request's deadline, because the
        retry dispatch re-drops abandoned (504'd) waiters before paying
        any engine time. Already-retried groups (or a pool with no
        healthy sibling) fail outright: the HTTP layer records those
        accepted-then-failed outcomes, so the accounting identity holds
        and no request is ever resolved twice (the retry path reuses the
        one finalize()-token accounting the success path has).

        A strict-mode :class:`~pvraft_tpu.obs.retrace.RetraceError` is
        NOT a replica failure: the predict itself succeeded and the
        process-wide compile it reports would fail the retry identically
        — it fails the group without touching the health ledger."""
        from pvraft_tpu.obs.retrace import RetraceError

        if self.supervisor is not None \
                and not isinstance(error, RetraceError):
            self.supervisor.record_failure(
                index, reason=type(error).__name__)
            if not retried:
                target = self.supervisor.retry_target(exclude=index)
                if target is not None:
                    with self._count_lock:
                        self._retries += 1
                    if self.metrics is not None:
                        self.metrics.record_retry()
                    self._dispatch(target, self.replicas[target], bucket,
                                   group, retried=True)
                    return
        for req in group:
            req.fail(error)

    def _watchdog_check(self, bucket: int, n: int,
                        compile_window: int) -> None:
        """Per-dispatch retrace check. The Prometheus counter bumps for
        every trip whether or not strict mode then raises (a strict
        failure must still be visible on /metrics)."""
        from pvraft_tpu.obs.retrace import RetraceError

        try:
            trips = self.watchdog.check(
                signature=f"bucket={bucket} n={n}",
                program=f"serve_dispatch_b{bucket}",
                window_start=compile_window)
        except RetraceError:
            if self.metrics is not None:
                self.metrics.record_recompile()
            raise
        if self.metrics is not None:
            for _ in trips:
                self.metrics.record_recompile()

    # ----------------------------------------------------------- shutdown --

    def shutdown(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop intake; ``drain=True`` finishes queued work first. Safe
        to call twice. Emits the ``serve_shutdown`` summary event."""
        with self._intake_lock:
            already = self._stopping.is_set()
            self._drain = drain
            self._stopping.set()
        for t in self._collectors:
            t.join(timeout)
        for t in self._executors:
            t.join(timeout)
        if drain:
            # Defense-in-depth: _intake_lock guarantees every accepted
            # enqueue happens-before the stop flag, and the thread exit
            # conditions (collector: queue empty; executor: collectors
            # done AND batch queue empty) mean nothing should be left.
            # Serve any stragglers inline on replica 0 anyway so a
            # drained shutdown can never strand an accepted request.
            while True:
                try:
                    bucket, group = self._batchq.get_nowait()
                except queue.Empty:
                    break
                self._dispatch(0, self.replicas[0], bucket, group)
            for bucket, q in self._queues.items():
                while True:
                    group: List[_Request] = []
                    while len(group) < self.cfg.max_batch:
                        try:
                            group.append(q.get_nowait())
                        except queue.Empty:
                            break
                    if not group:
                        break
                    self._dispatch(0, self.replicas[0], bucket, group)
        if not drain:
            # Fail anything the threads didn't pick up.
            while True:
                try:
                    _, group = self._batchq.get_nowait()
                except queue.Empty:
                    break
                self._fail_group(group)
            for q in self._queues.values():
                while True:
                    try:
                        req = q.get_nowait()
                    except queue.Empty:
                        break
                    self._fail_group([req])
        if self.telemetry is not None and not already:
            with self._count_lock:
                self.telemetry.emit_shutdown(
                    served=self._served, rejected=self._rejected,
                    drained=self._drained)

    @property
    def counts(self) -> Dict[str, int]:
        with self._count_lock:
            return {"served": self._served, "rejected": self._rejected,
                    "drained": self._drained, "retries": self._retries}
