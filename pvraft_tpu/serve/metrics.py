"""Thread-safe serve metrics: the ``/metrics`` endpoint's backing store.

Counters are updated from HTTP handler threads and the batcher workers
concurrently; one lock keeps the snapshot consistent. The latency
histogram uses fixed log-spaced bucket edges (ms) so the snapshot is
bounded-size no matter how long the server runs; quantiles reported from
it are upper-bound estimates (the edge of the bucket the quantile falls
in) — honest for SLO checks, not sub-bucket precise.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

# Fixed histogram edges (ms): latency falls in the first bucket whose
# edge is >= the sample; the final bucket is unbounded.
LATENCY_EDGES_MS = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
    1000.0, 2000.0, 5000.0, 10000.0, 30000.0,
)


class LatencyHistogram:
    """Fixed-edge histogram with count/sum/max (no lock: the owner
    serializes access)."""

    def __init__(self):
        self.counts = [0] * (len(LATENCY_EDGES_MS) + 1)
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def observe(self, ms: float) -> None:
        i = 0
        while i < len(LATENCY_EDGES_MS) and ms > LATENCY_EDGES_MS[i]:
            i += 1
        self.counts[i] += 1
        self.count += 1
        self.sum_ms += ms
        self.max_ms = max(self.max_ms, ms)

    def quantile(self, q: float) -> Optional[float]:
        """Upper-bound estimate: the edge of the bucket holding the
        q-quantile (None when empty; max_ms for the unbounded bucket)."""
        if not self.count:
            return None
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                if i < len(LATENCY_EDGES_MS):
                    return LATENCY_EDGES_MS[i]
                return self.max_ms
        return self.max_ms

    def snapshot(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "mean_ms": (self.sum_ms / self.count) if self.count else None,
            "max_ms": self.max_ms if self.count else None,
            "p50_ms": self.quantile(0.50),
            "p95_ms": self.quantile(0.95),
            "p99_ms": self.quantile(0.99),
            "bucket_edges_ms": list(LATENCY_EDGES_MS),
            "bucket_counts": list(self.counts),
        }


class ServeMetrics:
    """All serve counters behind one lock."""

    def __init__(self, buckets):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.responses_total = 0
        self.rejected: Dict[str, int] = {}
        self.batches_total = 0
        self.batch_fill_sum = 0.0
        self.per_bucket_requests: Dict[int, int] = {int(b): 0
                                                    for b in buckets}
        self.latency = LatencyHistogram()

    def record_submit(self, bucket: int) -> None:
        with self._lock:
            self.requests_total += 1
            self.per_bucket_requests[int(bucket)] = (
                self.per_bucket_requests.get(int(bucket), 0) + 1)

    def record_reject(self, reason: str) -> None:
        with self._lock:
            self.requests_total += 1
            self.rejected[reason] = self.rejected.get(reason, 0) + 1

    def record_failure(self, reason: str) -> None:
        """An ACCEPTED request (already in ``requests_total`` via
        ``record_submit``) that never produced a response — 504 predict
        timeout, 500 engine failure, shutdown-without-drain. Keeps the
        reconciliation identity ``requests_total == responses_total +
        sum(rejected) + in_flight`` without double-counting the request."""
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1

    def record_batch(self, n: int, fill: float,
                     latencies_ms: List[float]) -> None:
        with self._lock:
            self.batches_total += 1
            self.batch_fill_sum += fill
            self.responses_total += n
            for ms in latencies_ms:
                self.latency.observe(ms)

    def snapshot(self, queue_depths: Optional[Dict[int, int]] = None
                 ) -> Dict[str, Any]:
        with self._lock:
            snap: Dict[str, Any] = {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "rejected": dict(self.rejected),
                "batches_total": self.batches_total,
                "batch_fill_mean": (
                    self.batch_fill_sum / self.batches_total
                    if self.batches_total else None),
                "per_bucket_requests": {
                    str(k): v for k, v in self.per_bucket_requests.items()},
                "latency": self.latency.snapshot(),
            }
        if queue_depths is not None:
            snap["queue_depth"] = {str(k): v for k, v in queue_depths.items()}
        return snap
