"""Thread-safe serve metrics: the ``/metrics`` endpoint's backing store.

Counters are updated from HTTP handler threads and the batcher workers
concurrently; one lock keeps the snapshot consistent. The latency
histogram uses fixed log-spaced bucket edges (ms) so the snapshot is
bounded-size no matter how long the server runs; quantiles reported from
it are upper-bound estimates (the edge of the bucket the quantile falls
in) — honest for SLO checks, not sub-bucket precise.

Two exposition formats off the same store:

* JSON (default ``/metrics``) — the pre-existing snapshot, shape-frozen
  (``tests/test_trace.py`` pins the serialized bytes): dashboards built
  against it keep parsing.
* Prometheus text 0.0.4 (``/metrics?format=prometheus``,
  :func:`render_prometheus`) — everything in the JSON snapshot PLUS the
  per-(bucket, stage) latency histograms fed by the trace plane and the
  request-size histogram (the seed data for adaptive bucket geometry,
  ROADMAP item 3). New series appear only here so the JSON contract
  never grows by accident.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock

# Fixed histogram edges (ms): latency falls in the first bucket whose
# edge is >= the sample; the final bucket is unbounded.
LATENCY_EDGES_MS = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
    1000.0, 2000.0, 5000.0, 10000.0, 30000.0,
)

# Request-size (points per cloud) edges: power-of-two ladder spanning the
# certified bucket range — the live histogram adaptive bucket geometry
# will be learned from.
POINT_EDGES = (
    32.0, 64.0, 128.0, 256.0, 512.0, 1024.0, 2048.0, 4096.0, 8192.0,
    16384.0, 32768.0,
)

# Rolling window for the per-replica utilization gauge (the fraction of
# the last window each replica spent inside predict): long enough to
# smooth batch granularity, short enough that a drained replica reads 0
# within a scrape or two.
UTILIZATION_WINDOW_S = 60.0

# Hard backstop on the per-replica dispatch-interval history backing
# the rolling utilization. Intervals are pruned by AGE on every append
# (only the trailing window is ever kept), so this cap exists purely to
# bound memory against a pathological dispatch rate — at 65536 entries
# the window stays fully covered down to ~0.9 ms/dispatch; the counters
# (busy seconds) are exact regardless.
_BUSY_INTERVALS_MAX = 65536


class LatencyHistogram:
    """Fixed-edge histogram with count/sum/max (no lock: the owner
    serializes access). ``edges`` defaults to the latency ladder; the
    request-size histogram reuses the class with point-count edges."""

    def __init__(self, edges: Sequence[float] = LATENCY_EDGES_MS):
        self.edges = tuple(edges)
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def observe(self, ms: float) -> None:
        i = 0
        while i < len(self.edges) and ms > self.edges[i]:
            i += 1
        self.counts[i] += 1
        self.count += 1
        self.sum_ms += ms
        self.max_ms = max(self.max_ms, ms)

    def quantile(self, q: float) -> Optional[float]:
        """Upper-bound estimate: the edge of the bucket holding the
        q-quantile (None when empty; max_ms for the unbounded bucket)."""
        if not self.count:
            return None
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                if i < len(self.edges):
                    return self.edges[i]
                return self.max_ms
        return self.max_ms

    def snapshot(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "mean_ms": (self.sum_ms / self.count) if self.count else None,
            "max_ms": self.max_ms if self.count else None,
            "p50_ms": self.quantile(0.50),
            "p95_ms": self.quantile(0.95),
            "p99_ms": self.quantile(0.99),
            "bucket_edges_ms": list(self.edges),
            "bucket_counts": list(self.counts),
        }


class ServeMetrics:
    """All serve counters behind one lock."""

    def __init__(self, buckets):
        # Every field below is guarded-by _lock (machine-checked:
        # threadcheck GC001 flags any access outside it). External
        # readers go through snapshot()/prometheus(), never the fields.
        self._lock = ordered_lock("ServeMetrics._lock")
        self.requests_total = 0   # guarded-by: _lock
        self.responses_total = 0  # guarded-by: _lock
        # Accepted requests whose outcome is not yet recorded. Updated
        # under the same lock as every counter, so the reconciliation
        # identity `requests_total == responses_total + sum(rejected) +
        # in_flight` holds at EVERY snapshot, not just at quiescence.
        # Prometheus/healthz-only (the JSON snapshot shape is frozen).
        self.in_flight = 0  # guarded-by: _lock
        self.rejected: Dict[str, int] = {}  # guarded-by: _lock
        self.batches_total = 0    # guarded-by: _lock
        self.batch_fill_sum = 0.0  # guarded-by: _lock
        self.per_bucket_requests: Dict[int, int] = {int(b): 0  # guarded-by: _lock
                                                    for b in buckets}
        self.latency = LatencyHistogram()  # guarded-by: _lock
        # Prometheus-only series (the JSON snapshot's shape is frozen):
        # live request sizes (points per cloud) + per-(bucket, stage)
        # latency fed from traced requests (obs/trace.py).
        self.request_points = LatencyHistogram(edges=POINT_EDGES)  # guarded-by: _lock
        self.stage_latency: Dict[Tuple[int, str], LatencyHistogram] = {}  # guarded-by: _lock
        # Latest device-memory sample rows (obs/device_memory.py) and
        # the recompile-trip counter (obs/retrace.py) — both
        # Prometheus-only, fed by the serve pool's monitor/watchdog.
        self.device_memory: List[Dict[str, Any]] = []  # guarded-by: _lock
        self.recompiles_total = 0  # guarded-by: _lock
        # Batches re-dispatched on a different replica after a dispatch
        # failure (serve/supervisor.py retry-once) — Prometheus-only.
        self.retries_total = 0  # guarded-by: _lock
        # Cost-calibration plane (ISSUE 14; Prometheus/healthz-only, the
        # frozen JSON snapshot never sees any of it). Armed explicitly
        # by build_service when a cost surface is wired — a disarmed
        # store renders the exposition byte-identically to pre-surface
        # builds (test-gated).
        self.cost_armed = False  # guarded-by: _lock
        self.predicted_device_seconds_total = 0.0  # guarded-by: _lock
        self.busy_seconds: Dict[int, float] = {}  # guarded-by: _lock
        # (bucket, batch, dtype) -> running calibration sums.
        self.cost_calibration: Dict[Tuple[int, int, str], Dict[str, Any]] = {}  # guarded-by: _lock
        # replica -> recent (t_start, t_end) dispatch intervals, backing
        # the rolling utilization gauge.
        self._busy_intervals: Dict[int, Any] = {}  # guarded-by: _lock

    def current_in_flight(self) -> int:
        """Locked read of the in-flight gauge for external surfaces
        (/healthz): the fields themselves are guarded-by _lock and must
        not be read bare from other modules."""
        with self._lock:
            return self.in_flight

    def record_submit(self, bucket: int,
                      n_points: Optional[int] = None) -> None:
        with self._lock:
            self.requests_total += 1
            self.in_flight += 1
            self.per_bucket_requests[int(bucket)] = (
                self.per_bucket_requests.get(int(bucket), 0) + 1)
            if n_points is not None:
                self.request_points.observe(float(n_points))

    def record_stages(self, bucket: int,
                      stage_ms: Dict[str, float]) -> None:
        """Per-stage latencies of one traced request (sampled — the
        histograms cover the traced subset, which loadgen makes 100%)."""
        with self._lock:
            for stage, ms in stage_ms.items():
                hist = self.stage_latency.get((int(bucket), stage))
                if hist is None:
                    hist = LatencyHistogram()
                    self.stage_latency[(int(bucket), stage)] = hist
                hist.observe(ms)

    def record_device_memory(self, rows: List[Dict[str, Any]]) -> None:
        """Latest per-device memory sample (gauge semantics: the newest
        sample wins; history lives on the event stream, not here)."""
        with self._lock:
            self.device_memory = [dict(r) for r in rows]

    def record_recompile(self) -> None:
        """One retrace-watchdog trip (obs/retrace.py)."""
        with self._lock:
            self.recompiles_total += 1

    def record_retry(self) -> None:
        """One failed batch re-dispatched on a different replica
        (serve/batcher.py retry-once-on-other-replica)."""
        with self._lock:
            self.retries_total += 1

    def arm_cost(self) -> None:
        """Turn the cost-calibration series on (build_service, when a
        cost surface is wired). Disarmed stores render the exposition
        byte-identically to pre-surface builds."""
        with self._lock:
            self.cost_armed = True

    def record_cost(self, bucket: int, batch: int, dtype: str,
                    replica: int, predicted_s: float, measured_s: float,
                    t_start: float, t_end: float, comparable: bool,
                    extrapolated: bool) -> None:
        """One priced + measured dispatch (serve/costing.py): predicted
        device-seconds vs the measured dispatch wall, per (bucket,
        batch, dtype) and per replica."""
        key = (int(bucket), int(batch), dtype)
        with self._lock:
            self.predicted_device_seconds_total += predicted_s
            self.busy_seconds[int(replica)] = (
                self.busy_seconds.get(int(replica), 0.0) + measured_s)
            slot = self.cost_calibration.get(key)
            if slot is None:
                slot = {"n": 0, "predicted_s": 0.0, "measured_s": 0.0,
                        "comparable": comparable, "extrapolated": 0}
                self.cost_calibration[key] = slot
            slot["n"] += 1
            slot["predicted_s"] += predicted_s
            slot["measured_s"] += measured_s
            # One record per key: an incomparable dispatch poisons the
            # whole key (mixed-platform sums are never enforceable).
            slot["comparable"] = slot["comparable"] and comparable
            slot["extrapolated"] += 1 if extrapolated else 0
            window = self._busy_intervals.get(int(replica))
            if window is None:
                window = collections.deque(maxlen=_BUSY_INTERVALS_MAX)
                self._busy_intervals[int(replica)] = window
            window.append((t_start, t_end))
            # Prune by age so a busy replica's history always spans the
            # full utilization window (a fixed-size deque alone would
            # silently shrink the numerator's coverage below the
            # window it is divided by — phantom headroom).
            cutoff = t_end - UTILIZATION_WINDOW_S
            while window and window[0][1] < cutoff:
                window.popleft()

    def cost_snapshot(self, now: Optional[float] = None
                      ) -> Optional[Dict[str, Any]]:
        """The /healthz calibration + utilization block (None while the
        cost plane is disarmed — the JSON /metrics snapshot never
        carries any of this; /healthz is the operator surface)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if not self.cost_armed:
                return None
            rows = []
            for (bucket, batch, dtype), slot in sorted(
                    self.cost_calibration.items()):
                rows.append({
                    "bucket": bucket, "batch": batch, "dtype": dtype,
                    "n": slot["n"],
                    "predicted_s": round(slot["predicted_s"], 6),
                    "measured_s": round(slot["measured_s"], 6),
                    "ratio": (round(slot["measured_s"]
                                    / slot["predicted_s"], 4)
                              if slot["predicted_s"] > 0 else None),
                    "comparable": slot["comparable"],
                    "extrapolated": slot["extrapolated"],
                })
            return {
                "predicted_device_seconds_total": round(
                    self.predicted_device_seconds_total, 6),
                "device_busy_seconds": {
                    str(r): round(s, 6)
                    for r, s in sorted(self.busy_seconds.items())},
                "utilization_window_s": UTILIZATION_WINDOW_S,
                "utilization": {
                    str(r): round(u, 4)
                    for r, u in sorted(replica_utilization(
                        self._busy_intervals, now).items())},
                "calibration": rows,
            }

    def record_reject(self, reason: str) -> None:
        with self._lock:
            self.requests_total += 1
            self.rejected[reason] = self.rejected.get(reason, 0) + 1

    def record_failure(self, reason: str) -> None:
        """An ACCEPTED request (already in ``requests_total`` via
        ``record_submit``) that never produced a response — 504 predict
        timeout, 500 engine failure, shutdown-without-drain. Keeps the
        reconciliation identity ``requests_total == responses_total +
        sum(rejected) + in_flight`` without double-counting the request."""
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1
            self.in_flight -= 1

    def record_batch(self, n: int, fill: float,
                     latencies_ms: List[float]) -> None:
        with self._lock:
            self.batches_total += 1
            self.batch_fill_sum += fill
            self.responses_total += n
            self.in_flight -= n
            for ms in latencies_ms:
                self.latency.observe(ms)

    def snapshot(self, queue_depths: Optional[Dict[int, int]] = None
                 ) -> Dict[str, Any]:
        with self._lock:
            snap: Dict[str, Any] = {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "rejected": dict(self.rejected),
                "batches_total": self.batches_total,
                "batch_fill_mean": (
                    self.batch_fill_sum / self.batches_total
                    if self.batches_total else None),
                "per_bucket_requests": {
                    str(k): v for k, v in self.per_bucket_requests.items()},
                "latency": self.latency.snapshot(),
            }
        if queue_depths is not None:
            snap["queue_depth"] = {str(k): v for k, v in queue_depths.items()}
        return snap

    def prometheus(self, queue_depths: Optional[Dict[int, int]] = None,
                   replica_stats: Optional[List[Dict[str, Any]]] = None,
                   batch_queue_depth: Optional[int] = None) -> str:
        """Prometheus text exposition 0.0.4 of every counter, gauge and
        histogram — serve with ``Content-Type: text/plain;
        version=0.0.4``. Rendered under the one metrics lock so the
        scrape is as consistent as the JSON snapshot. ``replica_stats``
        (``MicroBatcher.replica_stats()``) and ``batch_queue_depth`` are
        live pool gauges sampled by the caller, like ``queue_depths``."""
        with self._lock:
            return render_prometheus(self, queue_depths,
                                     replica_stats=replica_stats,
                                     batch_queue_depth=batch_queue_depth)


def replica_utilization(busy_intervals: Dict[int, Any], now: float,
                        window_s: float = UTILIZATION_WINDOW_S
                        ) -> Dict[int, float]:
    """replica -> busy fraction of the trailing window, from the
    per-replica dispatch-interval history. The caller holds the metrics
    lock (cost_snapshot / the exposition render — the same
    caller-holds-lock contract as :func:`render_prometheus`)."""
    out: Dict[int, float] = {}
    cutoff = now - window_s
    for replica, intervals in busy_intervals.items():
        busy = sum(max(0.0, min(t1, now) - max(t0, cutoff))
                   for t0, t1 in intervals)
        out[replica] = min(1.0, busy / window_s)
    return out


# ------------------------------------------------ Prometheus exposition --

PROM_CONTENT_TYPE = "text/plain; version=0.0.4"


def _prom_escape(value: Any) -> str:
    return str(value).replace("\\", r"\\").replace(
        "\n", r"\n").replace('"', r'\"')


def _prom_labels(labels: Optional[Dict[str, Any]]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_prom_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _prom_num(v: float) -> str:
    # Prometheus floats: integers render bare, floats repr-style.
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class _PromDoc:
    """Accumulates one exposition document; HELP/TYPE precede each
    metric family exactly once (the format's requirement)."""

    def __init__(self):
        self.lines: List[str] = []

    def family(self, name: str, mtype: str, help_text: str) -> None:
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {mtype}")

    def sample(self, name: str, value: float,
               labels: Optional[Dict[str, Any]] = None) -> None:
        self.lines.append(f"{name}{_prom_labels(labels)} {_prom_num(value)}")

    def histogram(self, name: str, hist: LatencyHistogram,
                  labels: Optional[Dict[str, Any]] = None) -> None:
        """Cumulative ``_bucket{le=}`` series + ``_sum``/``_count`` for
        one labeled histogram (family() is the caller's job — labeled
        histograms share one family)."""
        cum = 0
        for edge, count in zip(hist.edges, hist.counts):
            cum += count
            le = dict(labels or {})
            le["le"] = _prom_num(float(edge))
            self.sample(f"{name}_bucket", cum, le)
        le = dict(labels or {})
        le["le"] = "+Inf"
        self.sample(f"{name}_bucket", cum + hist.counts[-1], le)
        self.sample(f"{name}_sum", round(hist.sum_ms, 6), labels)
        self.sample(f"{name}_count", hist.count, labels)

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"


def render_prometheus(metrics: "ServeMetrics",
                      queue_depths: Optional[Dict[int, int]] = None,
                      replica_stats: Optional[List[Dict[str, Any]]] = None,
                      batch_queue_depth: Optional[int] = None) -> str:
    """The ``pvraft_serve_*`` exposition. Caller must hold the metrics
    lock (use :meth:`ServeMetrics.prometheus`)."""
    doc = _PromDoc()
    doc.family("pvraft_serve_requests_total", "counter",
               "Requests received (accepted + rejected).")
    doc.sample("pvraft_serve_requests_total", metrics.requests_total)
    doc.family("pvraft_serve_responses_total", "counter",
               "Successful predict responses.")
    doc.sample("pvraft_serve_responses_total", metrics.responses_total)
    doc.family("pvraft_serve_in_flight", "gauge",
               "Accepted requests whose outcome is not yet recorded "
               "(requests_total == responses_total + rejected + this).")
    doc.sample("pvraft_serve_in_flight", metrics.in_flight)
    doc.family("pvraft_serve_rejected_total", "counter",
               "Rejected or failed requests by serve_reject reason.")
    for reason, count in sorted(metrics.rejected.items()):
        doc.sample("pvraft_serve_rejected_total", count,
                   {"reason": reason})
    doc.family("pvraft_serve_batches_total", "counter",
               "Dispatched micro-batches.")
    doc.sample("pvraft_serve_batches_total", metrics.batches_total)
    doc.family("pvraft_serve_batch_fill_sum", "counter",
               "Sum of per-batch fill ratios (divide by "
               "pvraft_serve_batches_total for the mean).")
    doc.sample("pvraft_serve_batch_fill_sum",
               round(metrics.batch_fill_sum, 6))
    doc.family("pvraft_serve_bucket_requests_total", "counter",
               "Accepted requests per point-count bucket.")
    for bucket, count in sorted(metrics.per_bucket_requests.items()):
        doc.sample("pvraft_serve_bucket_requests_total", count,
                   {"bucket": bucket})
    if queue_depths is not None:
        doc.family("pvraft_serve_queue_depth", "gauge",
                   "Pending requests per bucket queue.")
        for bucket, depth in sorted(queue_depths.items()):
            doc.sample("pvraft_serve_queue_depth", depth,
                       {"bucket": bucket})
    if batch_queue_depth is not None:
        doc.family("pvraft_serve_batch_queue_depth", "gauge",
                   "Formed micro-batches awaiting a replica executor.")
        doc.sample("pvraft_serve_batch_queue_depth", batch_queue_depth)
    if replica_stats:
        doc.family("pvraft_serve_replica_in_flight", "gauge",
                   "Requests currently executing per replica.")
        for row in replica_stats:
            doc.sample("pvraft_serve_replica_in_flight",
                       row["in_flight"],
                       {"replica": row["replica"],
                        "device": row["device_id"]})
        doc.family("pvraft_serve_replica_batches_total", "counter",
                   "Micro-batches served per replica (work-stealing "
                   "balance check).")
        for row in replica_stats:
            doc.sample("pvraft_serve_replica_batches_total",
                       row["batches_total"],
                       {"replica": row["replica"],
                        "device": row["device_id"]})
        if any("state" in row for row in replica_stats):
            from pvraft_tpu.obs.events import REPLICA_STATES

            doc.family("pvraft_serve_replica_state", "gauge",
                       "Supervisor health state per replica: 1 for the "
                       "current state, 0 otherwise (serve/supervisor.py "
                       "state machine).")
            for row in replica_stats:
                if "state" not in row:
                    continue
                for state in REPLICA_STATES:
                    doc.sample(
                        "pvraft_serve_replica_state",
                        1 if row["state"] == state else 0,
                        {"replica": row["replica"], "state": state})
    if metrics.device_memory:
        doc.family("pvraft_device_hbm_bytes", "gauge",
                   "Device bytes in use, latest device.memory_stats() "
                   "sample (obs/device_memory.py).")
        for row in metrics.device_memory:
            doc.sample("pvraft_device_hbm_bytes", row["bytes_in_use"],
                       {"device": row["device_id"]})
        if any("peak_bytes_in_use" in r for r in metrics.device_memory):
            doc.family("pvraft_device_hbm_peak_bytes", "gauge",
                       "Peak device bytes in use since process start "
                       "(allocator watermark).")
            for row in metrics.device_memory:
                if "peak_bytes_in_use" in row:
                    doc.sample("pvraft_device_hbm_peak_bytes",
                               row["peak_bytes_in_use"],
                               {"device": row["device_id"]})
    doc.family("pvraft_serve_recompiles_total", "counter",
               "Retrace-watchdog trips: backend compiles observed after "
               "the AOT program set sealed (each also rides the event "
               "stream as a `recompile` record).")
    doc.sample("pvraft_serve_recompiles_total", metrics.recompiles_total)
    doc.family("pvraft_serve_retries_total", "counter",
               "Failed micro-batches re-dispatched once on a different "
               "replica (supervisor retry path).")
    doc.sample("pvraft_serve_retries_total", metrics.retries_total)
    if metrics.cost_armed:
        # The cost-calibration plane (serve/costing.py) — present only
        # when a cost surface is armed, so pre-surface expositions stay
        # byte-identical.
        doc.family("pvraft_serve_predicted_device_seconds_total", "counter",
                   "Predicted device-seconds of every priced dispatch "
                   "(CostSurface over artifacts/programs_costs.json).")
        doc.sample("pvraft_serve_predicted_device_seconds_total",
                   round(metrics.predicted_device_seconds_total, 6))
        doc.family("pvraft_serve_device_busy_seconds_total", "counter",
                   "Measured dispatch wall-seconds per replica (the "
                   "device_execute window the trace plane marks).")
        for replica, busy in sorted(metrics.busy_seconds.items()):
            doc.sample("pvraft_serve_device_busy_seconds_total",
                       round(busy, 6), {"replica": replica})
        doc.family("pvraft_serve_replica_utilization", "gauge",
                   "Busy fraction of the trailing "
                   f"{UTILIZATION_WINDOW_S:.0f}s window per replica.")
        now = time.monotonic()
        for replica, util in sorted(replica_utilization(
                metrics._busy_intervals, now).items()):
            doc.sample("pvraft_serve_replica_utilization",
                       round(util, 4), {"replica": replica})
        cal = [((b, bs, dt), slot,
                {"bucket": b, "batch": bs, "dtype": dt})
               for (b, bs, dt), slot in sorted(
                   metrics.cost_calibration.items())]
        doc.family("pvraft_serve_cost_predicted_seconds_total", "counter",
                   "Predicted device-seconds by (bucket, batch, dtype).")
        for _, slot, labels in cal:
            doc.sample("pvraft_serve_cost_predicted_seconds_total",
                       round(slot["predicted_s"], 6), labels)
        doc.family("pvraft_serve_cost_measured_seconds_total", "counter",
                   "Measured dispatch seconds by (bucket, batch, dtype).")
        for _, slot, labels in cal:
            doc.sample("pvraft_serve_cost_measured_seconds_total",
                       round(slot["measured_s"], 6), labels)
        doc.family("pvraft_serve_cost_dispatches_total", "counter",
                   "Priced dispatches by (bucket, batch, dtype).")
        for _, slot, labels in cal:
            doc.sample("pvraft_serve_cost_dispatches_total",
                       slot["n"], labels)
        doc.family("pvraft_serve_cost_calibration_ratio", "gauge",
                   "measured/predicted device-seconds by (bucket, "
                   "batch, dtype) — near 1.0 when the cost model is "
                   "honest ON TPU; off-TPU the ratio is recorded but "
                   "never enforceable (comparable=false on the event "
                   "stream).")
        for _, slot, labels in cal:
            if slot["predicted_s"] > 0:
                doc.sample("pvraft_serve_cost_calibration_ratio",
                           round(slot["measured_s"] / slot["predicted_s"],
                                 4), labels)
    doc.family("pvraft_serve_latency_ms", "histogram",
               "End-to-end request latency (enqueue to resolve), ms.")
    doc.histogram("pvraft_serve_latency_ms", metrics.latency)
    doc.family("pvraft_serve_request_points", "histogram",
               "Requested points per cloud (adaptive-bucket seed data).")
    doc.histogram("pvraft_serve_request_points", metrics.request_points)
    doc.family("pvraft_serve_stage_latency_ms", "histogram",
               "Per-stage latency of traced requests by (bucket, stage) "
               "— stages: ingress validate queue_wait batch_form "
               "device_execute serialize respond.")
    for (bucket, stage), hist in sorted(metrics.stage_latency.items()):
        doc.histogram("pvraft_serve_stage_latency_ms", hist,
                      {"bucket": bucket, "stage": stage})
    return doc.render()
