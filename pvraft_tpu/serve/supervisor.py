"""Per-replica health supervision: the serve plane's fault-tolerance
state machine.

Each replica walks ``healthy -> degraded -> quarantined -> probing ->
healthy`` (``obs.events.REPLICA_STATES``), driven by two signals the
batcher's dispatch loop reports:

* **hard failures** — a dispatch raised (engine error, injected fault):
  ``degraded_after`` consecutive failures mark the replica degraded,
  ``quarantine_after`` pull it from the work-stealing rotation;
* **latency outliers** — a dispatch slower than ``latency_outlier_factor``
  x the per-bucket EWMA (after warmup, above an absolute floor):
  ``latency_outlier_after`` consecutive outliers degrade the replica.
  Slow is not dead — outliers never quarantine on their own.

A third signal needs no report at all: a dispatch still in flight after
``wedge_timeout_s`` is a **wedged** executor (the device hung, the
thread cannot be killed) — the probe loop quarantines it so the
capacity loss is visible and admission shrinks accordingly.

Quarantined replicas are revived only by the background **probe**: a
synthetic ``min_points`` request through the replica's own AOT program
(the smallest bucket's compiled predict — no new compile, the sealed
retrace watchdog stays quiet). The probe traverses the same replica
fault points the dispatch path does (``faults.replica_faults``), so an
armed fault fails the probe too and revival happens only once the fault
actually clears.

Every transition is a ``replica_state`` event on the ``pvraft_events/v1``
stream and a ``pvraft_serve_replica_state{replica,state}`` Prometheus
series; ``/healthz`` reports the per-replica rows plus the pool summary
(healthy count drives admission capacity and the all-quarantined
``rejected[unavailable]`` degradation — ``serve/batcher.py``).

Thresholds are declared data (``programs/geometries.SUPERVISOR_DEFAULTS``),
not literals here.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.obs.events import REPLICA_STATES
from pvraft_tpu.programs.geometries import SUPERVISOR_DEFAULTS
from pvraft_tpu.rng import DEFAULT_SEED, host_rng
from pvraft_tpu.serve import faults


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """The state machine's trip points — defaults are the registry's
    declared data (``geometries.SUPERVISOR_DEFAULTS``); tests tighten
    them, production overrides ride the serve CLI flags."""

    degraded_after: int = SUPERVISOR_DEFAULTS["degraded_after"]
    quarantine_after: int = SUPERVISOR_DEFAULTS["quarantine_after"]
    latency_outlier_factor: float = \
        SUPERVISOR_DEFAULTS["latency_outlier_factor"]
    latency_outlier_after: int = SUPERVISOR_DEFAULTS["latency_outlier_after"]
    latency_min_samples: int = SUPERVISOR_DEFAULTS["latency_min_samples"]
    latency_floor_ms: float = SUPERVISOR_DEFAULTS["latency_floor_ms"]
    probe_interval_s: float = SUPERVISOR_DEFAULTS["probe_interval_s"]
    probe_timeout_s: float = SUPERVISOR_DEFAULTS["probe_timeout_s"]
    wedge_timeout_s: float = SUPERVISOR_DEFAULTS["wedge_timeout_s"]

    def __post_init__(self):
        if self.degraded_after < 1 or self.quarantine_after < 1:
            raise ValueError("failure thresholds must be >= 1")
        if self.quarantine_after < self.degraded_after:
            raise ValueError(
                "quarantine_after must be >= degraded_after (the state "
                "machine escalates, never skips backwards)")
        if self.latency_outlier_factor <= 1.0:
            raise ValueError("latency_outlier_factor must be > 1")
        if self.probe_interval_s < 0 or self.wedge_timeout_s <= 0 \
                or self.probe_timeout_s <= 0:
            raise ValueError(
                "probe_interval_s/probe_timeout_s/wedge_timeout_s invalid")

    @property
    def retry_after_s(self) -> int:
        """What a 503's ``Retry-After`` header advertises: one probe
        cycle (rounded up, >= 1 s) — a client retrying then meets a
        pool whose health was re-evaluated at least once."""
        import math

        return max(1, int(math.ceil(self.probe_interval_s)))


def _transition(states: List[str], replicas, i: int, new: str,
                reason: str) -> Dict[str, Any]:
    """Apply one state transition and build its ``replica_state``
    record. Module-level on purpose: callers hold the supervisor lock,
    and the lexical lock analysis (threadcheck GC001) then sees every
    state mutation at a locked call site instead of inside an
    un-annotatable helper method."""
    assert new in REPLICA_STATES
    old = states[i]
    states[i] = new
    return {
        "replica": i, "state": new, "from_state": old, "reason": reason,
        "device_id": int(getattr(replicas[i], "device_id", i)),
    }


def _observe_latency(ewma: Dict[int, List[float]], cfg: SupervisorConfig,
                     bucket: int, seconds: float) -> bool:
    """Outlier decision + EWMA update for one dispatch (caller holds
    the supervisor lock — module-level for the same reason as
    :func:`_transition`). The EWMA is fed by non-outlier samples only:
    outliers must not drag the baseline toward the pathology they
    measure. Below the absolute floor nothing is an outlier (sub-ms
    scheduler noise must not degrade a replica)."""
    slot = ewma.setdefault(int(bucket), [0.0, 0])
    mean, count = slot[0], int(slot[1])
    outlier = (
        count >= cfg.latency_min_samples
        and seconds * 1000.0 > cfg.latency_floor_ms
        and seconds > cfg.latency_outlier_factor * mean)
    if not outlier:
        slot[0] = seconds if count == 0 else 0.8 * mean + 0.2 * seconds
        slot[1] = count + 1
    return outlier


class ReplicaSupervisor:
    """Health state per replica + the background probe/wedge-scan loop.

    Thread-safe: the batcher's executors report dispatch outcomes
    concurrently while the probe thread transitions states. Transitions
    are decided under ``_lock`` and EMITTED after release (telemetry
    does file I/O behind its own lock — never nest ours over it)."""

    def __init__(self, engine, cfg: Optional[SupervisorConfig] = None,
                 telemetry=None):
        self.engine = engine
        self.cfg = cfg or SupervisorConfig()
        self.telemetry = telemetry
        self.replicas = list(getattr(engine, "replicas", ()) or ()) \
            or [engine]
        n = len(self.replicas)
        self._lock = ordered_lock("ReplicaSupervisor._lock")
        self._state = ["healthy"] * n            # guarded-by: _lock
        self._fail_streak = [0] * n              # guarded-by: _lock
        self._outlier_streak = [0] * n           # guarded-by: _lock
        # bucket -> [ewma_seconds, samples]; fed by non-outlier
        # dispatches only (outliers must not drag the baseline toward
        # the pathology they measure).
        self._ewma: Dict[int, List[float]] = {}  # guarded-by: _lock
        # Per-replica in-flight dispatch start times, keyed by the token
        # note_dispatch_start returns. A replica can run >1 dispatch at
        # once (its executor plus a sibling's retry), so one slot would
        # be clobbered — a wedged dispatch silently untracked.
        self._dispatch_started: List[Dict[int, float]] = \
            [{} for _ in range(n)]               # guarded-by: _lock
        self._dispatch_tokens = 0                # guarded-by: _lock
        self._transitions = 0                    # guarded-by: _lock
        self._probes = 0                         # guarded-by: _lock
        self._probe_failures = 0                 # guarded-by: _lock
        # Probe payload built once, before any thread exists. The engine
        # owns the request contract, so it builds the payload
        # (InferenceEngine.probe_request); the fallback covers pool
        # doubles that only expose the config surface.
        probe = getattr(engine, "probe_request", None)
        if probe is not None:
            self._probe_cloud, self._probe_bucket = probe()
        else:
            ecfg = self.engine.cfg
            n_pts = max(int(getattr(ecfg, "min_points", 4)), 1)
            scale = min(1.0,
                        0.5 * float(getattr(ecfg, "coord_limit", 100.0)))
            rng = host_rng(DEFAULT_SEED, "serve.probe")
            self._probe_cloud = rng.uniform(
                -scale, scale, (n_pts, 3)).astype(np.float32)
            self._probe_bucket = int(ecfg.buckets[0])
        self._probe_bucket = int(self._probe_bucket)
        # Probe-loop lifecycle (the DeviceMemoryMonitor pattern,
        # threadcheck GC003): start/stop swap the thread field under one
        # lock so concurrent callers cannot double-start or join a
        # replaced thread.
        self._stop = threading.Event()
        self._state_lock = ordered_lock("ReplicaSupervisor._state_lock")
        self._thread: Optional[threading.Thread] = None  # guarded-by: _state_lock

    # -------------------------------------------------------- transitions --

    def _emit(self, transitions: List[Dict[str, Any]]) -> None:
        for t in transitions:
            if self.telemetry is not None:
                self.telemetry.emit_replica_state(**t)

    def _on_fault(self, record: Dict[str, Any]) -> None:
        """Probe-side fault_injected sink (the batcher has its own)."""
        if self.telemetry is not None:
            self.telemetry.emit_fault(**record)

    # ------------------------------------------------------------ signals --

    def record_success(self, i: int, bucket: int, seconds: float) -> None:
        """A dispatch on replica ``i`` completed in ``seconds``. Resets
        the failure streak; feeds the latency-outlier signal; recovers
        a degraded replica. A quarantined/probing replica's straggler
        dispatch (started before the quarantine) changes nothing — only
        the probe revives."""
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            if self._state[i] in ("quarantined", "probing"):
                pass
            elif _observe_latency(self._ewma, self.cfg, bucket, seconds):
                self._outlier_streak[i] += 1
                if (self._state[i] == "healthy"
                        and self._outlier_streak[i]
                        >= self.cfg.latency_outlier_after):
                    transitions.append(_transition(
                        self._state, self.replicas, i, "degraded",
                        "latency_outlier"))
                    self._transitions += 1
            else:
                self._fail_streak[i] = 0
                self._outlier_streak[i] = 0
                if self._state[i] == "degraded":
                    transitions.append(_transition(
                        self._state, self.replicas, i, "healthy",
                        "recovered"))
                    self._transitions += 1
        self._emit(transitions)

    def record_failure(self, i: int, reason: str = "dispatch_error") -> None:
        """A dispatch on replica ``i`` raised. Escalates healthy ->
        degraded -> quarantined on consecutive failures."""
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            if self._state[i] not in ("quarantined", "probing"):
                self._fail_streak[i] += 1
                if self._fail_streak[i] >= self.cfg.quarantine_after:
                    transitions.append(_transition(
                        self._state, self.replicas, i, "quarantined",
                        reason))
                    self._transitions += 1
                elif (self._fail_streak[i] >= self.cfg.degraded_after
                      and self._state[i] == "healthy"):
                    transitions.append(_transition(
                        self._state, self.replicas, i, "degraded", reason))
                    self._transitions += 1
        self._emit(transitions)

    def note_dispatch_start(self, i: int, t: float) -> int:
        """Track one in-flight dispatch; returns the token the matching
        :meth:`note_dispatch_end` must pass back (concurrent dispatches
        on one replica — its executor plus a sibling's retry — each get
        their own entry, so a wedged one stays visible)."""
        with self._lock:
            self._dispatch_tokens += 1
            token = self._dispatch_tokens
            self._dispatch_started[i][token] = t
        return token

    def note_dispatch_end(self, i: int, token: int) -> None:
        with self._lock:
            self._dispatch_started[i].pop(token, None)

    # ------------------------------------------------------------ queries --

    def state_of(self, i: int) -> str:
        with self._lock:
            return self._state[i]

    def in_rotation(self, i: int) -> bool:
        """May this replica's executor pull work? Degraded replicas keep
        serving (visibly); quarantined/probing ones are out."""
        with self._lock:
            return self._state[i] in ("healthy", "degraded")

    def serving_count(self) -> int:
        """Replicas still in the work-stealing rotation — the admission
        capacity the batcher scales by."""
        with self._lock:
            return sum(1 for s in self._state
                       if s in ("healthy", "degraded"))

    def retry_target(self, exclude: int) -> Optional[int]:
        """A different in-rotation replica for the one retry a failed
        batch gets (healthy preferred over degraded), or None."""
        with self._lock:
            for want in ("healthy", "degraded"):
                for i, s in enumerate(self._state):
                    if i != exclude and s == want:
                        return i
        return None

    def states(self) -> List[Dict[str, Any]]:
        """Per-replica health rows for ``/healthz`` and Prometheus."""
        with self._lock:
            return [{"replica": i, "state": self._state[i],
                     "fail_streak": self._fail_streak[i],
                     "outlier_streak": self._outlier_streak[i]}
                    for i in range(len(self.replicas))]

    def pool_health(self) -> Dict[str, Any]:
        """The ``/healthz`` pool summary: serving count + overall state
        (``ok`` / ``degraded`` capacity / ``unavailable``)."""
        with self._lock:
            serving = sum(1 for s in self._state
                          if s in ("healthy", "degraded"))
            total = len(self._state)
        state = ("unavailable" if serving == 0
                 else "degraded" if serving < total else "ok")
        return {"state": state, "healthy_replicas": serving,
                "replicas_total": total,
                "probe_interval_s": self.cfg.probe_interval_s,
                "retry_after_s": self.cfg.retry_after_s}

    @property
    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {"transitions": self._transitions,
                    "probes": self._probes,
                    "probe_failures": self._probe_failures}

    # ------------------------------------------------------------- probes --

    def poll(self) -> None:
        """One supervision pass: wedge scan, then probe every
        quarantined replica. Public so tests drive the state machine
        deterministically without the background thread."""
        self._scan_wedged()
        self._probe_quarantined()

    def _scan_wedged(self) -> None:
        now = time.monotonic()
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            for i, starts in enumerate(self._dispatch_started):
                if (starts
                        and now - min(starts.values())
                        > self.cfg.wedge_timeout_s
                        and self._state[i] not in ("quarantined",
                                                   "probing")):
                    transitions.append(_transition(
                        self._state, self.replicas, i, "quarantined",
                        "wedged"))
                    self._transitions += 1
        self._emit(transitions)

    def _probe_quarantined(self) -> None:
        with self._lock:
            # Skip replicas that still have a dispatch in flight (the
            # wedged case): the device is demonstrably stuck, so a probe
            # would only wedge the supervisor loop beside it — the
            # replica becomes probe-eligible once the stuck dispatch
            # actually returns (e.g. the injected wedge released).
            targets = [i for i, s in enumerate(self._state)
                       if s == "quarantined"
                       and not self._dispatch_started[i]]
        for i in targets:
            with self._lock:
                # Re-check under the lock: a concurrent poll() (tests
                # drive it directly) may already be probing this one.
                if self._state[i] != "quarantined":
                    continue
                transitions = [_transition(
                    self._state, self.replicas, i, "probing", "probe")]
                self._transitions += 1
                self._probes += 1
            self._emit(transitions)
            ok = self._probe(i)
            with self._lock:
                if self._state[i] != "probing":
                    continue
                if ok:
                    self._fail_streak[i] = 0
                    self._outlier_streak[i] = 0
                    transitions = [_transition(
                        self._state, self.replicas, i, "healthy",
                        "probe_ok")]
                else:
                    self._probe_failures += 1
                    transitions = [_transition(
                        self._state, self.replicas, i, "quarantined",
                        "probe_failed")]
                self._transitions += 1
            self._emit(transitions)

    def _probe(self, i: int) -> bool:
        """Synthetic min-points request through the replica's own AOT
        program (the smallest bucket — always compiled, so no new
        backend compile and the sealed retrace watchdog stays quiet).
        Traverses the replica fault points first: an armed fault fails
        the probe, exactly like a dispatch.

        The probe runs on a bounded worker thread: a replica that hangs
        mid-probe (a genuinely dead device) must cost ONE
        ``probe_timeout_s``, not the whole supervisor loop — wedge scans
        and every other replica's revival keep running. A timed-out
        probe counts as failed; its late completion (the daemon thread
        eventually returning) transitions nothing, because only this
        loop consumes the result."""
        result: Dict[str, bool] = {}

        def run() -> None:
            try:
                faults.replica_faults(i, bucket=self._probe_bucket,
                                      on_fire=self._on_fault)
                self.replicas[i].predict_batch(
                    [(self._probe_cloud, self._probe_cloud)],
                    self._probe_bucket)
                result["ok"] = True
            except BaseException:  # noqa: BLE001 — a failed probe is a state, not a crash
                result["ok"] = False

        worker = threading.Thread(target=run, daemon=True,
                                  name=f"pvraft-serve-probe-r{i}")
        worker.start()
        worker.join(self.cfg.probe_timeout_s)
        return bool(result.get("ok"))

    # ---------------------------------------------------------- lifecycle --

    def start(self) -> None:
        with self._state_lock:
            if self.cfg.probe_interval_s <= 0 or self._thread is not None:
                return
            self._stop.clear()  # restartable: stop() leaves the flag set
            self._thread = threading.Thread(
                target=self._run, name="pvraft-serve-supervisor",
                daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception:  # noqa: BLE001 — supervise, never crash serving
                pass
            self._stop.wait(self.cfg.probe_interval_s)

    def stop(self) -> None:
        # Join under the lifecycle lock: the probe thread never takes
        # it, so no deadlock — this only serializes a concurrent
        # start() against the swap (the DeviceMemoryMonitor pattern).
        with self._state_lock:
            thread = self._thread
            if thread is None:
                return
            self._thread = None
            self._stop.set()
            # Join INSIDE the lock: a concurrent start() must not clear
            # the stop flag while the old thread is still polling it
            # (it would survive and run beside the replacement).
            thread.join(10.0)
