"""Serve telemetry: lifecycle + per-batch events on the obs stream.

The serving subsystem emits ``pvraft_events/v1`` records through the
SAME :class:`pvraft_tpu.obs.events.EventLog` the trainer uses — one
schema, one validator (``python -m pvraft_tpu.obs validate``), one gate
stage in ``scripts/lint.sh`` covering training and serving telemetry
alike. Event types: ``serve_compile`` (one per AOT program at startup),
``serve_batch`` (one per dispatched micro-batch), ``serve_reject``
(backpressure/contract rejections), ``serve_shutdown`` (drain summary).

Unlike the trainer (one writer process, one thread), serve events are
emitted from HTTP handler threads and batcher workers concurrently, so
every emit is serialized behind one lock — ``EventLog.seq`` must stay
strictly sequential or the file fails its own validator.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.obs.events import EventLog, run_metadata


class ServeTelemetry:
    """Thread-safe ``pvraft_events/v1`` writer for the serve lifecycle."""

    def __init__(self, events_path: str, cfg=None,
                 enabled: Optional[bool] = None):
        self._lock = ordered_lock("ServeTelemetry._lock")
        # EventLog.seq must stay strictly sequential: every emit after
        # the construction-time run_header goes through _lock.
        self.events = EventLog(events_path, enabled=enabled)  # guarded-by: _lock
        self.events.emit("run_header", **run_metadata(cfg, mode="serve"))

    def emit_compile(self, bucket: int, batch: int, lower_s: float,
                     compile_s: float,
                     memory: Optional[Dict[str, Any]] = None,
                     dtype: Optional[str] = None,
                     replica: Optional[int] = None,
                     device_id: Optional[int] = None) -> None:
        fields: Dict[str, Any] = {
            "bucket": bucket, "batch": batch,
            "lower_s": lower_s, "compile_s": compile_s}
        if memory is not None:
            fields["memory"] = memory
        # Replica-pool provenance (optional, schema-additive): which
        # dtype the program serves and which replica/device compiled it.
        if dtype is not None:
            fields["dtype"] = dtype
        if replica is not None:
            fields["replica"] = replica
        if device_id is not None:
            fields["device_id"] = device_id
        with self._lock:
            self.events.emit("serve_compile", **fields)

    def emit_batch(self, bucket: int, batch: int, n: int, fill: float,
                   latency_ms: float,
                   queue_depth: Optional[int] = None,
                   replica: Optional[int] = None,
                   device_id: Optional[int] = None) -> None:
        fields: Dict[str, Any] = {
            "bucket": bucket, "batch": batch, "n": n,
            "fill": fill, "latency_ms": latency_ms}
        if queue_depth is not None:
            fields["queue_depth"] = queue_depth
        if replica is not None:
            fields["replica"] = replica
        if device_id is not None:
            fields["device_id"] = device_id
        with self._lock:
            self.events.emit("serve_batch", **fields)

    def emit_reject(self, reason: str, bucket: Optional[int] = None,
                    queue_depth: Optional[int] = None) -> None:
        fields: Dict[str, Any] = {"reason": reason}
        if bucket is not None:
            fields["bucket"] = bucket
        if queue_depth is not None:
            fields["queue_depth"] = queue_depth
        with self._lock:
            self.events.emit("serve_reject", **fields)

    def emit_trace_window(self, action: str, trace_dir: str) -> None:
        """An on-demand ``/debug/trace`` XLA profile window. The shared
        ``trace_window`` event type requires an epoch; serving has none,
        so -1 marks "not an epoch-indexed capture"."""
        with self._lock:
            self.events.emit("trace_window", action=action,
                             trace_dir=trace_dir, epoch=-1)

    def emit_span(self, **span: Any) -> None:
        """One ``span`` record from the request trace plane
        (``obs/trace.py``): emitted by the handler thread after the
        response is written, so tracing never sits between the engine
        and the client."""
        with self._lock:
            self.events.emit("span", **span)

    def emit_recompile(self, program: str, count: int,
                       baseline: Optional[int] = None,
                       signature: Optional[str] = None,
                       context: Optional[str] = None) -> None:
        """The serve retrace watchdog saw a backend compile AFTER the
        AOT startup sealed the program set (obs/retrace.py) — on the
        serving path every compile is a latency cliff, so it rides the
        event stream whether or not --strict-retrace is armed."""
        fields: Dict[str, Any] = {"program": program, "count": count}
        if baseline is not None:
            fields["baseline"] = baseline
        if signature is not None:
            fields["signature"] = signature
        if context is not None:
            fields["context"] = context
        with self._lock:
            self.events.emit("recompile", **fields)

    def emit_device_memory(self, devices: list,
                           context: Optional[str] = None) -> None:
        """One periodic device-memory sample from the serve pool's
        monitor thread (obs/device_memory.py)."""
        fields: Dict[str, Any] = {"devices": devices}
        if context is not None:
            fields["context"] = context
        with self._lock:
            self.events.emit("device_memory", **fields)

    def emit_replica_state(self, replica: int, state: str,
                           from_state: Optional[str] = None,
                           reason: Optional[str] = None,
                           device_id: Optional[int] = None) -> None:
        """One supervisor state-machine transition
        (serve/supervisor.py): healthy/degraded/quarantined/probing —
        the fleet-health ledger an operator replays to see exactly when
        a replica fell out of (and returned to) the rotation."""
        fields: Dict[str, Any] = {"replica": replica, "state": state}
        if from_state is not None:
            fields["from_state"] = from_state
        if reason is not None:
            fields["reason"] = reason
        if device_id is not None:
            fields["device_id"] = device_id
        with self._lock:
            self.events.emit("replica_state", **fields)

    def emit_fault(self, point: str, replica: Optional[int] = None,
                   bucket: Optional[int] = None,
                   traversal: Optional[int] = None,
                   fires: Optional[int] = None,
                   value: Optional[float] = None) -> None:
        """One deterministic fault-point firing (serve/faults.py): the
        chaos evidence trail — every injected failure is on the stream
        beside the replica_state transitions it caused."""
        fields: Dict[str, Any] = {"point": point}
        if replica is not None:
            fields["replica"] = replica
        if bucket is not None:
            fields["bucket"] = bucket
        if traversal is not None:
            fields["traversal"] = traversal
        if fires is not None:
            fields["fires"] = fires
        if value is not None:
            fields["value"] = value
        with self._lock:
            self.events.emit("fault_injected", **fields)

    def emit_cost_calibration(self, bucket: int, batch: int, dtype: str,
                              predicted_s: float, measured_s: float,
                              platform: str, comparable: bool,
                              replica: Optional[int] = None,
                              basis: Optional[str] = None,
                              extrapolated: Optional[bool] = None,
                              program: Optional[str] = None) -> None:
        """One dispatch priced through the cost surface
        (serve/costing.py) beside its measured wall-seconds — the
        calibration ledger that proves (or indicts) the cost model.
        ``comparable`` may be true only on platform "tpu"; the validator
        rejects anything else (enforcing off-TPU is a schema violation
        by design)."""
        fields: Dict[str, Any] = {
            "bucket": bucket, "batch": batch, "dtype": dtype,
            "predicted_s": predicted_s, "measured_s": measured_s,
            "platform": platform, "comparable": comparable}
        if replica is not None:
            fields["replica"] = replica
        if basis is not None:
            fields["basis"] = basis
        if extrapolated is not None:
            fields["extrapolated"] = extrapolated
        if program is not None:
            fields["program"] = program
        with self._lock:
            self.events.emit("cost_calibration", **fields)

    def emit_fleet_route(self, backend: int, reason: str,
                         bucket: Optional[int] = None,
                         queue_depth: Optional[int] = None,
                         predicted_s: Optional[float] = None,
                         attempts: Optional[int] = None,
                         canary: Optional[bool] = None,
                         status: Optional[int] = None) -> None:
        """One fleet-router routing decision (pvraft_tpu/fleet): which
        backend got a request and why — the ledger a spillover or canary
        interleave is replayed from."""
        fields: Dict[str, Any] = {"backend": backend, "reason": reason}
        if bucket is not None:
            fields["bucket"] = bucket
        if queue_depth is not None:
            fields["queue_depth"] = queue_depth
        if predicted_s is not None:
            fields["predicted_s"] = predicted_s
        if attempts is not None:
            fields["attempts"] = attempts
        if canary is not None:
            fields["canary"] = canary
        if status is not None:
            fields["status"] = status
        with self._lock:
            self.events.emit("fleet_route", **fields)

    def emit_weight_swap(self, digest: str, epoch: int,
                         path: Optional[str] = None,
                         previous_digest: Optional[str] = None,
                         replicas: Optional[int] = None,
                         swap_ms: Optional[float] = None,
                         drained: Optional[int] = None) -> None:
        """One zero-downtime hot-swap (engine.swap_params): every
        replica's params pointer replaced with no recompile; ``epoch``
        is the checkpoint's epoch or the -1 epoch-less sentinel."""
        fields: Dict[str, Any] = {"digest": digest, "epoch": epoch}
        if path is not None:
            fields["path"] = path
        if previous_digest is not None:
            fields["previous_digest"] = previous_digest
        if replicas is not None:
            fields["replicas"] = replicas
        if swap_ms is not None:
            fields["swap_ms"] = swap_ms
        if drained is not None:
            fields["drained"] = drained
        with self._lock:
            self.events.emit("weight_swap", **fields)

    def emit_canary_verdict(self, verdict: str, epe: float, bound: float,
                            rel_epe: Optional[float] = None,
                            rel_bound: Optional[float] = None,
                            samples: Optional[int] = None,
                            fraction: Optional[float] = None,
                            canary_backend: Optional[int] = None,
                            baseline_backend: Optional[int] = None
                            ) -> None:
        """The router's canary promotion gate fired: mean EPE between
        canary and incumbent flows versus the pinned bound (the
        bf16-promotion precedent, programs/geometries.py)."""
        fields: Dict[str, Any] = {
            "verdict": verdict, "epe": epe, "bound": bound}
        if rel_epe is not None:
            fields["rel_epe"] = rel_epe
        if rel_bound is not None:
            fields["rel_bound"] = rel_bound
        if samples is not None:
            fields["samples"] = samples
        if fraction is not None:
            fields["fraction"] = fraction
        if canary_backend is not None:
            fields["canary_backend"] = canary_backend
        if baseline_backend is not None:
            fields["baseline_backend"] = baseline_backend
        with self._lock:
            self.events.emit("canary_verdict", **fields)

    def emit_shutdown(self, served: int, rejected: int,
                      drained: int) -> None:
        with self._lock:
            self.events.emit("serve_shutdown", served=served,
                             rejected=rejected, drained=drained)

    def close(self) -> None:
        with self._lock:
            self.events.close()
