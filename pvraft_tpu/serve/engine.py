"""AOT-bucketed scene-flow inference engine.

The serving counterpart of ``engine/steps.py``: a trained checkpoint
becomes a fixed set of ahead-of-time compiled ``predict`` programs, one
per (point-count bucket, batch size), so no request ever pays a compile
stall and the compile cost + HBM footprint are known (and reported)
before the first request arrives.

Padding-bucket discipline — the core design problem of serving
variable-N point clouds on TPU (XLA programs are shape-specialized):

  * every request is padded up to the smallest bucket that fits, with
    padding points placed GEOMETRICALLY FAR from the valid coordinate
    box (``ServeConfig.coord_limit``), so a real point's kNN sets (the
    encoder graph, built unmasked) are exactly the unpadded ones;
  * boolean validity masks ride along as program inputs: they exclude
    padding from every GroupNorm statistic and force padding candidates
    below every real value in the correlation truncation
    (``models/raft.py``, ``ops/corr.py`` ``valid1``/``valid2``);
  * together that makes padded-bucket predictions match unpadded
    inference to float-reassociation precision (test-gated,
    ``tests/test_serve.py``), so bucketing is a pure latency/memory
    trade with no accuracy cliff.

The batch axis needs no masking at all: every model op is
batch-parallel, so unused batch slots (filled with a copy of the first
request) cannot perturb real slots.

``pc1`` is donated to each predict program — it is the one input whose
(shape, dtype) matches the flow output, so XLA aliases instead of
allocating (deepcheck GJ004/GJ005 verify exactly this via the
``serve.predict`` audit entries).

Replica pool: the engine is data-parallel across local devices. Each
:class:`Replica` is a single-device executor — its own device-resident
copy of the params and its own per-(bucket, batch) compiled program
table. An XLA executable is bound to its device assignment, so every
replica pays a REAL backend compile per program (only the lowering is
cached — the committed ``serve_compile`` evidence shows replica > 0 at
``lower_s`` ~3 ms but full ``compile_s``); replica tables therefore
compile CONCURRENTLY at startup — wall-clock is one fail-fast first
program plus the slowest remaining table, not replicas x table. The batcher dispatches formed batches
to whichever replica is idle (work-stealing), so a slow large-bucket
batch occupies one replica while the others keep draining small
buckets. Serving dtype defaults to bfloat16
(``geometries.SERVE_DEFAULT_DTYPE``), gated by the pinned accuracy
bound vs fp32 (``tests/test_serve_pool.py``); fp32 is one ``--dtype
float32`` away.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.analysis.contracts import shapecheck
from pvraft_tpu.config import ModelConfig
from pvraft_tpu.programs.geometries import (
    SERVE_DEFAULT_BATCH_SIZES,
    SERVE_DEFAULT_BUCKETS,
    SERVE_DEFAULT_DTYPE,
    SERVE_DEFAULT_ITERS,
    SERVE_DEFAULT_REPLICAS,
    SERVE_DTYPES,
    SERVE_PREDICT_DONATE,
    predict_program_name,
    serve_program_keys,
)
from pvraft_tpu.rng import DEFAULT_SEED, host_rng
from pvraft_tpu.serve.aot import AotProgram, aot_compile


class RequestError(ValueError):
    """A request the engine cannot serve (size/coords out of contract).

    ``reason`` is a ``serve_reject`` event reason ("too_large",
    "too_small", "bad_request") so callers map it straight to telemetry
    and HTTP status codes."""

    def __init__(self, reason: str, msg: str):
        super().__init__(msg)
        self.reason = reason


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving knobs on top of the model architecture."""

    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    # Point-count buckets, ascending. A request with n points runs in the
    # smallest bucket >= n; larger requests are rejected (413). Defaults
    # are the registry-declared production geometry
    # (pvraft_tpu/programs/geometries.py) — the single place bucket/batch
    # tables live; tests/test_programs.py guards this file against
    # re-growing its own literals.
    buckets: Tuple[int, ...] = SERVE_DEFAULT_BUCKETS
    # Batch sizes compiled per bucket. The micro-batcher dispatches with
    # the smallest compiled size that fits the pending group and fills
    # unused slots with a copy of the first request (batch-parallel ops
    # make that exact).
    batch_sizes: Tuple[int, ...] = SERVE_DEFAULT_BATCH_SIZES
    # GRU refinement iterations at serve time (the reference evaluates at
    # 32; the default is the latency-lean choice — an accuracy/latency
    # knob).
    num_iters: int = SERVE_DEFAULT_ITERS
    # Serve a stage-2 (PVRaftRefine) checkpoint.
    refine: bool = False
    # Valid requests keep every |coordinate| < coord_limit; padding points
    # sit on a diagonal ray starting at 100 * coord_limit, so no padding
    # point can ever enter a real point's kNN neighborhood.
    coord_limit: float = 100.0
    # Serving compute dtype: bfloat16 by default (the TPU fast path),
    # test-gated by the pinned EPE bound vs fp32
    # (geometries.SERVE_BF16_EPE_BOUND); "float32" is the fallback flag.
    # Overrides the model config's compute_dtype — the serving dtype is
    # a serve decision, declared here, not a checkpoint property.
    dtype: str = SERVE_DEFAULT_DTYPE
    # Replica pool size: one single-device executor per replica. 0 = one
    # replica per local device; n > local devices is rejected at build.
    replicas: int = SERVE_DEFAULT_REPLICAS

    def __post_init__(self):
        if not self.buckets:
            raise ValueError("at least one bucket is required")
        if tuple(sorted(set(self.buckets))) != tuple(self.buckets):
            raise ValueError(
                f"buckets must be ascending and distinct, got {self.buckets}")
        if not self.batch_sizes:
            raise ValueError("at least one batch size is required")
        if tuple(sorted(set(self.batch_sizes))) != tuple(self.batch_sizes):
            raise ValueError(
                f"batch_sizes must be ascending and distinct, "
                f"got {self.batch_sizes}")
        if self.buckets[0] < self.min_points:
            raise ValueError(
                f"smallest bucket ({self.buckets[0]}) is below min_points "
                f"({self.min_points}): it could never hold a valid request")
        if self.coord_limit <= 0:
            raise ValueError("coord_limit must be positive")
        if self.dtype not in SERVE_DTYPES:
            raise ValueError(
                f"dtype must be one of {tuple(SERVE_DTYPES)}, "
                f"got {self.dtype!r}")
        if self.replicas < 0:
            raise ValueError("replicas must be >= 0 (0 = all local devices)")

    @property
    def min_points(self) -> int:
        """Smallest request the masked model serves exactly: the masked
        correlation truncation needs >= truncate_k real candidates, and
        the (unmasked, geometry-excluded) kNN graph needs > graph_k real
        points so no padding point is ever selected."""
        return max(self.model.truncate_k, self.model.graph_k + 1)

    @property
    def max_points(self) -> int:
        return self.buckets[-1]


def pad_points(pc: np.ndarray, bucket: int,
               coord_limit: float) -> np.ndarray:
    """Pad an (n, 3) cloud to (bucket, 3) with far-away points: a
    diagonal ray at 100x the coordinate limit, unit spacing, so padding
    is far from every real point AND padding points are distinct from
    each other (their own kNN stays well-defined)."""
    n = pc.shape[0]
    if n == bucket:
        return np.ascontiguousarray(pc, dtype=np.float32)
    base = 100.0 * coord_limit
    ray = base + np.arange(bucket - n, dtype=np.float32)
    pad = np.repeat(ray[:, None], 3, axis=1)
    return np.concatenate(
        [np.asarray(pc, np.float32), pad], axis=0)


def params_digest(variables) -> str:
    """Content fingerprint of a params tree: sha256 over every leaf's
    dtype/shape/bytes in deterministic tree order, truncated to 16 hex
    chars. What ``/healthz``'s weights block and ``weight_swap`` events
    carry — two engines serving the same weights agree on it, a hot-swap
    visibly changes it."""
    import hashlib

    import jax

    leaves, treedef = jax.tree_util.tree_flatten(variables)
    h = hashlib.sha256()
    h.update(repr(treedef).encode())
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


def build_predict_fn(model, num_iters: int, refine: bool = False):
    """The serve predict program body (what gets AOT-compiled):
    ``predict(params, pc1, pc2, valid1, valid2) -> flow`` with the
    padded clouds plus their validity masks. Named so pjit compiles a
    distinguishable program (profiles and deepcheck findings say
    'serve_predict', repo convention since PR 4)."""

    def serve_predict(params, pc1, pc2, valid1, valid2):
        if refine:
            return model.apply(params, pc1, pc2, num_iters, valid1, valid2)
        flows, _ = model.apply(
            params, pc1, pc2, num_iters, valid1, valid2)
        return flows[-1]

    return serve_predict


class Replica:
    """One single-device executor: device-local params + its own
    compiled (bucket, batch) program table.

    XLA executables are bound to their device assignment, so each
    replica compiles its own table — a full backend compile per program
    (only lowering is cached across replicas); the engine compiles the
    tables concurrently and the per-replica cost is on the record
    (``serve_compile`` events carry replica/device_id). ``predict_batch``
    is the only hot method; everything batch-agnostic (validation,
    bucket routing) stays on the engine."""

    def __init__(self, index: int, device, params, engine):
        self.index = index
        self.device = device
        self.device_id = int(device.id)
        self.engine = engine
        self.programs: Dict[Tuple[int, int], AotProgram] = {}
        # Hot-swap coordination (engine.swap_params): dispatches read
        # the params pointer AND register in-flight under _lock, so a
        # swap can replace the pointer and then wait for every dispatch
        # still holding the OLD params — never a torn read, never a
        # dropped old-params reference while a batch is on device.
        self._lock = ordered_lock("Replica._lock")
        self.params = params                 # guarded-by: _lock
        self._params_generation = 0          # guarded-by: _lock
        self._inflight: Dict[int, int] = {}  # generation -> dispatches; guarded-by: _lock
        self._drain_below = 0                # guarded-by: _lock
        self._drained: Optional[threading.Event] = None  # guarded-by: _lock

    def swap_params(self, params,
                    drain_timeout_s: float = 30.0) -> Tuple[int, bool]:
        """Replace this replica's device-resident params and wait for
        every dispatch still running on the OLD params to drain. New
        dispatches pick up the new pointer immediately (zero downtime);
        the old params object stays referenced by in-flight calls until
        they finish, and this method blocks (bounded) until that count
        is zero. Returns ``(old_inflight, drained_in_time)``. The AOT
        programs take params as a call argument, so nothing recompiles
        — the sealed retrace watchdog proves it structurally."""
        with self._lock:
            self.params = params
            self._params_generation += 1
            self._drain_below = self._params_generation
            pending = sum(c for g, c in self._inflight.items()
                          if g < self._drain_below)
            event = threading.Event() if pending else None
            self._drained = event
        if event is None:
            return 0, True
        drained = event.wait(drain_timeout_s)
        with self._lock:
            self._drained = None
        return pending, drained

    def predict_batch(
        self,
        requests: Sequence[Tuple[np.ndarray, np.ndarray]],
        bucket: int,
    ) -> List[np.ndarray]:
        """Run a group of validated same-bucket requests through this
        replica's compiled program; returns each request's un-padded
        (n1, 3) flow. Unused batch slots repeat request 0 (exact:
        batch-parallel ops)."""
        if not requests:
            return []
        cfg = self.engine.cfg
        bs = self.engine.batch_size_for(len(requests))
        if len(requests) > bs:
            raise ValueError(
                f"{len(requests)} requests exceed the largest compiled "
                f"batch size {bs}; the batcher must split first")
        cl = cfg.coord_limit
        rows1, rows2, v1, v2 = [], [], [], []
        for pc1, pc2 in requests:
            rows1.append(pad_points(np.asarray(pc1, np.float32), bucket, cl))
            rows2.append(pad_points(np.asarray(pc2, np.float32), bucket, cl))
            m1 = np.zeros(bucket, bool)
            m1[: pc1.shape[0]] = True
            m2 = np.zeros(bucket, bool)
            m2[: pc2.shape[0]] = True
            v1.append(m1)
            v2.append(m2)
        for _ in range(bs - len(requests)):          # fill: repeat slot 0
            rows1.append(rows1[0])
            rows2.append(rows2[0])
            v1.append(v1[0])
            v2.append(v2[0])
        prog = self.programs[(bucket, bs)]
        import jax

        # Read the params pointer and register in-flight in ONE lock
        # hold: a concurrent swap_params either sees this dispatch (and
        # waits for it) or hasn't swapped yet (this dispatch runs the
        # new params) — never a half-swapped view.
        with self._lock:
            params = self.params
            gen = self._params_generation
            self._inflight[gen] = self._inflight.get(gen, 0) + 1
        try:
            # The annotation brackets execute + host fetch (np.asarray
            # is the sync), so the trace plane's device_execute span
            # lines up with this named region in an XLA profile captured
            # via /debug/trace (one region per replica: device id in the
            # name).
            with jax.profiler.TraceAnnotation(
                    f"serve_device_execute_b{bucket}_bs{bs}"
                    f"_d{self.device_id}"):
                flow = np.asarray(prog(
                    params,
                    np.stack(rows1), np.stack(rows2),
                    np.stack(v1), np.stack(v2)))
        finally:
            with self._lock:
                self._inflight[gen] -= 1
                if self._inflight[gen] == 0:
                    del self._inflight[gen]
                event = self._drained
                old_pending = sum(
                    c for g, c in self._inflight.items()
                    if g < self._drain_below)
            # Signal AFTER release (never wake a waiter into a held
            # lock); the swap only cares that old-generation dispatches
            # hit zero.
            if event is not None and old_pending == 0:
                event.set()
        return [flow[i, : requests[i][0].shape[0]]
                for i in range(len(requests))]


class InferenceEngine:
    """Checkpoint -> a replica pool of AOT-compiled bucketed predict
    programs.

    Construction compiles every (bucket, batch) program up front on
    every replica's device and records per-program compile seconds +
    XLA memory analysis (``compile_report()``); a telemetry sink
    receives one ``serve_compile`` event per (replica, program), so the
    startup cost is in the event log before the first request. The
    serving dtype (``cfg.dtype``, bf16 by default) overrides the model
    config's ``compute_dtype`` — one declared serving decision instead
    of a per-checkpoint accident."""

    def __init__(self, params, cfg: ServeConfig, telemetry=None):
        import jax
        from jax.sharding import SingleDeviceSharding

        self.cfg = cfg
        self._telemetry = telemetry
        # Weights provenance (the /healthz weights block + weight_swap
        # events): source path (None = in-memory params), content
        # digest, checkpoint epoch (-1 = the epoch-less sentinel from
        # engine/checkpoint.load_params), swap count. Swaps serialize
        # behind _swap_lock (one admin reload at a time).
        self._swap_lock = ordered_lock("InferenceEngine._swap_lock")
        self._weights: Dict[str, Any] = {
            "path": None,
            "digest": params_digest(params),
            "epoch": -1,
            "swaps": 0,
        }  # guarded-by: _swap_lock
        from pvraft_tpu.models.raft import PVRaft, PVRaftRefine

        model_cfg = dataclasses.replace(cfg.model, compute_dtype=cfg.dtype)
        self.model = (PVRaftRefine if cfg.refine else PVRaft)(model_cfg)
        self._predict_fn = build_predict_fn(
            self.model, cfg.num_iters, refine=cfg.refine)
        devices = jax.local_devices()
        n = cfg.replicas or len(devices)
        if n > len(devices):
            raise ValueError(
                f"replicas={n} exceeds the {len(devices)} local devices "
                f"(one single-device executor per replica)")
        # Commit params to every replica device once; each program call
        # reuses its replica's copy (no cross-device traffic per request).
        self.replicas: List[Replica] = [
            Replica(idx, devices[idx],
                    jax.device_put(params, devices[idx]), self)
            for idx in range(n)]
        # The (bucket, batch) program table is the registry's
        # enumeration (programs/geometries.serve_program_keys) — the
        # same iteration order aot_readiness certifies and /healthz
        # reports.
        keys = list(serve_program_keys(cfg.buckets, cfg.batch_sizes))

        def build_one(replica: Replica, sharding, bucket: int,
                      bs: int) -> None:
            prog = self._compile(bucket, bs, replica, sharding)
            replica.programs[(bucket, bs)] = prog
            if telemetry is not None:
                telemetry.emit_compile(
                    bucket=bucket, batch=bs,
                    lower_s=round(prog.lower_s, 3),
                    compile_s=round(prog.compile_s, 3),
                    memory=prog.memory,
                    dtype=cfg.dtype, replica=replica.index,
                    device_id=replica.device_id)

        def build_table(replica: Replica, skip_first: bool) -> None:
            sharding = SingleDeviceSharding(replica.device)
            for bucket, bs in (keys[1:] if skip_first else keys):
                build_one(replica, sharding, bucket, bs)

        # Replica 0's FIRST program compiles alone: a broken program
        # fails fast with one clean traceback before any threads exist.
        # Everything else — the rest of replica 0's table and every
        # other replica's full table — compiles CONCURRENTLY: XLA
        # rebuilds the executable per device assignment (a full backend
        # compile each; only lowering is cached), so threading is what
        # keeps pool startup at ~one table of wall-clock (first program
        # + the slowest remaining table) instead of replicas x table.
        # Compiles release the GIL; telemetry emits are lock-serialized
        # (events interleave across replicas, each record carries its
        # replica id).
        build_one(self.replicas[0],
                  SingleDeviceSharding(self.replicas[0].device),
                  *keys[0])
        if len(self.replicas) == 1:
            build_table(self.replicas[0], skip_first=True)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=len(self.replicas),
                    thread_name_prefix="pvraft-serve-compile") as pool:
                futures = [pool.submit(build_table, r, r.index == 0)
                           for r in self.replicas]
                for f in futures:
                    f.result()          # propagate the first failure
        self.params = self.replicas[0].params
        self._programs = self.replicas[0].programs

    @classmethod
    def from_checkpoint(cls, path: str, cfg: ServeConfig, telemetry=None):
        """Load a checkpoint written by either backend (msgpack file or
        orbax directory, auto-detected) and build the engine. The
        checkpoint's path and epoch (-1 = epoch-less sentinel) are kept
        as weights provenance for /healthz and hot-swap events."""
        from pvraft_tpu.engine.checkpoint import load_params

        variables, epoch = load_params(path)
        engine = cls(variables, cfg, telemetry=telemetry)
        with engine._swap_lock:
            engine._weights["path"] = path
            engine._weights["epoch"] = int(epoch)
        return engine

    def _compile(self, bucket: int, bs: int, replica: Replica,
                 sharding) -> AotProgram:
        import jax

        f32 = jax.ShapeDtypeStruct((bs, bucket, 3), "float32",
                                   sharding=sharding)
        vmask = jax.ShapeDtypeStruct((bs, bucket), "bool",
                                     sharding=sharding)
        params_sds = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=sharding),
            replica.params)
        # Donate pc1 only: it is the unique input aliasing the (bs,
        # bucket, 3) f32 output; donating pc2/masks too would just be
        # silent copies (GJ004). The donation intent and program naming
        # are registry declarations (programs/geometries.py).
        return aot_compile(
            predict_program_name(bucket, bs, self.cfg.dtype),
            self._predict_fn,
            (params_sds, f32, f32, vmask, vmask),
            donate_argnums=SERVE_PREDICT_DONATE,
        )

    # ---------------------------------------------------------------- API --

    @property
    def platform(self) -> str:
        """The backend the replica pool executes on ("tpu"/"cpu"/...):
        the cost-calibration plane's comparable flag keys off this —
        predictions come from the TPU-topology inventory, and only a
        TPU measurement may be enforced against them."""
        return str(self.replicas[0].device.platform)

    def bucket_for(self, n_points: int) -> Optional[int]:
        """Smallest bucket holding ``n_points``, or None if too large."""
        for b in self.cfg.buckets:
            if n_points <= b:
                return b
        return None

    def batch_size_for(self, n_requests: int) -> int:
        """Smallest compiled batch size >= n_requests (the largest
        compiled size if none is — callers split such groups)."""
        for bs in self.cfg.batch_sizes:
            if n_requests <= bs:
                return bs
        return self.cfg.batch_sizes[-1]

    def compile_report(self) -> List[Dict[str, Any]]:
        return [p.report() for p in self._programs.values()]

    def weights_info(self) -> Dict[str, Any]:
        """The /healthz weights block: checkpoint path + content digest
        + epoch (-1 = the epoch-less sentinel) + hot-swap count."""
        with self._swap_lock:
            return dict(self._weights)

    def _check_swap_structure(self, variables) -> None:
        """A swapped-in tree must match the compiled params signature
        exactly (structure, shapes, dtypes): the AOT programs were
        compiled against it, so any mismatch would mean a recompile (or
        a crash mid-dispatch) — rejected up front instead."""
        import jax

        new_leaves, new_def = jax.tree_util.tree_flatten(variables)
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        if new_def != old_def:
            raise ValueError(
                "swap rejected: checkpoint tree structure differs from "
                "the compiled params signature (a hot-swap must never "
                f"recompile) — got {new_def}, serving {old_def}")
        for i, (n, o) in enumerate(zip(new_leaves, old_leaves)):
            if tuple(np.shape(n)) != tuple(np.shape(o)) \
                    or np.dtype(np.asarray(n).dtype) != np.dtype(
                        np.asarray(o).dtype):
                raise ValueError(
                    f"swap rejected: leaf {i} is "
                    f"{np.asarray(n).dtype}{tuple(np.shape(n))}, the "
                    f"compiled program expects "
                    f"{np.asarray(o).dtype}{tuple(np.shape(o))} (a "
                    "hot-swap must never recompile)")

    def swap_params(self, variables, path: Optional[str] = None,
                    epoch: int = -1,
                    drain_timeout_s: float = 30.0) -> Dict[str, Any]:
        """Zero-downtime weight hot-swap: commit ``variables`` to every
        replica's device and swap each replica's params pointer, waiting
        for in-flight batches on the old params to drain. The AOT
        programs take params as call arguments, so NOTHING recompiles —
        the sealed retrace watchdog (build_service) structurally proves
        it. Returns the swap report (also emitted as a ``weight_swap``
        event when the engine has a telemetry sink)."""
        import jax

        self._check_swap_structure(variables)
        t0 = time.monotonic()
        digest = params_digest(variables)
        drained = 0
        all_in_time = True
        pool_params = None
        with self._swap_lock:
            for replica in self.replicas:
                dev_params = jax.device_put(variables, replica.device)
                if pool_params is None:
                    pool_params = dev_params
                pending, in_time = replica.swap_params(
                    dev_params, drain_timeout_s=drain_timeout_s)
                drained += pending
                all_in_time = all_in_time and in_time
            self.params = pool_params
            previous = self._weights["digest"]
            self._weights = {
                "path": path, "digest": digest, "epoch": int(epoch),
                "swaps": self._weights["swaps"] + 1,
            }
        report = {
            "digest": digest,
            "previous_digest": previous,
            "epoch": int(epoch),
            "path": path,
            "replicas": len(self.replicas),
            "drained": drained,
            "drained_in_time": all_in_time,
            "swap_ms": round(1e3 * (time.monotonic() - t0), 3),
        }
        # Emit AFTER _swap_lock release: telemetry serializes behind its
        # own lock and we never nest it under ours.
        if self._telemetry is not None:
            self._telemetry.emit_weight_swap(
                digest=digest, epoch=int(epoch), path=path,
                previous_digest=previous,
                replicas=len(self.replicas), swap_ms=report["swap_ms"],
                drained=drained)
        return report

    def reload_checkpoint(self, path: str,
                          drain_timeout_s: float = 30.0) -> Dict[str, Any]:
        """``POST /admin/reload`` body: load a checkpoint (msgpack or
        orbax, auto-detected) and hot-swap it into the replica pool."""
        from pvraft_tpu.engine.checkpoint import load_params

        variables, epoch = load_params(path)
        return self.swap_params(variables, path=path, epoch=int(epoch),
                                drain_timeout_s=drain_timeout_s)

    def probe_request(self) -> Tuple[np.ndarray, int]:
        """The supervisor's synthetic health-probe payload: a
        deterministic ``min_points`` cloud strictly inside the
        coordinate contract, targeted at the smallest bucket — whose
        program table is always compiled, so a probe can never trigger
        a backend compile (the sealed retrace watchdog stays quiet).
        The engine owns the request contract, so the payload is built
        here, not in the supervisor."""
        rng = host_rng(DEFAULT_SEED, "serve.probe")
        scale = min(1.0, 0.5 * self.cfg.coord_limit)
        cloud = rng.uniform(
            -scale, scale,
            (max(self.cfg.min_points, 1), 3)).astype(np.float32)
        return cloud, self.cfg.buckets[0]

    def validate_request(self, pc1: np.ndarray, pc2: np.ndarray) -> int:
        """Check one request against the serve contract; returns its
        bucket. Raises :class:`RequestError` with a telemetry reason."""
        for name, pc in (("pc1", pc1), ("pc2", pc2)):
            pc = np.asarray(pc)
            if pc.ndim != 2 or pc.shape[1] != 3:
                raise RequestError(
                    "bad_request",
                    f"{name} must be (n, 3), got {pc.shape}")
            if not np.all(np.isfinite(pc)):
                raise RequestError(
                    "bad_request", f"{name} contains non-finite values")
            if np.abs(pc).max(initial=0.0) >= self.cfg.coord_limit:
                raise RequestError(
                    "bad_request",
                    f"{name} coordinates must satisfy |x| < "
                    f"{self.cfg.coord_limit} (padding points live beyond "
                    f"that; rescale the scene)")
            if pc.shape[0] < self.cfg.min_points:
                raise RequestError(
                    "too_small",
                    f"{name} has {pc.shape[0]} points; the masked model "
                    f"needs >= {self.cfg.min_points} real points per cloud "
                    f"(truncate_k={self.cfg.model.truncate_k}, "
                    f"graph_k={self.cfg.model.graph_k})")
        bucket = self.bucket_for(max(pc1.shape[0], pc2.shape[0]))
        if bucket is None:
            raise RequestError(
                "too_large",
                f"request has {max(pc1.shape[0], pc2.shape[0])} points; "
                f"largest bucket is {self.cfg.buckets[-1]}")
        return bucket

    def predict_batch(
        self,
        requests: Sequence[Tuple[np.ndarray, np.ndarray]],
        bucket: int,
    ) -> List[np.ndarray]:
        """Run a group of validated same-bucket requests on replica 0
        (the direct API path; the batcher work-steals across the whole
        pool). Returns each request's un-padded (n1, 3) flow."""
        return self.replicas[0].predict_batch(requests, bucket)

    @shapecheck("N 3", "M 3", out="N 3")
    def predict(self, pc1: np.ndarray, pc2: np.ndarray) -> np.ndarray:
        """Single-request convenience path (the public predict API):
        validate, pad to the bucket, run the bs-1 program, un-pad."""
        pc1 = np.asarray(pc1, np.float32)
        pc2 = np.asarray(pc2, np.float32)
        bucket = self.validate_request(pc1, pc2)
        return self.predict_batch([(pc1, pc2)], bucket)[0]
