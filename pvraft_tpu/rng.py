"""Seed-derivation contract: every RNG in the package starts here.

One config seed, many consumers. Before this module each plane invented
its own entropy — ``jax.random.key(0)`` inits scattered across the
audit/catalog/evaluator, ``np.random.default_rng(0)`` warm-up clouds in
serve (silently colliding with loadgen traffic seeded 0), ad-hoc
``seed * 100003 + idx`` arithmetic in the data plane. Determinism then
depends on nobody reusing a constant, which no tool checked.

The contract: a *stream* is a declared name below; every key/generator
is ``derive(seed, stream, *indices)`` (jax) or ``host_rng(seed, stream,
*indices)`` (numpy), where the stream name folds in as a stable tag so
two streams can never collide even from the same seed — the
``PARTITION_RULES`` discipline applied to entropy. ``detcheck`` (rules
GD001-GD005, ``pvraft_tpu/analysis/determinism/``) statically enforces
it: raw RNG constructors outside this file are GD002 findings, and
stream strings are validated against :data:`STREAMS` both here at call
time and there at lint time (the table is parsed from this file's AST,
so the checker and the runtime cannot drift).

Import-light on purpose: jax only inside :func:`derive`, numpy only
inside :func:`host_rng` — the data plane (which must stay jax-free) and
the registry (which must stay import-light) both use this module.
"""

from __future__ import annotations

import zlib
from typing import Tuple, Union

# The seed used where no config seed exists (registry thunks, audit
# entries, probe payloads): the de-facto value every hard-coded site
# used, now spelled once.
DEFAULT_SEED = 0

# The declared stream vocabulary: (name, what it seeds). Declared as
# data — like PARTITION_RULES for shardings and KERNEL_BINDINGS for
# kernel geometry — so GD002 can parse this tuple statically and flag
# any call site using a name that is not here.
STREAMS: Tuple[Tuple[str, str], ...] = (
    ("model.init", "network parameter initialization"),
    ("encoder.init", "encoder-only init (step-profiler ladder)"),
    ("data.shuffle", "epoch-level sample order (PrefetchLoader)"),
    ("data.subsample", "per-scene subsample permutations"),
    ("data.synthetic", "synthetic scene-flow scene generation"),
    ("serve.probe", "supervisor health-probe payload cloud"),
    ("serve.loadgen", "load-generator request payloads"),
    ("serve.retry_jitter", "load-generator retry backoff jitter"),
    ("profile.data", "step-profiler synthetic input clouds"),
    ("replay.input", "determinism replay input materialization"),
)

STREAM_NAMES: Tuple[str, ...] = tuple(name for name, _ in STREAMS)


def stream_tag(name: str) -> int:
    """Stable 31-bit tag of a declared stream name.

    crc32 of the name, masked positive: stable across processes and
    python versions (unlike ``hash``), cheap, and collision-free over
    the declared vocabulary (validated at import below).
    """
    if name not in STREAM_NAMES:
        raise ValueError(
            f"undeclared rng stream {name!r}; declare it in "
            f"pvraft_tpu.rng.STREAMS (known: {', '.join(STREAM_NAMES)})")
    return zlib.crc32(name.encode("utf-8")) & 0x7FFFFFFF


# A tag collision would silently merge two streams; with a ~10-entry
# vocabulary this is astronomically unlikely, but check once at import
# so adding a colliding name fails loudly, not statistically.
_tags = [zlib.crc32(n.encode("utf-8")) & 0x7FFFFFFF for n in STREAM_NAMES]
if len(set(_tags)) != len(_tags):  # pragma: no cover - vocabulary bug
    raise AssertionError("rng stream tag collision in STREAMS")
del _tags


def _fold_parts(parts: Tuple[Union[str, int], ...]) -> Tuple[int, ...]:
    if not parts or not isinstance(parts[0], str):
        raise ValueError(
            "derive/host_rng need a declared stream name as the first "
            "part: derive(seed, 'model.init', ...)")
    out = []
    for p in parts:
        if isinstance(p, str):
            out.append(stream_tag(p))
        elif isinstance(p, (int,)) and not isinstance(p, bool):
            out.append(int(p))
        else:
            raise TypeError(
                f"rng derivation parts must be declared stream names or "
                f"ints, got {type(p).__name__}: {p!r}")
    return tuple(out)


def derive(seed: int, *parts: Union[str, int]):
    """A jax PRNG key for ``(seed, *parts)`` via a fold_in chain.

    ``parts`` is a declared stream name followed by optional integer
    indices (epoch, replica, item...). Every distinct part sequence is
    an independent stream of the same config seed.
    """
    import jax

    key = jax.random.key(int(seed))
    for tag in _fold_parts(parts):
        key = jax.random.fold_in(key, tag)
    return key


def host_rng(seed: int, *parts: Union[str, int]):
    """A ``numpy.random.Generator`` for ``(seed, *parts)``.

    The host-side twin of :func:`derive` (data plane, serve payloads,
    profiler clouds — everywhere numpy sampling happens outside a
    trace). The entropy tuple seeds a SeedSequence, so distinct streams
    and indices are independent by construction; jax is never imported.
    """
    import numpy as np

    return np.random.default_rng((int(seed),) + _fold_parts(parts))


def host_entropy(seed: int, *parts: Union[str, int]) -> Tuple[int, ...]:
    """The raw entropy tuple ``host_rng`` seeds with — for consumers
    that derive outside numpy (the native C++ loader takes plain ints).
    """
    return (int(seed),) + _fold_parts(parts)
