"""Voxel-branch correlation pooling.

This op plays the role of the external ``torch-scatter`` CUDA kernel in the
reference (``model/corr.py:50,64-66``): for each query point and each pyramid
level, average the truncated correlation values of candidate points that fall
into each cell of a ``resolution^3`` cube centered on the current coordinate
estimate.

Semantics preserved exactly (SURVEY.md §7 hard-part 1):
  * cell index = round((candidate - coord) / r) per axis, valid iff all three
    components lie within +/- floor(resolution/2) (``corr.py:54-55``);
  * invalid candidates contribute nothing: the reference multiplies both the
    scattered values and the counts by the validity mask before scatter_add
    (``corr.py:64-65``), so its "dump into bin 0" only ever adds zeros;
  * counts are clamped to [1, N] before division (``corr.py:65``);
  * output always has resolution^3 cells per level (the reference pads
    missing trailing cells with zeros, ``corr.py:67-69`` — with a fixed
    num_segments the pad is never needed, same result).

Implementations:
  * ``voxel_bin_means`` — pure XLA: per-cell masked reductions, fully fused
    elementwise+reduce chains, deterministic (unlike CUDA atomics).
  * a Pallas TPU kernel (``pvraft_tpu.ops.pallas.voxel_corr``) that keeps the
    (TILE, K) candidate block in VMEM across all levels and cells — used when
    ``ModelConfig.use_pallas`` is set.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from pvraft_tpu.analysis.contracts import shapecheck


@shapecheck("B N K", "B N K 3", out="B N C", dtype="floating")
def voxel_bin_means(
    corr: jnp.ndarray,
    rel: jnp.ndarray,
    num_levels: int,
    base_scale: float,
    resolution: int = 3,
) -> jnp.ndarray:
    """Per-cell mean correlation over a pyramid of voxel cubes.

    corr: (B, N, K) truncated correlation values.
    rel:  (B, N, K, 3) candidate positions relative to the query coordinate.
    Returns (B, N, num_levels * resolution**3).

    The cell geometry is computed under ``stop_gradient`` mirroring the
    reference's ``no_grad`` region (``corr.py:52-62``); gradients flow only
    through the correlation values.
    """
    half = resolution // 2
    r3 = resolution**3
    n_pts = corr.shape[1]
    rel = lax.stop_gradient(rel)

    feats = []
    for lvl in range(num_levels):
        r = base_scale * (2**lvl)
        dv = jnp.round(rel / r)
        valid = jnp.all(jnp.abs(dv) <= half, axis=-1)          # (B, N, K)
        cell = (
            (dv[..., 0] + half) * (resolution**2)
            + (dv[..., 1] + half) * resolution
            + (dv[..., 2] + half)
        ).astype(jnp.int32)
        cell = jnp.where(valid, cell, 0)
        w = corr * valid.astype(corr.dtype)
        vf = valid.astype(corr.dtype)
        # One masked sum per cell: elementwise compare + reduce, which XLA
        # fuses into a handful of VPU passes over the (B, N, K) block.
        sums = jnp.stack(
            [jnp.sum(jnp.where(cell == j, w, 0), axis=-1) for j in range(r3)],
            axis=-1,
        )
        cnts = jnp.stack(
            [jnp.sum(jnp.where(cell == j, vf, 0), axis=-1) for j in range(r3)],
            axis=-1,
        )
        feats.append(sums / jnp.clip(cnts, 1, n_pts))
    return jnp.concatenate(feats, axis=-1)
