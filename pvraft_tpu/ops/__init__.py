from pvraft_tpu.ops.geometry import (
    Graph,
    build_graph,
    gather_neighbors,
    knn_indices,
    pairwise_sqdist,
)
from pvraft_tpu.ops.corr import CorrState, corr_init, corr_volume, knn_lookup
from pvraft_tpu.ops.voxel import voxel_bin_means

__all__ = [
    "Graph",
    "build_graph",
    "gather_neighbors",
    "knn_indices",
    "pairwise_sqdist",
    "CorrState",
    "corr_init",
    "corr_volume",
    "knn_lookup",
    "voxel_bin_means",
]
