"""Scatter-free custom VJPs for the gather-dominated hot path.

XLA differentiates every gather into a scatter-add, and on TPU scatter is
a serialized per-update loop the MXU cannot help with — the backward pass
of PV-RAFT's hot loop (neighbor gathers in ``SetConv``, the candidate
selection in ``knn_lookup``, the k-neighbor max-pool) is therefore
scatter-bound even though the forward is gather/matmul-bound. These
custom VJPs rewrite each backward as a **one-hot matmul** (a batched
segment-sum expressed as a dense contraction), the dense-primitive
restructuring PointTransformerX argues for (PAPERS.md): the "scatter" of
``K`` cotangent rows into ``M`` bins becomes ``onehot(idx) @ g`` on the
MXU.

Memory discipline: the one-hot tensor is never materialized beyond
``ONEHOT_ELEM_BUDGET`` elements — larger problems stream the flattened
gather axis (accumulating carry) or the batch-like point axis (stacked
outputs) under ``lax.scan``.

All of these are **opt-in** via ``ModelConfig.scatter_free_vjp``; with
the flag off the callers' jaxprs are byte-identical to the pre-existing
XLA-default paths. Grad parity against the XLA default is test-gated
(``tests/test_scatter_free.py``). Tie semantics of ``max_pool_argmax``:
the full cotangent goes to the FIRST maximum (torch semantics) where the
XLA default splits it across ties — identical whenever the max is unique.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pvraft_tpu.analysis.contracts import shapecheck

# Peak one-hot footprint allowed inside a backward before the problem is
# chunked under lax.scan (elements, not bytes; 1<<24 = 16M elems = 64 MB
# fp32 — comfortably inside a v5e core's working set next to the
# activations the same backward already holds).
ONEHOT_ELEM_BUDGET = 1 << 24


def _int_cotangent(idx: jnp.ndarray):
    """The float0 zero cotangent custom_vjp requires for integer primals."""
    return np.zeros(np.shape(idx), dtype=jax.dtypes.float0)


def _scatter_add_onehot(
    idx_flat: jnp.ndarray, g_flat: jnp.ndarray, m: int
) -> jnp.ndarray:
    """Sum cotangent rows into their index bins via one-hot matmuls.

    idx_flat: (B, P) int32, g_flat: (B, P, C) -> (B, M, C) with
    ``out[b, idx_flat[b, p]] += g_flat[b, p]`` — the segment-sum that XLA
    would emit as scatter-add, expressed as ``onehot^T @ g`` so it runs on
    the MXU. P is streamed in chunks (accumulating carry) when the one-hot
    would exceed ``ONEHOT_ELEM_BUDGET``.
    """
    b, p = idx_flat.shape
    c = g_flat.shape[-1]
    acc_dtype = jnp.promote_types(g_flat.dtype, jnp.float32)
    bins = jnp.arange(m, dtype=idx_flat.dtype)

    n_chunks = max(1, -(-(b * p * m) // ONEHOT_ELEM_BUDGET))
    if n_chunks == 1:
        oh = (idx_flat[..., None] == bins).astype(acc_dtype)      # (B, P, M)
        out = jnp.einsum(
            "bpm,bpc->bmc", oh, g_flat.astype(acc_dtype),
            preferred_element_type=acc_dtype,
        )
        return out.astype(g_flat.dtype)

    chunk = -(-p // n_chunks)
    pad = n_chunks * chunk - p
    # Zero-padded cotangent rows contribute nothing wherever their
    # (padded-to-0) index lands, so padding is exact.
    idx_p = jnp.pad(idx_flat, ((0, 0), (0, pad)))
    g_p = jnp.pad(g_flat, ((0, 0), (0, pad), (0, 0)))
    idx_c = jnp.swapaxes(idx_p.reshape(b, n_chunks, chunk), 0, 1)
    g_c = jnp.swapaxes(g_p.reshape(b, n_chunks, chunk, c), 0, 1)

    def step(acc, xs):
        ic, gc = xs
        oh = (ic[..., None] == bins).astype(acc_dtype)
        return acc + jnp.einsum(
            "bpm,bpc->bmc", oh, gc.astype(acc_dtype),
            preferred_element_type=acc_dtype,
        ), None

    acc0 = jnp.zeros((b, m, c), acc_dtype)
    acc, _ = lax.scan(step, acc0, (idx_c, g_c))
    return acc.astype(g_flat.dtype)


# --- gather_neighbors -------------------------------------------------------

# Static data (bin counts) rides as nondiff_argnums: custom_vjp residuals
# are pytrees of arrays, so shapes/dtypes must never be residual leaves.
# Cotangent dtypes already equal the primal output dtypes, so no dtype
# bookkeeping is needed.


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _gather_onehot(m: int, feats: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    del m
    return jax.vmap(lambda f, i: f[i])(feats, idx)


def _gather_onehot_fwd(m, feats, idx):
    del m
    return jax.vmap(lambda f, i: f[i])(feats, idx), idx


def _gather_onehot_bwd(m, idx, g):
    b = idx.shape[0]
    df = _scatter_add_onehot(
        idx.reshape(b, -1), g.reshape(b, -1, g.shape[-1]), m
    )
    return df, _int_cotangent(idx)


_gather_onehot.defvjp(_gather_onehot_fwd, _gather_onehot_bwd)


@shapecheck("B M C", "B N K", out="B N K C")
def gather_neighbors_onehot(feats: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``ops.geometry.gather_neighbors`` with a scatter-free backward.

    feats: (B, M, C), idx: (B, N, k) -> (B, N, k, C). Forward is the same
    batched gather; the VJP accumulates ``d feats`` with one-hot matmuls
    instead of XLA's scatter-add.
    """
    return _gather_onehot(feats.shape[1], feats, idx)


# --- knn_lookup candidate selection ----------------------------------------


def _take_pair_impl(corr, rel, nbr):
    knn_corr = jnp.take_along_axis(corr, nbr, axis=-1)
    rel_xyz = jnp.take_along_axis(rel, nbr[..., None], axis=2)
    return knn_corr, rel_xyz


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _take_pair_onehot(k_total, corr, rel, nbr):
    del k_total
    return _take_pair_impl(corr, rel, nbr)


def _take_pair_fwd(k_total, corr, rel, nbr):
    del k_total
    return _take_pair_impl(corr, rel, nbr), nbr


def _take_pair_bwd(k_total, nbr, gs):
    g_corr, g_rel = gs
    b, n, j = nbr.shape
    acc_dtype = jnp.promote_types(g_corr.dtype, jnp.float32)
    bins = jnp.arange(k_total, dtype=nbr.dtype)

    def dense(nc, g1, g2):
        # nc: (B, n', j); one (B, n', j, K) one-hot feeds BOTH cotangents.
        oh = (nc[..., None] == bins).astype(acc_dtype)
        dc = jnp.einsum("bnjk,bnj->bnk", oh, g1.astype(acc_dtype),
                        preferred_element_type=acc_dtype)
        dr = jnp.einsum("bnjk,bnjc->bnkc", oh, g2.astype(acc_dtype),
                        preferred_element_type=acc_dtype)
        return dc, dr

    n_chunks = max(1, -(-(b * n * j * k_total) // ONEHOT_ELEM_BUDGET))
    if n_chunks == 1:
        dc, dr = dense(nbr, g_corr, g_rel)
    else:
        # N is a batch axis here: stream it with stacked outputs.
        chunk = -(-n // n_chunks)
        pad = n_chunks * chunk - n

        def pad_n(x):
            return jnp.pad(x, ((0, 0), (0, pad)) + ((0, 0),) * (x.ndim - 2))

        def to_chunks(x):
            return jnp.swapaxes(
                pad_n(x).reshape((b, n_chunks, chunk) + x.shape[2:]), 0, 1
            )

        def step(_, xs):
            return None, dense(*xs)

        _, (dc_c, dr_c) = lax.scan(
            step, None, (to_chunks(nbr), to_chunks(g_corr), to_chunks(g_rel))
        )
        dc = jnp.swapaxes(dc_c, 0, 1).reshape(b, n_chunks * chunk, k_total)
        dc = dc[:, :n]
        dr = jnp.swapaxes(dr_c, 0, 1).reshape(
            b, n_chunks * chunk, k_total, g_rel.shape[-1]
        )[:, :n]
    return dc.astype(g_corr.dtype), dr.astype(g_rel.dtype), _int_cotangent(nbr)


_take_pair_onehot.defvjp(_take_pair_fwd, _take_pair_bwd)


@shapecheck("B N K", "B N K 3", "B N J", out=("B N J", "B N J 3"))
def take_pair_onehot(
    corr: jnp.ndarray, rel: jnp.ndarray, nbr: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The ``knn_lookup`` candidate selection with a scatter-free backward.

    corr: (B, N, K), rel: (B, N, K, 3), nbr: (B, N, j) indices into the K
    axis -> (knn_corr (B, N, j), rel_xyz (B, N, j, 3)). One shared
    ``(B, N, j, K)`` one-hot turns both ``take_along_axis`` backwards into
    per-row matmuls over the K candidate axis.
    """
    return _take_pair_onehot(corr.shape[-1], corr, rel, nbr)


# --- SetConv k-neighbor max-pool -------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _max_pool_argmax(k, h):
    del k
    return jnp.max(h, axis=2)


def _max_pool_fwd(k, h):
    del k
    # Residual is the int argmax (B, N, C) — k x smaller than saving h,
    # which matters under remat policies that would otherwise rebuild the
    # full (B, N, k, C) pre-pool tensor just to re-derive the max mask.
    return jnp.max(h, axis=2), jnp.argmax(h, axis=2).astype(jnp.int32)


def _max_pool_bwd(k, amax, g):
    sel = (
        jnp.arange(k, dtype=amax.dtype)[None, None, :, None]
        == amax[:, :, None, :]
    )
    return (jnp.where(sel, g[:, :, None, :], 0),)


_max_pool_argmax.defvjp(_max_pool_fwd, _max_pool_bwd)


@shapecheck("B N K C", out="B N C")
def max_pool_argmax(h: jnp.ndarray) -> jnp.ndarray:
    """``jnp.max(h, axis=2)`` with a scatter-free, argmax-residual VJP.

    h: (B, N, k, C) -> (B, N, C). The backward routes the cotangent to the
    first maximum along k via a dense comparison against the saved int32
    argmax — no recomputation of h, no tie-splitting division.
    """
    return _max_pool_argmax(h.shape[2], h)
