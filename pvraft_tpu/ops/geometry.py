"""Point-cloud geometry primitives.

TPU-native replacements for the reference graph machinery
(``model/flot/graph.py``). Differences by design:

  * the kNN graph is a dense ``(B, N, k)`` index tensor — not the reference's
    flat, per-batch-offset edge list built in Python loops
    (``model/flot/graph.py:62-79``); gathers stay batched and XLA-friendly;
  * neighbor search uses one MXU matmul for the distance matrix
    (same quadratic-expansion math as ``model/flot/graph.py:53-57``) and
    ``lax.top_k`` instead of a full ``argsort`` (``graph.py:60``);
  * edge features (relative coordinates) are gathered on demand — nothing is
    materialized per edge up front.

Tie-breaking of equidistant neighbors may differ from torch ``argsort``;
this affects bit-level parity only (SURVEY.md §7 hard-part 2).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from pvraft_tpu.analysis.contracts import shapecheck


@shapecheck("B N 3", "B M 3", out="B N M")
def pairwise_sqdist(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Squared euclidean distances between two clouds.

    a: (B, N, 3), b: (B, M, 3) -> (B, N, M).

    Quadratic expansion ``|a|^2 + |b|^2 - 2 a.b`` so the cross term is a
    single batched matmul on the MXU (semantics of
    ``model/flot/graph.py:53-57``).
    """
    a2 = jnp.sum(a * a, axis=-1, keepdims=True)            # (B, N, 1)
    b2 = jnp.sum(b * b, axis=-1, keepdims=True)            # (B, M, 1)
    # f32 accumulation pinned (precision-flow discipline, deepcheck
    # GJ006): neighbor SELECTION must not move with the compute_dtype
    # lever — bf16-accumulated distances change which edges the graph
    # aggregates. Same convention as corr.py / ring.py / scatter_free.py.
    cross = jnp.einsum(
        "bnc,bmc->bnm", a, b, preferred_element_type=jnp.float32
    )
    return a2 + jnp.swapaxes(b2, -1, -2) - 2.0 * cross


@shapecheck("B N 3", "B M 3", out="B N K")
def knn_indices(
    query: jnp.ndarray,
    points: jnp.ndarray,
    k: int,
    chunk: Optional[int] = None,
    approx: bool = False,
) -> jnp.ndarray:
    """Indices of the k nearest ``points`` for each ``query`` point.

    query: (B, N, 3), points: (B, M, 3) -> (B, N, k) int32, nearest first.
    When query is points itself, each point's first neighbor is itself
    (distance 0), matching ``model/flot/graph.py:60``.

    With ``chunk`` set, the M axis is streamed with a running top-k so the
    full (N, M) distance matrix is never materialized — the memory lever
    for 16k+ point graphs (1 GB fp32 at 16,384^2), mirroring the chunked
    correlation truncation (SURVEY.md §5 long-context note).

    ``approx`` selects ``lax.approx_max_k`` (TPU-native partial
    reduction, recall ~0.95, same lever as ``corr_init``'s
    ``approx_topk``) on the dense path; rejected with ``chunk`` (the
    streaming running top-k is exact by construction).
    """
    if approx and chunk is not None:
        # Rejected BEFORE the chunk>=M dense-path normalization below so
        # the contract is deterministic (not dependent on the cloud size),
        # matching ModelConfig's unconditional approx_knn x graph_chunk
        # rejection.
        raise ValueError(
            "approx kNN is not supported with chunked streaming "
            "(the running top-k is exact by construction)"
        )
    if chunk is not None and chunk >= points.shape[1]:
        chunk = None   # one chunk would cover everything: use the dense path
    if chunk is None:
        d = pairwise_sqdist(query, points)
        if approx:
            _, idx = lax.approx_max_k(-d, k, aggregate_to_topk=True)
        else:
            _, idx = lax.top_k(-d, k)
        return idx.astype(jnp.int32)

    b, m, _ = points.shape
    if m % chunk != 0:
        raise ValueError(f"chunk {chunk} must divide M={m}")
    q2 = jnp.sum(query * query, axis=-1, keepdims=True)      # (B, N, 1)
    points_c = jnp.swapaxes(points.reshape(b, m // chunk, chunk, 3), 0, 1)
    offsets = jnp.arange(m // chunk, dtype=jnp.int32) * chunk

    def step(carry, xs):
        best_negd, best_idx = carry
        pts, off = xs                                        # (B, chunk, 3)
        p2 = jnp.sum(pts * pts, axis=-1)[:, None, :]         # (B, 1, chunk)
        # f32 accumulation pinned — same selection-precision discipline
        # as the dense path above.
        cross = jnp.einsum(
            "bnc,bmc->bnm", query, pts, preferred_element_type=jnp.float32
        )
        negd = -(q2 + p2 - 2.0 * cross)                      # (B, N, chunk)
        idx = jnp.broadcast_to(
            (jnp.arange(chunk, dtype=jnp.int32) + off)[None, None, :],
            negd.shape,
        )
        cand_v = jnp.concatenate([best_negd, negd], axis=-1)
        cand_i = jnp.concatenate([best_idx, idx], axis=-1)
        new_v, sel = lax.top_k(cand_v, k)
        new_i = jnp.take_along_axis(cand_i, sel, axis=-1)
        return (new_v, new_i), None

    init = (
        # f32 like the fold output (the pinned-accumulation einsum):
        # a bf16 query must not give the scan a carry-dtype mismatch.
        jnp.full((b, query.shape[1], k), -jnp.inf, jnp.float32),
        jnp.zeros((b, query.shape[1], k), jnp.int32),
    )
    (_, idx), _ = lax.scan(step, init, (points_c, offsets))
    return idx


@shapecheck("B M C", "B N K", out="B N K C")
def gather_neighbors(
    feats: jnp.ndarray, idx: jnp.ndarray, dense_vjp: bool = False
) -> jnp.ndarray:
    """Gather per-neighbor features.

    feats: (B, M, C), idx: (B, N, k) -> (B, N, k, C).

    ``dense_vjp`` swaps XLA's default gather-grad (a scatter-add, which
    serializes on TPU) for the scatter-free one-hot-matmul VJP
    (``ops/scatter_free.py``); forward values and the default-path jaxpr
    are unchanged. Opt-in via ``ModelConfig.scatter_free_vjp``.
    """
    if dense_vjp:
        from pvraft_tpu.ops.scatter_free import gather_neighbors_onehot

        return gather_neighbors_onehot(feats, idx)
    return jax.vmap(lambda f, i: f[i])(feats, idx)


class Graph(NamedTuple):
    """Directed kNN graph on a point cloud.

    Functional replacement for the reference ``Graph`` object
    (``model/flot/graph.py:4-25``): batched index tensor + relative
    neighbor coordinates, usable directly inside jit.
    """

    neighbors: jnp.ndarray   # (B, N, k) int32
    rel_pos: jnp.ndarray     # (B, N, k, 3) = xyz[neighbor] - xyz[center]

    @property
    def k(self) -> int:
        return self.neighbors.shape[-1]


@shapecheck("B N 3", out=("B N K", "B N K 3"))
def build_graph(pc: jnp.ndarray, k: int, chunk: Optional[int] = None,
                approx: bool = False, dense_vjp: bool = False) -> Graph:
    """Construct the kNN graph of a cloud with itself.

    pc: (B, N, 3). Mirrors ``Graph.construct_graph`` (``graph.py:27-89``)
    with batched tensors instead of flat edge lists. ``dense_vjp`` routes
    the coordinate gather's backward through the scatter-free VJP (the
    cloud receives gradient via ``rel_pos``).
    """
    idx = knn_indices(pc, pc, k, chunk=chunk, approx=approx)
    nb = gather_neighbors(pc, idx, dense_vjp=dense_vjp)
    return Graph(neighbors=idx, rel_pos=nb - pc[:, :, None, :])
