"""Pallas TPU kernel for voxel-branch correlation pooling.

The "native" tier of this framework — playing the role torch-scatter's CUDA
``scatter_add`` plays in the reference (``model/corr.py:50,64-66``). One
kernel invocation computes the per-cell mean correlation for ALL pyramid
levels of a tile of query points, keeping the (TILE_N, K) candidate block
resident in VMEM across the 3 levels x 27 cells of masked reductions —
versus the XLA fallback which re-reads the block from HBM per fused
reduction group.

Layout notes:
  * ``rel`` is passed as three separate (B, N, K) planes so the lane
    (last) dimension is K (512 by default) — a (..., 3) trailing axis
    would waste the 128-wide vector lanes;
  * the grid is (B, N / TILE_N); each program writes a (TILE_N, L*27)
    output tile;
  * gradients flow through ``corr`` only (the reference computes cell
    geometry under ``no_grad``, ``corr.py:52-62``) via a custom VJP whose
    backward is a cheap XLA gather.

Deterministic by construction (fixed reduction order), unlike CUDA
scatter-add atomics — see SURVEY.md §5 "race detection".

Statically analyzed: kernelcheck (``python -m pvraft_tpu.analysis
kernels``) models the ``pallas_call`` site below at the flagship
geometry via the ``KERNEL_BINDINGS`` row keyed on
``_voxel_forward_pallas`` and its parameter names — a rename or
geometry change here must keep that row in sync (the gate fails with
GK000 otherwise, never silently).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp

from pvraft_tpu.analysis.contracts import shapecheck
from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()


def _pick_tile(n: int, target: int = 64) -> int:
    """Largest divisor of n that is <= target (prefer multiples of 8 —
    the fp32 sublane quantum, so the (tile, K) block maps onto whole
    (8, 128) layout tiles; kernelcheck GK001 errors on misaligned
    *chosen* tiles). kernelcheck evaluates this helper from its AST
    (never imports this module) when modeling the launch geometry, so
    keep it dependency-free pure Python."""
    best = 1
    for t in range(1, min(n, target) + 1):
        if n % t == 0 and (t % 8 == 0 or t == n or best < 8):
            best = t
    return best


def voxel_level_means(
    corr, relx, rely, relz, scale: float, resolution: int, count_cap: float
):
    """Per-cell mean correlation of ONE pyramid level for a VMEM tile.

    The single source of the parity-critical binning semantics
    (round/valid/cell-index/count-clamp, reference ``corr.py:52-69``) —
    shared by the voxel-only and fused kernels. Inputs are (TILE, K)
    values; returns (TILE, resolution**3).
    """
    half = resolution // 2
    r3 = resolution**3
    inv = 1.0 / scale
    dvx = jnp.round(relx * inv)
    dvy = jnp.round(rely * inv)
    dvz = jnp.round(relz * inv)
    valid = (
        (jnp.abs(dvx) <= half) & (jnp.abs(dvy) <= half) & (jnp.abs(dvz) <= half)
    )
    cell = (dvx + half) * (resolution**2) + (dvy + half) * resolution + (dvz + half)
    w = jnp.where(valid, corr, 0.0)
    vf = valid.astype(corr.dtype)
    cols = []
    for j in range(r3):
        hit = (cell == j).astype(corr.dtype) * vf     # (TILE, K)
        s = jnp.sum(w * hit, axis=-1)                  # (TILE,)
        c = jnp.sum(hit, axis=-1)
        cols.append(s / jnp.clip(c, 1.0, count_cap))
    return jnp.stack(cols, axis=-1)


def _voxel_kernel(
    corr_ref,
    relx_ref,
    rely_ref,
    relz_ref,
    out_ref,
    *,
    scales: Sequence[float],
    resolution: int,
    count_cap: float,
):
    corr = corr_ref[0]          # (TILE_N, K)
    relx = relx_ref[0]
    rely = rely_ref[0]
    relz = relz_ref[0]
    r3 = resolution**3
    for lvl, r in enumerate(scales):
        out_ref[0, :, lvl * r3 : (lvl + 1) * r3] = voxel_level_means(
            corr, relx, rely, relz, r, resolution, count_cap
        )


def _voxel_forward_pallas(
    corr: jnp.ndarray,
    relx: jnp.ndarray,
    rely: jnp.ndarray,
    relz: jnp.ndarray,
    num_levels: int,
    base_scale: float,
    resolution: int,
) -> jnp.ndarray:
    b, n, k = corr.shape
    tile = _pick_tile(n)
    r3 = resolution**3
    scales = tuple(base_scale * (2**i) for i in range(num_levels))
    kernel = functools.partial(
        _voxel_kernel,
        scales=scales,
        resolution=resolution,
        count_cap=float(n),
    )
    in_spec = pl.BlockSpec((1, tile, k), lambda bi, ni: (bi, ni, 0))
    return pl.pallas_call(
        kernel,
        grid=(b, n // tile),
        in_specs=[in_spec, in_spec, in_spec, in_spec],
        out_specs=pl.BlockSpec(
            (1, tile, num_levels * r3), lambda bi, ni: (bi, ni, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, n, num_levels * r3), corr.dtype),
        interpret=interpret_mode(),
    )(corr, relx, rely, relz)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
@shapecheck("B N K", "B N K 3", out="B N C", dtype="floating")
def voxel_bin_means_pallas(
    corr: jnp.ndarray,
    rel: jnp.ndarray,
    num_levels: int,
    base_scale: float,
    resolution: int = 3,
) -> jnp.ndarray:
    """Drop-in for :func:`pvraft_tpu.ops.voxel.voxel_bin_means` backed by the
    Pallas kernel. corr: (B, N, K); rel: (B, N, K, 3) -> (B, N, L*R^3)."""
    rel = jax.lax.stop_gradient(rel)
    return _voxel_forward_pallas(
        corr, rel[..., 0], rel[..., 1], rel[..., 2],
        num_levels, base_scale, resolution,
    )


def _cells_and_valid(rel, scale, resolution):
    half = resolution // 2
    dv = jnp.round(rel / scale)
    valid = jnp.all(jnp.abs(dv) <= half, axis=-1)
    cell = (
        (dv[..., 0] + half) * (resolution**2)
        + (dv[..., 1] + half) * resolution
        + (dv[..., 2] + half)
    ).astype(jnp.int32)
    return jnp.where(valid, cell, 0), valid


def _voxel_fwd(corr, rel, num_levels, base_scale, resolution):
    out = voxel_bin_means_pallas(corr, rel, num_levels, base_scale, resolution)
    return out, (corr, rel)


def _voxel_bwd(num_levels, base_scale, resolution, res, g):
    corr, rel = res
    rel = jax.lax.stop_gradient(rel)
    b, n, k = corr.shape
    r3 = resolution**3
    dcorr = jnp.zeros_like(corr)
    for lvl in range(num_levels):
        scale = base_scale * (2**lvl)
        cell, valid = _cells_and_valid(rel, scale, resolution)
        vf = valid.astype(corr.dtype)
        # Recompute per-cell counts (cheap: 27 fused masked reductions).
        cnts = jnp.stack(
            [jnp.sum(jnp.where(cell == j, vf, 0), axis=-1) for j in range(r3)],
            axis=-1,
        )
        g_over_c = g[..., lvl * r3 : (lvl + 1) * r3] / jnp.clip(cnts, 1, n)
        # d out[cell]/d corr[k] = valid[k]/count[cell]  -> gather per candidate.
        dcorr = dcorr + vf * jnp.take_along_axis(g_over_c, cell, axis=-1)
    return dcorr, None


voxel_bin_means_pallas.defvjp(_voxel_fwd, _voxel_bwd)
