"""Fused MotionEncoder + ConvGRU update — Pallas TPU kernel.

One kernel pass per tile of points runs the whole per-iteration feature
update (reference ``model/update.py``: MotionEncoder's three 1x1 convs +
ConvGRU's three gates) from VMEM-resident inputs:

  * the corr features, context features and hidden state for a point
    tile are read from HBM ONCE per iteration and every intermediate —
    ``cor``/``flo`` motion features, the 192-channel ``hx`` concat, the
    ``z``/``r``/``q`` gate activations — lives and dies in VMEM; the
    unfused path materializes each of them to HBM between the eight
    separate Dense launches;
  * the three gate Denses are packed into single lane-stacked matmuls
    (``wn3``/``wi3``/``wh3``/``wf3``: one (·, 3H) dot per input block
    instead of three (·, H) dots), and the concat-Dense pairs of the
    reference are decomposed into per-operand dots — exact math
    (``concat(a, b) @ W == a @ W_a + b @ W_b``), different float
    accumulation order, which is what the pinned parity tolerances in
    ``tests/test_fused_gru.py`` absorb.

Tiling follows the committed VMEM plan (``artifacts/kernel_plan.json``):
tile=1024 at K=512, tile=2048 at K<=128 on the point axis — the same
point-tile geometry the plan certifies VMEM-resident alongside the
lookup working set. The plan's *cross-iteration* residency row (keep the
candidate block on chip across all 32 iterations) is NOT implementable
at exact parity: every GRU iteration contains cross-point global ops
(GroupNorm over the point axis inside both CorrLookup heads, and the
FlowHead's SetConv gathers graph neighbors across the full cloud), so
the scan must sync the whole cloud each iteration. This kernel ships the
per-iteration fusion the plan's geometry admits; the planner's
``gru_iter`` rows record the shipped footprint honestly.

Gradients: ``jax.custom_vjp`` whose backward differentiates the pure-XLA
:func:`_gru_reference` (the same rank-agnostic :func:`_gru_math` the
kernel body executes, so forward and backward describe one function —
the ``corr_lookup._fused_bwd`` recompute-in-XLA precedent).

Statically analyzed: kernelcheck models the single ``pallas_call`` site
below at the flagship geometry via the ``KERNEL_BINDINGS`` row keyed on
``_gru_forward`` and its parameter names. A rename or geometry change
here must keep that row in sync; the gate fails with GK000 otherwise,
never silently. Keep this module to ONE ``pallas_call`` site: the VMEM
planner maps kernel-tagged ProgramSpecs to modules one-to-one.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from pvraft_tpu.analysis.contracts import shapecheck
from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()

# Flow is padded from 3 to FLOW_PAD channels (zero columns) so the
# flow-input matmuls run on an 8-row (one fp32 sublane) operand; zero
# rows contribute exact zeros, so the padded dot equals the 3-row dot.
FLOW_PAD = 8


def _gru_tile(n: int, k: int) -> int:
    """Point-axis tile: the kernel_plan.json geometry (tile=1024 at
    K=512, tile=2048 at K<=128), clamped to an 8-aligned tile that does
    not exceed the cloud. Non-divisible ``n`` is fine — the grid rounds
    up and Pallas masks the tail block's out-of-bounds lanes (per-point
    rows are independent). Pure Python on ints: kernelcheck executes
    this helper for real when modeling the launch geometry."""
    target = 2048 if k <= 128 else 1024
    aligned = max(8, (n // 8) * 8)
    return min(target, aligned)


def _gru_math(net, inp, cor_in, flow8, weights, dtype_name: str):
    """The fused update's math, rank-agnostic over leading axes: the
    kernel body runs it on (TILE, C) VMEM blocks, :func:`_gru_reference`
    (and through it the custom-VJP backward) on (B, N, C) arrays —
    one definition, so the two paths cannot drift.

    Dtype discipline mirrors the unfused flax modules exactly:
    ``nn.Dense(dtype=d)`` promotes inputs and params to ``d``; the GRU
    carry stays float32 (``net32``) and the blend back to float32 is the
    last op, token for token the unfused ``ConvGRU`` return line.
    """
    d = jnp.dtype(dtype_name)
    h = net.shape[-1]
    net32 = net.astype(jnp.float32)
    netd = net32.astype(d)
    inpd = inp.astype(d)
    cord = cor_in.astype(d)
    flod = flow8.astype(d)
    wc, wf, wh, wn3, wi3, wh3, wf3, bias = (w.astype(d) for w in weights)
    b_me = bias[0:1]                      # MotionEncoder biases, (1, 3H)
    b_g = bias[1:2]                       # gate biases bz|br|bq, (1, 3H)

    # MotionEncoder: conv_corr / conv_flow / conv (update.py:34-40).
    cor = jax.nn.relu(jnp.dot(cord, wc) + b_me[..., 0:h])
    flo = jax.nn.relu(jnp.dot(flod, wf) + b_me[..., h:2 * h])
    hid = jax.nn.relu(jnp.dot(cor, wh[:h]) + jnp.dot(flo, wh[h:])
                      + b_me[..., 2 * h:3 * h])

    # ConvGRU gates (update.py:52-66), all three packed on the lane
    # axis. px = the net-independent contribution dot(x, W*) + b* where
    # x = concat(inp, hid, flow); z/r add dot(net, W*_net), q adds
    # dot(r*net, Wq_net).
    px = (jnp.dot(inpd, wi3) + jnp.dot(hid, wh3) + jnp.dot(flod, wf3)
          + b_g)
    zr = px[..., 0:2 * h] + jnp.dot(netd, wn3[..., 0:2 * h])
    z = jax.nn.sigmoid(zr[..., 0:h])
    r = jax.nn.sigmoid(zr[..., h:2 * h])
    q = jnp.tanh(px[..., 2 * h:3 * h]
                 + jnp.dot(r * netd, wn3[..., 2 * h:3 * h]))
    return ((1.0 - z) * net32 + z * q).astype(jnp.float32)


def _gru_kernel(net_ref, inp_ref, cor_ref, flow_ref, wc_ref, wf_ref,
                wh_ref, wn3_ref, wi3_ref, wh3_ref, wf3_ref, bias_ref,
                out_ref, *, dtype_name: str):
    weights = (wc_ref[...], wf_ref[...], wh_ref[...], wn3_ref[...],
               wi3_ref[...], wh3_ref[...], wf3_ref[...], bias_ref[...])
    out_ref[0] = _gru_math(net_ref[0], inp_ref[0], cor_ref[0],
                           flow_ref[0], weights, dtype_name)


def _gru_forward(net, inp, cor, flow8, weights, truncate_k, dtype_name):
    b, n, h = net.shape
    c = inp.shape[2]
    cw = cor.shape[2]
    f = flow8.shape[2]
    tile = _gru_tile(n, truncate_k)
    wc, wf, wh, wn3, wi3, wh3, wf3, bias = weights
    kernel = functools.partial(_gru_kernel, dtype_name=dtype_name)
    net_spec = pl.BlockSpec((1, tile, h), lambda bi, ni: (bi, ni, 0))
    inp_spec = pl.BlockSpec((1, tile, c), lambda bi, ni: (bi, ni, 0))
    cor_spec = pl.BlockSpec((1, tile, cw), lambda bi, ni: (bi, ni, 0))
    flow_spec = pl.BlockSpec((1, tile, f), lambda bi, ni: (bi, ni, 0))
    # Weights ride along whole (block == array, constant index map):
    # ~0.2 MiB total, dwarfed by the streamed point blocks.
    wc_spec = pl.BlockSpec(wc.shape, lambda bi, ni: (0, 0))
    wf_spec = pl.BlockSpec(wf.shape, lambda bi, ni: (0, 0))
    wh_spec = pl.BlockSpec(wh.shape, lambda bi, ni: (0, 0))
    wn3_spec = pl.BlockSpec(wn3.shape, lambda bi, ni: (0, 0))
    wi3_spec = pl.BlockSpec(wi3.shape, lambda bi, ni: (0, 0))
    wh3_spec = pl.BlockSpec(wh3.shape, lambda bi, ni: (0, 0))
    wf3_spec = pl.BlockSpec(wf3.shape, lambda bi, ni: (0, 0))
    bias_spec = pl.BlockSpec(bias.shape, lambda bi, ni: (0, 0))
    return pl.pallas_call(
        kernel,
        grid=(b, -(-n // tile)),
        in_specs=[net_spec, inp_spec, cor_spec, flow_spec, wc_spec,
                  wf_spec, wh_spec, wn3_spec, wi3_spec, wh3_spec,
                  wf3_spec, bias_spec],
        out_specs=pl.BlockSpec((1, tile, h), lambda bi, ni: (bi, ni, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n, h), jnp.float32),
        interpret=interpret_mode(),
    )(net, inp, cor, flow8, wc, wf, wh, wn3, wi3, wh3, wf3, bias)


def pad_flow(flow):
    """Zero-pad the (B, N, 3) flow to (B, N, FLOW_PAD) channels.

    Callers pad BEFORE :func:`fused_gru_update`: the padded array is the
    custom VJP's flow operand, so the compiled program's flow argument is
    byte-identical to the kernel operand the static HBM model counts
    (the planner's exactness pin), and flow gradients reach the raw
    3-channel estimate through this concat's transpose (a slice)."""
    b, n, w = flow.shape
    return jnp.concatenate(
        [flow, jnp.zeros((b, n, FLOW_PAD - w), flow.dtype)], axis=-1)


def pack_gru_weights(me_params, gru_params, hidden: int, context: int):
    """Pack the raw flax Dense params into the kernel's operand layout.

    ``me_params``: ``(wc, bc, wf, bf, wh, bh)`` — MotionEncoder's
    conv_corr / conv_flow / conv kernels+biases; ``gru_params``:
    ``(wz, bz, wr, br, wq, bq)``. Returns the 8-tuple
    ``(wc, wf, wh, wn3, wi3, wh3, wf3, bias)``:

      * flow-input kernels zero-padded from 3 to :data:`FLOW_PAD` rows
        (matching :func:`pad_flow`'s zero columns — exact);
      * the ``conv`` kernel's output padded ``hidden-3 -> hidden``
        columns (the motion feature's flow channels are handled by the
        separate ``wf3`` path, so the pad columns stay exactly zero);
      * the three gate kernels lane-stacked to ``(·, 3*hidden)`` and
        row-split by ``hx = concat(net, inp, hid, flow)`` segment;
      * both bias sets in one sublane-padded ``(FLOW_PAD, 3*hidden)``
        array (row 0: MotionEncoder, row 1: gates).

    Runs OUTSIDE the custom VJP: only zero-pads, slices and concats, so
    gradients flow back to the raw flax params exactly.
    """
    wc, bc, wf, bf, wh, bh = me_params
    wz, bz, wr, br, wq, bq = gru_params
    h = hidden
    wf8 = jnp.pad(wf, ((0, FLOW_PAD - wf.shape[0]), (0, 0)))
    whp = jnp.pad(wh, ((0, 0), (0, h - wh.shape[1])))
    bhp = jnp.pad(bh, (0, h - bh.shape[0]))
    wg = jnp.concatenate([wz, wr, wq], axis=1)        # (H+C+H, 3H)
    wn3 = wg[0:h]
    wi3 = wg[h:h + context]
    hid_rows = wg[h + context:h + context + (h - 3)]
    wh3 = jnp.pad(hid_rows, ((0, 3), (0, 0)))         # pad H-3 -> H rows
    flow_rows = wg[h + context + (h - 3):]
    wf3 = jnp.pad(flow_rows, ((0, FLOW_PAD - 3), (0, 0)))
    bias2 = jnp.stack([jnp.concatenate([bc, bf, bhp]),
                       jnp.concatenate([bz, br, bq])])
    bias = jnp.pad(bias2, ((0, FLOW_PAD - 2), (0, 0)))
    return (wc, wf8, whp, wn3, wi3, wh3, wf3, bias)


def _gru_reference(net, inp, cor, flow8, weights, dtype_name):
    """Pure-XLA twin of the kernel — same :func:`_gru_math`, whole-array
    operands (flow already :func:`pad_flow`-padded, like the kernel's).
    The custom VJP differentiates THIS, and the parity tests pin the
    Pallas forward against it."""
    return _gru_math(net, inp, cor, flow8, weights, dtype_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
@shapecheck("B N H", "B N C", "B N D", "B N 8", None, out="B N H")
def fused_gru_update(
    net: jnp.ndarray,
    inp: jnp.ndarray,
    cor: jnp.ndarray,
    flow8: jnp.ndarray,
    weights: Tuple[jnp.ndarray, ...],
    dtype_name: str,
    truncate_k: int,
) -> jnp.ndarray:
    """Fused MotionEncoder + ConvGRU hidden-state update.

    net: (B, N, H) float32 GRU hidden state; inp: (B, N, C) context
    features; cor: (B, N, D) correlation features (compute dtype);
    flow8: (B, N, FLOW_PAD) flow estimate, zero-padded by
    :func:`pad_flow` OUTSIDE this custom VJP; weights: the 8-tuple from
    :func:`pack_gru_weights`. ``dtype_name`` is the compute dtype
    (``"float32"`` / ``"bfloat16"``), ``truncate_k`` the model's
    candidate count — it selects the plan-certified point tile.
    Returns the new (B, N, H) float32 hidden state.
    """
    return _gru_forward(net, inp, cor, flow8, weights,
                        truncate_k, dtype_name)


def _fused_gru_fwd(net, inp, cor, flow8, weights, dtype_name, truncate_k):
    out = fused_gru_update(net, inp, cor, flow8, weights, dtype_name,
                           truncate_k)
    return out, (net, inp, cor, flow8, weights)


def _fused_gru_bwd(dtype_name, truncate_k, res, g):
    net, inp, cor, flow8, weights = res
    _, vjp = jax.vjp(
        lambda n_, i_, c_, f_, w_: _gru_reference(n_, i_, c_, f_, w_,
                                                  dtype_name),
        net, inp, cor, flow8, weights)
    return vjp(g)


fused_gru_update.defvjp(_fused_gru_fwd, _fused_gru_bwd)
