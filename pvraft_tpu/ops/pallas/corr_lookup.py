"""Fused point-voxel correlation lookup — Pallas TPU kernel.

One kernel pass per tile of query points computes BOTH branches of the
paper's correlation lookup (reference ``CorrBlock.__call__``,
``model/corr.py:44-93``) from VMEM-resident candidates:

  * voxel branch: per-cell mean correlation over ``num_levels`` cube
    pyramids (the torch-scatter role, ``corr.py:47-73``);
  * point branch: the 32 candidates nearest to the current coordinate,
    their correlation values and relative offsets (``corr.py:75-89``).

Versus the unfused path this reads the (N, K) candidate block once per GRU
iteration instead of: once for rel, once for the voxel masks, once for the
kNN distances — the lookup is HBM-bound, so fewer passes is the win. The
relative offsets are computed in-kernel from the iteration-invariant
candidate positions and the per-iteration coords, so the (B, N, K, 3)
``rel`` tensor never exists in HBM at all.

kNN selection is 32 rounds of (min, first-argmin-by-iota, mask-out) on the
VMEM tile — O(k·K) VPU work, no sort. Tie-breaking: lowest candidate index
wins (torch ``topk`` tie order differs; bit-level only, SURVEY.md §7).

Gradients flow to ``corr`` only (geometry is under ``no_grad`` in the
reference, and the model stop-gradients coords before the lookup);
backward recomputes selections with XLA ops.

Statically analyzed: kernelcheck models the ``pallas_call`` site below
at the flagship geometry via the ``KERNEL_BINDINGS`` row keyed on
``_fused_forward`` and its parameter names (the float-valued-iota argmin
below is exactly the shape its GK004 hazard table guards — the integer
pre-fix form is pinned DETECTED in ``tests/fixtures/kernelcheck/``). A
rename or geometry change here must keep that row in sync; the gate
fails with GK000 otherwise, never silently.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from pvraft_tpu.analysis.contracts import shapecheck
from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()

from pvraft_tpu.ops.pallas.voxel_corr import (
    _pick_tile,
    _voxel_bwd,
    voxel_level_means,
)


def _fused_kernel(
    corr_ref, x2x_ref, x2y_ref, x2z_ref, cx_ref, cy_ref, cz_ref,
    vox_ref, kcorr_ref, krx_ref, kry_ref, krz_ref,
    *, scales: Sequence[float], resolution: int, count_cap: float, knn: int,
):
    corr = corr_ref[0]                     # (TILE, K)
    relx = x2x_ref[0] - cx_ref[0]          # coords broadcast: (TILE, 1)
    rely = x2y_ref[0] - cy_ref[0]
    relz = x2z_ref[0] - cz_ref[0]
    r3 = resolution**3
    k_cand = corr.shape[-1]

    # --- voxel branch (shared binning semantics, voxel_corr.py) -----------
    for lvl, r in enumerate(scales):
        vox_ref[0, :, lvl * r3 : (lvl + 1) * r3] = voxel_level_means(
            corr, relx, rely, relz, r, resolution, count_cap
        )

    # --- kNN branch -------------------------------------------------------
    dist = relx * relx + rely * rely + relz * relz     # (TILE, K)
    # Float-VALUED iota, generated as i32 then cast: Mosaic has no
    # integer min-reduction lowering (the all-int variant FAILs to
    # compile on current libtpu) and only supports 32-bit integer iota
    # generation — and f32 represents candidate indices exactly up to
    # 2^24 >> any K here, so the first-of-ties argmin semantics are
    # unchanged.
    iota = lax.broadcasted_iota(
        jnp.int32, dist.shape, 1).astype(jnp.float32)
    cap = jnp.asarray(float(k_cand), jnp.float32)
    big = jnp.asarray(jnp.inf, dist.dtype)
    # Collect the knn columns and store each output once, contiguously
    # (per-lane stores in the loop lower poorly on TPU).
    c_corr, c_rx, c_ry, c_rz = [], [], [], []
    for j in range(knn):
        m = jnp.min(dist, axis=-1, keepdims=True)             # (TILE, 1)
        eq = dist == m
        first = iota == jnp.min(
            jnp.where(eq, iota, cap), axis=-1, keepdims=True
        )
        sel = first.astype(corr.dtype)
        c_corr.append(jnp.sum(corr * sel, axis=-1))
        c_rx.append(jnp.sum(relx * sel, axis=-1))
        c_ry.append(jnp.sum(rely * sel, axis=-1))
        c_rz.append(jnp.sum(relz * sel, axis=-1))
        dist = jnp.where(first, big, dist)
    kcorr_ref[0] = jnp.stack(c_corr, axis=-1)
    krx_ref[0] = jnp.stack(c_rx, axis=-1)
    kry_ref[0] = jnp.stack(c_ry, axis=-1)
    krz_ref[0] = jnp.stack(c_rz, axis=-1)


def _fused_forward(
    corr: jnp.ndarray, xyz: jnp.ndarray, coords: jnp.ndarray,
    num_levels: int, base_scale: float, resolution: int, knn: int,
):
    b, n, k = corr.shape
    tile = _pick_tile(n)
    r3 = resolution**3
    scales = tuple(base_scale * (2**i) for i in range(num_levels))
    kernel = functools.partial(
        _fused_kernel,
        scales=scales, resolution=resolution, count_cap=float(n), knn=knn,
    )
    cand_spec = pl.BlockSpec((1, tile, k), lambda bi, ni: (bi, ni, 0))
    coord_spec = pl.BlockSpec((1, tile, 1), lambda bi, ni: (bi, ni, 0))
    out_shapes = (
        jax.ShapeDtypeStruct((b, n, num_levels * r3), corr.dtype),
        jax.ShapeDtypeStruct((b, n, knn), corr.dtype),
        jax.ShapeDtypeStruct((b, n, knn), corr.dtype),
        jax.ShapeDtypeStruct((b, n, knn), corr.dtype),
        jax.ShapeDtypeStruct((b, n, knn), corr.dtype),
    )
    out_spec = pl.BlockSpec(
        (1, tile, num_levels * r3), lambda bi, ni: (bi, ni, 0)
    )
    knn_spec = pl.BlockSpec((1, tile, knn), lambda bi, ni: (bi, ni, 0))
    return pl.pallas_call(
        kernel,
        grid=(b, n // tile),
        in_specs=[cand_spec] * 4 + [coord_spec] * 3,
        out_specs=(out_spec, knn_spec, knn_spec, knn_spec, knn_spec),
        out_shape=out_shapes,
        interpret=interpret_mode(),
    )(
        corr,
        xyz[..., 0], xyz[..., 1], xyz[..., 2],
        coords[..., 0:1], coords[..., 1:2], coords[..., 2:3],
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
@shapecheck("B N K", "B N K 3", "B N 3", out=("B N C", "B N J", "B N J 3"))
def fused_corr_lookup(
    corr: jnp.ndarray,
    xyz: jnp.ndarray,
    coords: jnp.ndarray,
    num_levels: int,
    base_scale: float,
    resolution: int,
    knn: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused lookup.

    corr: (B, N, K); xyz: (B, N, K, 3) candidate positions; coords: (B, N, 3)
    current estimates. Returns:
      vox      (B, N, num_levels * resolution^3) per-cell means,
      knn_corr (B, N, knn),
      knn_rel  (B, N, knn, 3).
    """
    xyz = lax.stop_gradient(xyz)
    coords = lax.stop_gradient(coords)
    vox, kcorr, krx, kry, krz = _fused_forward(
        corr, xyz, coords, num_levels, base_scale, resolution, knn
    )
    return vox, kcorr, jnp.stack([krx, kry, krz], axis=-1)


def _fused_fwd(corr, xyz, coords, num_levels, base_scale, resolution, knn):
    out = fused_corr_lookup(
        corr, xyz, coords, num_levels, base_scale, resolution, knn
    )
    return out, (corr, xyz, coords)


def _fused_bwd(num_levels, base_scale, resolution, knn, res, grads):
    corr, xyz, coords = res
    g_vox, g_kcorr, _g_krel = grads
    rel = lax.stop_gradient(xyz - coords[:, :, None, :])

    # Voxel branch: shared with the voxel-only kernel's VJP.
    dcorr, _ = _voxel_bwd(num_levels, base_scale, resolution, (corr, rel), g_vox)

    # kNN branch: scatter the selected-candidate grads back. Selection is
    # recomputed with lax.top_k (identical up to tie order).
    dist = jnp.sum(rel * rel, axis=-1)
    _, nbr = lax.top_k(-dist, knn)                       # (B, N, knn)
    dsel = jnp.zeros_like(corr)
    dsel = jax.vmap(
        jax.vmap(lambda d, i, g: d.at[i].add(g))
    )(dsel, nbr, g_kcorr)
    return dcorr + dsel, None, None


fused_corr_lookup.defvjp(_fused_fwd, _fused_bwd)
