"""Pallas TPU kernels (Mosaic) with CPU interpreter fallback.

``interpret_mode()`` decides whether ``pl.pallas_call`` runs the
interpreter (CPU tests) or compiles through Mosaic (TPU). The
``PVRAFT_PALLAS_INTERPRET`` env var overrides the backend-based default:
``0`` forces compiled mode — used by ``scripts/aot_readiness.py`` to
deviceless-compile the kernels against a TPU topology from a CPU host
(the backend there is cpu, but the target is tpu) — and ``1`` forces the
interpreter.

Contract (machine-checked): every ``pallas_call`` in this package passes
``interpret=interpret_mode()`` (kernelcheck rule GK006 — a hardcoded or
missing kwarg either bricks CPU tier-1 or silently benchmarks the
interpreter on TPU), registers ``kernel``-tagged ProgramSpecs in
``programs/catalog.py`` so the deviceless Mosaic compile gate sees it
(GK005), and models statically at its certified geometry — literal dims
or a ``KERNEL_BINDINGS`` row in ``analysis/kernels/model.py`` (GK000).
``python -m pvraft_tpu.analysis kernels`` is the gate.
"""

from __future__ import annotations

import os


def interpret_mode() -> bool:
    force = os.environ.get("PVRAFT_PALLAS_INTERPRET")
    if force is not None:
        if force not in ("0", "1"):
            # A typo like "true" silently forcing compiled mode would
            # surface as an opaque Mosaic lowering error on CPU hosts.
            raise ValueError(
                f"PVRAFT_PALLAS_INTERPRET must be '0' or '1', got {force!r}"
            )
        return force == "1"
    import jax

    return jax.default_backend() == "cpu"
