"""Truncated all-pairs correlation volume.

Functional replacement of the reference ``CorrBlock.init_module`` /
``get_knn_feature`` state machinery (``model/corr.py:31-45,75-100``). The
cache of per-point top-k correlation candidates becomes an explicit
``CorrState`` pytree threaded through the update loop — no module-state
mutation (which is also what made the reference DataParallel-hostile).

Memory notes (SURVEY.md §7 hard-part 3): the reference materializes both the
(B, N, N) correlation *and* a (B, N, N, 3) xyz expand (``corr.py:33``). We
gather xyz only after truncation, and optionally stream the N2 axis with a
running top-k (``corr_init`` with ``chunk``) so the N x N matrix is never
resident — the long-context ("ring attention"-style) path for 16k+ points.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from pvraft_tpu.analysis.contracts import shapecheck
from pvraft_tpu.ops.geometry import gather_neighbors


class CorrState(NamedTuple):
    """Per-pair correlation cache (reference ``corr.py:38-42``)."""

    corr: jnp.ndarray   # (B, N1, K) top-k correlation values, descending
    xyz: jnp.ndarray    # (B, N1, K, 3) positions of the top-k pc2 points


@shapecheck("B N D", "B M D", out="B N M", dtype="floating")
def corr_volume(fmap1: jnp.ndarray, fmap2: jnp.ndarray) -> jnp.ndarray:
    """Scaled all-pairs feature correlation.

    fmap1: (B, N, D), fmap2: (B, M, D) -> (B, N, M); dot products over the
    feature axis scaled by 1/sqrt(D) (``model/corr.py:95-100``).
    """
    d = fmap1.shape[-1]
    # Accumulate in float32 even when fmaps are bfloat16 (MXU-native mode).
    out = jnp.einsum(
        "bnd,bmd->bnm", fmap1, fmap2, preferred_element_type=jnp.float32
    )
    return out / jnp.sqrt(jnp.asarray(d, out.dtype))


def merge_topk_xyz(best_v, best_x, part_v, part_x, truncate_k: int):
    """Fold candidate (corr, xyz) blocks into a running top-k over the last
    value axis. Shared by the chunked scan below and the ring
    sequence-parallel path (``parallel/ring.py``)."""
    cand_v = jnp.concatenate([best_v, part_v], axis=-1)
    cand_x = jnp.concatenate([best_x, part_x], axis=2)
    new_v, sel = lax.top_k(cand_v, truncate_k)
    new_x = jnp.take_along_axis(cand_x, sel[..., None], axis=2)
    return new_v, new_x


@shapecheck("B N D", "B M D", "B M 3", None, None, None, "B M",
            out=("B N K", "B N K 3"))
def corr_init(
    fmap1: jnp.ndarray,
    fmap2: jnp.ndarray,
    xyz2: jnp.ndarray,
    truncate_k: int,
    chunk: Optional[int] = None,
    approx: bool = False,
    valid2: Optional[jnp.ndarray] = None,
) -> CorrState:
    """Build the truncated correlation cache (``model/corr.py:31-42``).

    fmap1: (B, N, D), fmap2: (B, M, D), xyz2: (B, M, 3).

    With ``chunk=None`` the full (B, N, M) volume is formed and truncated with
    one ``lax.top_k``. With an integer ``chunk`` the M axis is processed in
    slices under ``lax.scan`` while a running top-k of size K is maintained —
    peak memory O(N * (K + chunk)) instead of O(N * M).

    ``valid2`` (B, M) bool, True = real pc2 point: padding candidates are
    forced below every real correlation value before the truncation, so
    the selected top-k (values AND gathered xyz) is exactly the unpadded
    one whenever each scene has >= ``truncate_k`` real points (the serve
    engine enforces that). ``None`` (default) leaves the jaxpr untouched.
    """
    if truncate_k > fmap2.shape[1]:
        raise ValueError(
            f"truncate_k ({truncate_k}) must be <= the number of candidate "
            f"points N2 ({fmap2.shape[1]})"
        )
    if valid2 is not None and approx:
        raise ValueError(
            "valid2 masking is not supported with approx_topk: approx_max_k "
            "recall is ~0.95, so finfo.min padding candidates can leak into "
            "the selected top-k and break the padding-exactness guarantee "
            "the serve path is built on (use the exact top_k with masks)"
        )
    if approx and chunk is not None:
        # Checked before the size-based fallback so the config error does
        # not depend on the input size.
        raise ValueError(
            "approx_topk is not supported with corr_chunk: the chunked "
            "scan keeps an exact running top-k (use one or the other)"
        )
    if chunk is not None and chunk >= fmap2.shape[1]:
        chunk = None   # one chunk would cover everything: use the dense path
    if valid2 is not None and chunk is not None:
        # Checked AFTER the size fallback (unlike the approx+chunk config
        # error above): a training config's corr_chunk tuned for 16k+
        # points routinely exceeds a serve bucket, and the dense path it
        # degenerates to is exactly the one the serve masks support — a
        # masked predict must not fail to build over a chunk value that
        # would have been discarded anyway.
        raise ValueError(
            "valid2 masking is not supported with corr_chunk: the serve "
            "path uses the dense truncation (chunking exists for training "
            "at 16k+ points, beyond the serve buckets)"
        )
    if chunk is None:
        corr = corr_volume(fmap1, fmap2)
        if valid2 is not None:
            # finfo.min, not -inf: strictly below any real correlation (so
            # never selected while truncate_k <= n_real) without minting
            # non-finite values that could poison downstream arithmetic.
            corr = jnp.where(
                valid2[:, None, :], corr, jnp.finfo(corr.dtype).min)
        if approx:
            # TPU-native approximate top-k (recall ~0.95): substantially
            # cheaper than the sort-based exact path at N=8192, K=512.
            vals, idx = lax.approx_max_k(corr, truncate_k)
        else:
            vals, idx = lax.top_k(corr, truncate_k)
        return CorrState(corr=vals, xyz=gather_neighbors(xyz2, idx))

    b, m, d = fmap2.shape
    if m % chunk != 0:
        raise ValueError(f"chunk {chunk} must divide N2={m}")
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    n1 = fmap1.shape[1]
    neg = jnp.asarray(-jnp.inf, jnp.float32)

    fmap2_c = fmap2.reshape(b, m // chunk, chunk, d)
    xyz2_c = xyz2.reshape(b, m // chunk, chunk, 3)

    def step(carry, xs):
        best_v, best_x = carry
        f2, x2 = xs                                  # (B, chunk, D), (B, chunk, 3)
        part = jnp.einsum(
            "bnd,bcd->bnc", fmap1, f2, preferred_element_type=jnp.float32
        ) * scale                                    # (B, N, chunk)
        part_x = jnp.broadcast_to(x2[:, None], (b, n1, chunk, 3))
        return merge_topk_xyz(best_v, best_x, part, part_x, truncate_k), None

    init = (
        jnp.full((b, n1, truncate_k), neg, jnp.float32),
        jnp.zeros((b, n1, truncate_k, 3), xyz2.dtype),
    )
    (vals, xyz), _ = lax.scan(
        step, init, (jnp.swapaxes(fmap2_c, 0, 1), jnp.swapaxes(xyz2_c, 0, 1))
    )
    return CorrState(corr=vals, xyz=xyz)


@shapecheck(None, "B N K 3", out=("B N J", "B N J 3"))
def knn_lookup(state: CorrState, rel: jnp.ndarray, k: int,
               dense_vjp: bool = False):
    """Point-branch lookup: pick the k truncated candidates nearest to the
    current coordinate estimate (``model/corr.py:75-89``).

    rel: (B, N, K, 3) candidate positions relative to the current coords
    (precomputed by the caller and shared with the voxel branch). Returns:
      knn_corr (B, N, k) — their correlation values,
      rel_xyz  (B, N, k, 3) — their positions relative to the coords.

    ``dense_vjp`` replaces the two ``take_along_axis`` backwards (scatter-
    adds over the K candidate axis) with one shared one-hot matmul
    (``ops/scatter_free.py``); forward values and the default-path jaxpr
    are unchanged. Opt-in via ``ModelConfig.scatter_free_vjp``; only the
    XLA fallback path is affected (the fused Pallas lookup has its own
    VJP).
    """
    dist = jnp.sum(rel * rel, axis=-1)  # (B, N, K)
    _, nbr = lax.top_k(-dist, k)                      # (B, N, k)
    if dense_vjp:
        from pvraft_tpu.ops.scatter_free import take_pair_onehot

        return take_pair_onehot(state.corr, rel, nbr)
    knn_corr = jnp.take_along_axis(state.corr, nbr, axis=-1)
    rel_xyz = jnp.take_along_axis(rel, nbr[..., None], axis=2)
    return knn_corr, rel_xyz
