"""pvraft_tpu — a TPU-native scene-flow framework.

A brand-new JAX/XLA/Pallas implementation of the capabilities of PV-RAFT
(CVPR 2021, reference snapshot at /root/reference): RAFT-style iterative
scene-flow estimation on point clouds with a truncated point-voxel
correlation volume.

Design stance (TPU-first, not a port):
  * channel-last ``(B, N, C)`` layout — every 1x1 conv of the reference is a
    Dense layer, i.e. a single MXU matmul;
  * static shapes end-to-end (the reference's exact-N sampling,
    ``datasets/generic.py:101-110``, makes this natural);
  * the GRU refinement loop is a ``lax.scan`` with ``stop_gradient``
    replacing per-iteration ``.detach()`` (``model/RAFTSceneFlow.py:41``);
  * the correlation cache is an explicit functional ``CorrState`` pytree
    instead of module-state mutation (``model/corr.py:31-42``);
  * torch-scatter's voxel binning role (``model/corr.py:50,64-65``) is a
    Pallas TPU kernel with a pure-XLA fallback;
  * data parallelism is ``jax.sharding`` over a device mesh with XLA
    collectives, replacing ``nn.DataParallel`` (``tools/engine.py:63-64``).
"""

__version__ = "0.1.0"


def parse_int_list(text: str):
    """``"2048,4096,8192"`` -> ``(2048, 4096, 8192)``.

    Lives at the package root (which imports nothing) so CLIs can parse
    bucket/batch-size flags BEFORE importing anything jax-heavy — both
    serve entry points must pin the platform before jax commits to a
    backend."""
    return tuple(int(tok) for tok in text.split(",") if tok)
