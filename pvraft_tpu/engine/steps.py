"""Jitted train/eval step factories.

The training step fuses forward (scan over GRU iterations), sequence loss,
backward, and the optax update into one XLA program (the reference's
zero_grad/forward/loss/backward/step sequence, ``tools/engine.py:135-143``).
Data parallelism comes from input shardings: with the batch sharded over the
mesh ``data`` axis and params replicated, XLA inserts the gradient
all-reduce over ICI — the role ``nn.DataParallel`` plays in the reference
(``tools/engine.py:63-64``), minus the per-step replicate/scatter/gather.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from pvraft_tpu.engine.loss import compute_loss, sequence_loss
from pvraft_tpu.engine.metrics import epe_train, flow_metrics


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    gamma: float,
    num_iters: int,
    donate: bool = True,
) -> Callable:
    """Stage-1 training step: sequence loss over all iteration outputs
    (``tools/engine.py:135-143``)."""

    def step(params, opt_state, batch):
        def loss_fn(p):
            flows, _ = model.apply(p, batch["pc1"], batch["pc2"], num_iters)
            loss = sequence_loss(flows, batch["mask"], batch["flow"], gamma)
            return loss, flows

        (loss, flows), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        epe = epe_train(flows[-1], batch["mask"], batch["flow"])
        return params, opt_state, {"loss": loss, "epe": epe}

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def make_refine_train_step(
    model,
    tx: optax.GradientTransformation,
    num_iters: int,
    donate: bool = True,
) -> Callable:
    """Stage-2 step: plain masked-L1 on the single refined flow
    (``tools/engine_refine.py:142``). The backbone is frozen by the model's
    ``stop_gradient`` (plus the optimizer mask built in the Trainer)."""

    def step(params, opt_state, batch):
        def loss_fn(p):
            flow = model.apply(p, batch["pc1"], batch["pc2"], num_iters)
            return compute_loss(flow, batch["mask"], batch["flow"]), flow

        (loss, flow), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        epe = epe_train(flow, batch["mask"], batch["flow"])
        return params, opt_state, {"loss": loss, "epe": epe}

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def make_packed_train_step(
    model,
    tx: optax.GradientTransformation,
    gamma: float,
    num_iters: int,
    params,
    opt_state,
    donate: bool = True,
    refine: bool = False,
):
    """``make_train_step`` with the train state crossing the step boundary
    as ONE flat buffer instead of a ~300-leaf pytree.

    Motivation (hypothesis, decided by ``scripts/chain_bisect.py`` on
    hardware): the remote-TPU tunnel shows a large per-step overhead when
    the full train step's ~300-leaf output tree feeds the next call
    (BENCHMARKS.md) — small-program chains don't reproduce it, so one
    candidate cause is the chained executable/buffer bookkeeping, which
    this step minimizes by carrying params+opt_state as a single array.
    Cost: one concat/split pair per step (a few MB of on-device copies).
    Numerics are identical to the unpacked step: ``ravel_pytree`` casts
    the optax int32 step count through the promoted dtype and back
    losslessly for any realistic step count (< 2^24).

    Returns ``(step, flat0, unravel)``: ``step(flat, batch) ->
    (new_flat, metrics)``, ``flat0`` the packed initial state, and
    ``unravel(flat) -> (params, opt_state)`` for checkpointing.
    """
    from jax.flatten_util import ravel_pytree

    flat0, unravel = ravel_pytree((params, opt_state))

    def step(flat, batch):
        params, opt_state = unravel(flat)

        def loss_fn(p):
            if refine:
                flow = model.apply(p, batch["pc1"], batch["pc2"], num_iters)
                return compute_loss(flow, batch["mask"], batch["flow"]), flow
            flows, _ = model.apply(p, batch["pc1"], batch["pc2"], num_iters)
            loss = sequence_loss(flows, batch["mask"], batch["flow"], gamma)
            return loss, flows[-1]

        (loss, last), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        epe = epe_train(last, batch["mask"], batch["flow"])
        new_flat, _ = ravel_pytree((params, opt_state))
        return new_flat, {"loss": loss, "epe": epe}

    return (
        jax.jit(step, donate_argnums=(0,) if donate else ()),
        flat0,
        unravel,
    )


def make_eval_step(model, num_iters: int, gamma: float, refine: bool = False,
                   per_scene: bool = False):
    """Eval step returning loss + the full metric set
    (``tools/engine.py:197-234``, ``test.py:117-126``).

    ``per_scene=True`` returns every metric as a ``(B,)`` array (one value
    per scene) instead of a pooled batch mean — what keeps the reference's
    bs=1 running means exact when the standalone eval batches scenes
    across the device mesh (``test.py:128-142`` semantics at any batch)."""

    def step(params, batch):
        mask, gt = batch["mask"], batch["flow"]
        if refine:
            flow = model.apply(params, batch["pc1"], batch["pc2"], num_iters)
            if per_scene:
                loss = jax.vmap(
                    lambda f, m, g: compute_loss(f[None], m[None], g[None])
                )(flow, mask, gt)
            else:
                loss = compute_loss(flow, mask, gt)
        else:
            flows, _ = model.apply(params, batch["pc1"], batch["pc2"], num_iters)
            if per_scene:
                loss = jax.vmap(
                    lambda fl, m, g: sequence_loss(
                        fl[:, None], m[None], g[None], gamma),
                    in_axes=(1, 0, 0),
                )(flows, mask, gt)
            else:
                loss = sequence_loss(flows, mask, gt, gamma)
            flow = flows[-1]
        out = {"loss": loss}
        if per_scene:
            out.update(jax.vmap(
                lambda f, m, g: flow_metrics(f[None], m[None], g[None])
            )(flow, mask, gt))
        else:
            out.update(flow_metrics(flow, mask, gt))
        return out, flow

    return jax.jit(step)
