"""Jitted train/eval step factories.

The training step fuses forward (scan over GRU iterations), sequence loss,
backward, and the optax update into one XLA program (the reference's
zero_grad/forward/loss/backward/step sequence, ``tools/engine.py:135-143``).
Data parallelism comes from input shardings: with the batch sharded over the
mesh ``data`` axis and params replicated, XLA inserts the gradient
all-reduce over ICI — the role ``nn.DataParallel`` plays in the reference
(``tools/engine.py:63-64``), minus the per-step replicate/scatter/gather.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from pvraft_tpu.engine.loss import compute_loss, sequence_loss
from pvraft_tpu.engine.metrics import epe_train, flow_metrics


def maybe_cast_grads(grads, grad_dtype: Optional[str]):
    """The bf16-gradient lever (``TrainConfig.grad_dtype``): cast grads
    once right after ``value_and_grad`` — the dtype any cross-device
    all-reduce and downstream grad traffic run in — then restore the
    original dtype so the optimizer state stays float32. A no-op (and an
    unchanged jaxpr) for the float32 default.

    Public API: ``bench.py`` and the step profiler apply the same cast to
    their standalone steps so an A/B labeled ``grad_dtype`` measures
    exactly what the Trainer runs."""
    if grad_dtype in (None, "float32", "f32"):
        return grads
    dt = jnp.dtype(grad_dtype)
    return jax.tree_util.tree_map(
        lambda g: g.astype(dt).astype(g.dtype), grads
    )


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    gamma: float,
    num_iters: int,
    donate: bool = True,
    grad_dtype: Optional[str] = None,
    telemetry: bool = False,
) -> Callable:
    """Stage-1 training step: sequence loss over all iteration outputs
    (``tools/engine.py:135-143``).

    ``telemetry=True`` adds the in-jit numerics monitors
    (``obs/monitors.py``) as a ``metrics["telemetry"]`` leaf — a few
    fused reductions, no host callback; with the flag off the branch is
    Python-level dead code and the jaxpr stays byte-identical
    (test-gated, ``tests/test_obs.py``)."""

    # Named per variant so pjit compiles a distinguishable program:
    # profiles, deepcheck donation findings and XLA dumps say WHICH step.
    def train_step(params, opt_state, batch):
        def loss_fn(p):
            flows, _ = model.apply(p, batch["pc1"], batch["pc2"], num_iters)
            loss = sequence_loss(flows, batch["mask"], batch["flow"], gamma)
            return loss, flows

        (loss, flows), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        grads = maybe_cast_grads(grads, grad_dtype)
        updates, opt_state = tx.update(grads, opt_state, params)
        if telemetry:
            # params here are still PRE-update (the ratio's denominator).
            # Off path: both branches are Python-dead, the statement
            # sequence matches the pre-telemetry step exactly, and the
            # jaxpr stays byte-identical.
            from pvraft_tpu.obs.monitors import telemetry_leaves

            tel = telemetry_leaves(params, grads, updates, loss, flows)
        params = optax.apply_updates(params, updates)
        epe = epe_train(flows[-1], batch["mask"], batch["flow"])
        metrics = {"loss": loss, "epe": epe}
        if telemetry:
            metrics["telemetry"] = tel
        return params, opt_state, metrics

    return jax.jit(train_step, donate_argnums=(0, 1) if donate else ())


def make_refine_train_step(
    model,
    tx: optax.GradientTransformation,
    num_iters: int,
    donate: bool = True,
    grad_dtype: Optional[str] = None,
    telemetry: bool = False,
) -> Callable:
    """Stage-2 step: plain masked-L1 on the single refined flow
    (``tools/engine_refine.py:142``). The backbone is frozen by the model's
    ``stop_gradient`` (plus the optimizer mask built in the Trainer).

    ``telemetry`` as in :func:`make_train_step`; the refine model returns
    one flow, so there is no per-iteration ``delta_flow_norm`` leaf."""

    def refine_train_step(params, opt_state, batch):
        def loss_fn(p):
            flow = model.apply(p, batch["pc1"], batch["pc2"], num_iters)
            return compute_loss(flow, batch["mask"], batch["flow"]), flow

        (loss, flow), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        grads = maybe_cast_grads(grads, grad_dtype)
        updates, opt_state = tx.update(grads, opt_state, params)
        if telemetry:
            from pvraft_tpu.obs.monitors import telemetry_leaves

            tel = telemetry_leaves(params, grads, updates, loss, flows=None)
        params = optax.apply_updates(params, updates)
        epe = epe_train(flow, batch["mask"], batch["flow"])
        metrics = {"loss": loss, "epe": epe}
        if telemetry:
            metrics["telemetry"] = tel
        return params, opt_state, metrics

    return jax.jit(refine_train_step, donate_argnums=(0, 1) if donate else ())


def make_packed_train_step(
    model,
    tx: optax.GradientTransformation,
    gamma: float,
    num_iters: int,
    params,
    opt_state,
    donate: bool = True,
    refine: bool = False,
    grad_dtype: Optional[str] = None,
    telemetry: bool = False,
):
    """``make_train_step`` with the train state crossing the step boundary
    as ONE flat buffer instead of a ~300-leaf pytree.

    Motivation (hypothesis, decided by ``scripts/chain_bisect.py`` on
    hardware): the remote-TPU tunnel shows a large per-step overhead when
    the full train step's ~300-leaf output tree feeds the next call
    (BENCHMARKS.md) — small-program chains don't reproduce it, so one
    candidate cause is the chained executable/buffer bookkeeping, which
    this step minimizes by carrying params+opt_state as a single array.
    Cost: one concat/split pair per step (a few MB of on-device copies).
    Numerics are identical to the unpacked step: ``ravel_pytree`` casts
    the optax int32 step count through the promoted dtype and back
    losslessly for any realistic step count (< 2^24).

    Returns ``(step, flat0, unravel)``: ``step(flat, batch) ->
    (new_flat, metrics)``, ``flat0`` the packed initial state, and
    ``unravel(flat) -> (params, opt_state)`` for checkpointing.
    """
    step, flat0, unravel = _packed_step_fn(
        model, tx, gamma, num_iters, params, opt_state, refine, grad_dtype,
        telemetry,
    )
    return (
        jax.jit(step, donate_argnums=(0,) if donate else ()),
        flat0,
        unravel,
    )


def _packed_step_fn(model, tx, gamma, num_iters, params, opt_state, refine,
                    grad_dtype: Optional[str] = None,
                    telemetry: bool = False):
    """Unjitted packed-state step body shared by the single-step and the
    scan-fused multi-step factories. Returns ``(step, flat0, unravel)``."""
    from jax.flatten_util import ravel_pytree

    flat0, unravel = ravel_pytree((params, opt_state))

    def packed_train_step(flat, batch):
        params, opt_state = unravel(flat)

        def loss_fn(p):
            if refine:
                flow = model.apply(p, batch["pc1"], batch["pc2"], num_iters)
                return compute_loss(flow, batch["mask"], batch["flow"]), flow
            flows, _ = model.apply(p, batch["pc1"], batch["pc2"], num_iters)
            loss = sequence_loss(flows, batch["mask"], batch["flow"], gamma)
            return loss, flows[-1]

        (loss, last), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        grads = maybe_cast_grads(grads, grad_dtype)
        updates, opt_state = tx.update(grads, opt_state, params)
        if telemetry:
            # Packed-mode monitors: the loss aux carries only the LAST
            # flow (the (T, ...) stack never crosses the packed
            # boundary), so there is no delta_flow_norm leaf here; the
            # rest matches make_train_step's telemetry exactly.
            from pvraft_tpu.obs.monitors import telemetry_leaves

            tel = telemetry_leaves(params, grads, updates, loss, flows=None)
        params = optax.apply_updates(params, updates)
        epe = epe_train(last, batch["mask"], batch["flow"])
        metrics = {"loss": loss, "epe": epe}
        if telemetry:
            metrics["telemetry"] = tel
        new_flat, _ = ravel_pytree((params, opt_state))
        return new_flat, metrics

    return packed_train_step, flat0, unravel


def make_multistep_train_step(
    model,
    tx: optax.GradientTransformation,
    gamma: float,
    num_iters: int,
    params,
    opt_state,
    steps_per_dispatch: int,
    donate: bool = True,
    refine: bool = False,
    grad_dtype: Optional[str] = None,
    telemetry: bool = False,
):
    """K packed train steps fused into ONE compiled program via
    ``lax.scan`` — one dispatch runs K genuine fwd+bwd+adam steps.

    Motivation: on remote-dispatch tunnels the per-dispatch overhead of the
    full train-step executable is seconds (BENCHMARKS.md "chained full train
    step"), ~700x the measured device step time. Fusing K steps amortizes
    that overhead K-fold while remaining a true training loop: the packed
    state is the scan carry, so step i+1 consumes step i's updated params
    and optimizer state, exactly as K separate dispatches would. On a
    directly attached TPU the same fusion removes K-1 host dispatches per
    group (smaller but still real).

    The reference has no counterpart (its ``tools/engine.py:135-143`` loop
    is one optimizer step per Python iteration by construction); this is a
    TPU/XLA-native capability: deterministic control flow inside one XLA
    program.

    ``step(flat, batches) -> (new_flat, metrics)`` where every leaf of
    ``batches`` carries a leading ``steps_per_dispatch`` axis (K stacked
    loader batches) and each metrics leaf comes back with shape ``(K,)`` —
    per-step losses/EPEs, so logging stays per-step exact.

    Returns ``(step, flat0, unravel)`` like ``make_packed_train_step``.
    """
    if steps_per_dispatch < 1:
        raise ValueError("steps_per_dispatch must be >= 1")
    inner, flat0, unravel = _packed_step_fn(
        model, tx, gamma, num_iters, params, opt_state, refine, grad_dtype,
        telemetry,
    )

    def multistep_train_step(flat, batches):
        return jax.lax.scan(inner, flat, batches)

    return (
        jax.jit(multistep_train_step, donate_argnums=(0,) if donate else ()),
        flat0,
        unravel,
    )


def make_eval_step(model, num_iters: int, gamma: float, refine: bool = False,
                   per_scene: bool = False):
    """Eval step returning loss + the full metric set
    (``tools/engine.py:197-234``, ``test.py:117-126``).

    ``per_scene=True`` returns every metric as a ``(B,)`` array (one value
    per scene) instead of a pooled batch mean — what keeps the reference's
    bs=1 running means exact when the standalone eval batches scenes
    across the device mesh (``test.py:128-142`` semantics at any batch)."""

    def eval_step(params, batch):
        mask, gt = batch["mask"], batch["flow"]
        if refine:
            flow = model.apply(params, batch["pc1"], batch["pc2"], num_iters)
            if per_scene:
                loss = jax.vmap(
                    lambda f, m, g: compute_loss(f[None], m[None], g[None])
                )(flow, mask, gt)
            else:
                loss = compute_loss(flow, mask, gt)
        else:
            flows, _ = model.apply(params, batch["pc1"], batch["pc2"], num_iters)
            if per_scene:
                loss = jax.vmap(
                    lambda fl, m, g: sequence_loss(
                        fl[:, None], m[None], g[None], gamma),
                    in_axes=(1, 0, 0),
                )(flows, mask, gt)
            else:
                loss = sequence_loss(flows, mask, gt, gamma)
            flow = flows[-1]
        out = {"loss": loss}
        if per_scene:
            out.update(jax.vmap(
                lambda f, m, g: flow_metrics(f[None], m[None], g[None])
            )(flow, mask, gt))
        else:
            out.update(flow_metrics(flow, mask, gt))
        return out, flow

    return jax.jit(eval_step)
