"""Scene-flow metrics (equivalent of ``tools/metric.py``).

All metrics are masked jnp reductions with static shapes so they run on
device inside jit (the reference computes eval metrics on CPU via numpy,
``metric.py:59-63`` — including a deprecated ``np.float`` that breaks on
numpy>=1.24; not reproduced).

Definitions (``tools/metric.py:66-78``):
  EPE3D    = mean ||pred - gt||
  Acc3DS   = mean[ ||err|| < 0.05  or  rel < 0.05 ]
  Acc3DR   = mean[ ||err|| < 0.1   or  rel < 0.1  ]
  Outliers = mean[ ||err|| > 0.3   or  rel > 0.1  ]
  rel      = ||err|| / (||gt|| + 1e-4)
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp


def _masked_mean(x: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(x * m) / jnp.maximum(jnp.sum(m), 1.0)


def epe_train(
    est_flow: jnp.ndarray, mask: jnp.ndarray, gt_flow: jnp.ndarray
) -> jnp.ndarray:
    """Masked mean end-point error (``tools/metric.py:6-31``)."""
    if mask.ndim == 3:
        mask = mask[..., 0]
    m = (mask > 0).astype(est_flow.dtype)
    err = est_flow - gt_flow
    epe = jnp.sqrt(jnp.sum(err * err, axis=-1))
    return _masked_mean(epe, m)


def flow_metrics(
    est_flow: jnp.ndarray, mask: jnp.ndarray, gt_flow: jnp.ndarray
) -> Dict[str, jnp.ndarray]:
    """Full eval metric set (``tools/metric.py:34-80``)."""
    if mask.ndim == 3:
        mask = mask[..., 0]
    m = (mask > 0).astype(est_flow.dtype)
    err = est_flow - gt_flow
    l2 = jnp.sqrt(jnp.sum(err * err, axis=-1))
    gt_norm = jnp.sqrt(jnp.sum(gt_flow * gt_flow, axis=-1))
    rel = l2 / (gt_norm + 1e-4)
    return {
        "epe3d": _masked_mean(l2, m),
        "acc3d_strict": _masked_mean(
            jnp.logical_or(l2 < 0.05, rel < 0.05).astype(est_flow.dtype), m
        ),
        "acc3d_relax": _masked_mean(
            jnp.logical_or(l2 < 0.1, rel < 0.1).astype(est_flow.dtype), m
        ),
        "outlier": _masked_mean(
            jnp.logical_or(l2 > 0.3, rel > 0.1).astype(est_flow.dtype), m
        ),
    }
