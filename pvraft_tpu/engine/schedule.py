"""Learning-rate schedules.

The reference constructs ``CosineAnnealingLR(T_max=num_epochs *
len(train_dataset))`` but steps it once per *epoch* (``tools/engine.py:58,
168``), so the cosine argument only ever reaches ``num_epochs /
(num_epochs * dataset_len)`` — an effectively constant LR. ``parity`` mode
reproduces that behavior exactly; ``cosine`` is the corrected per-step
cosine decay (SURVEY.md §7 hard-part 7).
"""

from __future__ import annotations

import jax.numpy as jnp


def make_lr_schedule(
    kind: str,
    base_lr: float,
    num_epochs: int,
    steps_per_epoch: int,
    dataset_len: int,
):
    """Returns lr(step) usable as an optax schedule."""
    if kind == "parity":
        t_max = float(num_epochs * dataset_len)

        def schedule(step):
            epoch = step // max(1, steps_per_epoch)
            return base_lr * 0.5 * (1.0 + jnp.cos(jnp.pi * epoch / t_max))

        return schedule
    if kind == "cosine":
        total = max(1, num_epochs * steps_per_epoch)

        def schedule(step):
            frac = jnp.clip(step / total, 0.0, 1.0)
            return base_lr * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))

        return schedule
    if kind == "constant":
        return lambda step: base_lr
    raise ValueError(f"unknown lr schedule {kind!r}")
