from pvraft_tpu.engine.loss import compute_loss, sequence_loss
from pvraft_tpu.engine.metrics import epe_train, flow_metrics
from pvraft_tpu.engine.schedule import make_lr_schedule
from pvraft_tpu.engine.checkpoint import (
    import_torch_state_dict,
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from pvraft_tpu.engine.steps import (
    make_eval_step,
    make_refine_train_step,
    make_train_step,
)

__all__ = [
    "compute_loss",
    "sequence_loss",
    "epe_train",
    "flow_metrics",
    "make_lr_schedule",
    "import_torch_state_dict",
    "latest_checkpoint",
    "load_checkpoint",
    "save_checkpoint",
    "make_eval_step",
    "make_refine_train_step",
    "make_train_step",
]
