"""Checkpoint save/load.

Replaces ``tools/utils.py:6-29`` with two backends behind one API. Same
three name classes as the reference: ``last_checkpoint``, ``{epoch:03d}``
every ``checkpoint_interval`` epochs, and ``best_checkpoint`` on val
improvement; the payload carries ``epoch`` alongside the parameter/optimizer
pytrees like the reference's ``{'epoch', 'state_dict'}`` dict.

- ``msgpack`` (default): one flax-serialized file per checkpoint, atomic
  via tmp+rename. Zero extra dependencies, best for single-host runs.
- ``orbax``: one ``.orbax`` directory per checkpoint written by an
  orbax ``AsyncCheckpointer`` — the array snapshot is taken synchronously
  but persistence runs in a background thread, overlapping the next
  training epoch; on multi-host meshes orbax coordinates the per-process
  writes and commit barrier (SURVEY.md §5: "orbax checkpointing with
  save-interval + auto-resume").

Loads auto-detect the backend from the path (directory => orbax), so
``--weights``/``--resume`` work unchanged whichever backend wrote the file.

Also provides the torch<->jax converters so reference-published checkpoints
can be imported (and ours exported) for parity testing (SURVEY.md §5).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization

SUFFIX = ".msgpack"
ORBAX_SUFFIX = ".orbax"

_orbax_writer = None
# (tmp_dir, final_dir, extra_final_dirs) owed once the async write commits.
_orbax_pending: list = []
# Failed recoveries after which an epoch-unreadable debt is retired loudly
# instead of warning on every recovery forever (round-4 advisor).
_MAX_DEBT_KEEPS = 3


def _write(path: str, payload: Dict[str, Any]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.msgpack_serialize(payload))
    os.replace(tmp, path)


def _orbax():
    """Lazy singleton AsyncCheckpointer (spawns a persistence thread)."""
    global _orbax_writer
    if _orbax_writer is None:
        import orbax.checkpoint as ocp

        _orbax_writer = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _orbax_writer


def _swap_in(tmp: str, dst: str) -> None:
    """Replace directory ``dst`` with ``tmp`` without ever deleting the
    only copy: old dst is renamed aside, tmp renamed in, then the old one
    removed. A crash leaves either dst or dst+'.old' intact."""
    import shutil

    old = dst + ".old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(dst):
        os.replace(dst, old)
    os.replace(tmp, dst)
    if os.path.exists(old):
        shutil.rmtree(old)


def _promote_ckpt(tmp: str, dst: str) -> None:
    """``_swap_in`` plus epoch-sidecar maintenance. Ordering matters: the
    destination's old ``.epoch.json`` is removed BEFORE the swap and the
    tmp's moved in AFTER, so a crash anywhere between leaves the sidecar
    MISSING (readers fall back to a full restore) but never STALE — a
    stale epoch could misdirect debt delivery in ``_recover_leftover_tmp``."""
    epoch_sidecar = dst + ".epoch.json"
    if os.path.isfile(epoch_sidecar):
        os.unlink(epoch_sidecar)
    _swap_in(tmp, dst)
    if os.path.isfile(tmp + ".epoch.json"):
        os.replace(tmp + ".epoch.json", epoch_sidecar)


def _read_dst_epoch(dst: str):
    """Epoch of the promoted checkpoint at ``dst``. Cheap path: the
    ``.epoch.json`` sidecar written at save time. Fallback (sidecar
    missing — pre-sidecar checkpoints, or a crash inside ``_promote_ckpt``):
    one full orbax restore, which drags params+opt_state into host memory
    just to read an int — exactly what the sidecar exists to avoid."""
    import json

    try:
        with open(dst + ".epoch.json") as f:
            return int(json.load(f)["epoch"])
    except (OSError, ValueError, KeyError, TypeError):
        pass
    for _ in range(2):  # one retry absorbs transient read failures
        try:
            return int(_orbax().restore(os.path.abspath(dst))["epoch"])
        except Exception:
            continue
    return None


def _orbax_promote() -> None:
    """Swap committed tmp directories into their final names and copy them
    to the extra name classes (NNN/best). Caller must have settled the
    async writer first. Filesystem mutation is process-0-only: on a
    multi-host mesh every process calls save (orbax saves are collective)
    but only one may touch the shared directory names."""
    import shutil

    if jax.process_index() != 0:
        _orbax_pending.clear()
        return
    while _orbax_pending:
        tmp, dst, extras = _orbax_pending.pop(0)
        if not os.path.exists(tmp):
            continue  # already recovered by find_checkpoint
        _promote_ckpt(tmp, dst)
        _copy_extras(dst, extras)
        sidecar = tmp + ".extras.json"
        if os.path.isfile(sidecar):  # owed copies delivered; retire it
            os.unlink(sidecar)


def _copy_extras(dst: str, extras) -> None:
    """Copy checkpoint directory ``dst`` to each extra name (NNN/best).

    The intermediate name is ``.copytmp``, NOT ``.tmp``: recovery adopts
    ``.tmp`` directories as complete checkpoints (orbax's commit makes
    them so atomically), but ``shutil.copytree`` is not atomic — a
    half-written copy temp must never be mistakable for a checkpoint.
    ``_swap_in`` makes the final rename atomic and refreshes any stale
    pre-existing copy."""
    import shutil

    for extra in extras:
        ctmp = extra + ".copytmp"
        if os.path.exists(ctmp):
            shutil.rmtree(ctmp)
        shutil.copytree(dst, ctmp)
        _swap_in(ctmp, extra)


def _sync_hosts(tag: str) -> None:
    """Barrier so non-0 processes never observe mid-rename filesystem
    states (promotion/recovery is process-0-only)."""
    if jax.process_count() > 1:
        from pvraft_tpu import compat

        compat.sync_global_devices(tag)


def wait_for_saves() -> None:
    """Block until pending async (orbax) checkpoint writes are durable and
    visible under their final names. No-op for the msgpack backend. Call
    before process exit."""
    if _orbax_writer is not None:
        _orbax_writer.wait_until_finished()
        _orbax_promote()
        _sync_hosts("pvraft-ckpt-promote")


def _sidecar_debts(meta) -> list:
    """Normalize a sidecar payload to a list of ``{"epoch", "extras"}``
    debts (current shape: ``{"debts": [...]}``; legacy shapes accepted)."""
    if isinstance(meta, dict) and "debts" in meta:
        return [d for d in meta["debts"] if isinstance(d, dict)]
    if isinstance(meta, dict):
        return [{"epoch": meta.get("epoch"), "extras": meta.get("extras", [])}]
    if isinstance(meta, list):
        return [{"epoch": None, "extras": meta}]
    return []


def _recover_leftover_tmp(dst: str) -> None:
    """Promote a committed-but-unpromoted tmp directory left by a run that
    died before its deferred promote (orbax's own commit is an atomic
    rename, so an existing ``.tmp`` directory is always a complete
    checkpoint — and always newer than the promoted name next to it)."""
    import json
    import shutil

    tmp = dst + ".tmp"
    sidecar = tmp + ".extras.json"
    if jax.process_index() == 0:
        if os.path.isdir(tmp):
            _promote_ckpt(tmp, dst)
        # Re-create the NNN/best copies the dying run still owed (the
        # sidecar records them at save time; without it only
        # last_checkpoint would survive a crash between the async commit
        # and the deferred promote). Two crash shapes reach here: tmp
        # still present (death before promote — adopted above) and tmp
        # already swapped in (death mid-promote, before the extras
        # copies). Both leave dst holding the owed payload; the epoch
        # gate rejects the third shape — death before the async write
        # ever committed — where dst is an OLDER checkpoint that must not
        # be recorded under the owed NNN/best names.
        if os.path.isfile(sidecar):
            try:
                with open(sidecar) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = {}
            debts = _sidecar_debts(meta)
            unresolved, retired = [], 0
            if debts and os.path.isdir(dst):
                dst_epoch = _read_dst_epoch(dst)
                for debt in debts:
                    owed_epoch = debt.get("epoch")
                    extras = debt.get("extras", [])
                    if not extras:
                        continue
                    if owed_epoch is None or dst_epoch == owed_epoch:
                        _copy_extras(dst, extras)
                    elif dst_epoch is None:
                        # dst exists but its epoch could not be read
                        # (persistent restore failure): keep the debt so a
                        # LATER recovery can still deliver the owed copies
                        # — unlinking here would drop them silently. The
                        # next _orbax_write appends its own debt to this
                        # sidecar rather than clobbering it. The debt dies
                        # when dst is readable with a different epoch (the
                        # owed payload is genuinely gone) or after
                        # _MAX_DEBT_KEEPS failed recoveries (a permanently
                        # unreadable dst must not warn forever).
                        kept = int(debt.get("kept", 0)) + 1
                        if kept >= _MAX_DEBT_KEEPS:
                            retired += 1
                        else:
                            unresolved.append({**debt, "kept": kept})
                    # else: dst readable but a different epoch — the owed
                    # payload never committed (or was since replaced);
                    # the debt is undeliverable, retire it.
            if retired:
                import warnings

                warnings.warn(
                    f"checkpoint recovery: retiring {retired} debt(s) "
                    f"after {_MAX_DEBT_KEEPS} recoveries with an "
                    f"unreadable epoch at {dst} — the owed NNN/best "
                    f"copies will NOT be re-created; inspect {dst} "
                    f"manually if they matter")
            if unresolved:
                import warnings

                warnings.warn(
                    f"checkpoint recovery: could not read epoch from {dst}; "
                    f"keeping {len(unresolved)} unresolved debt(s) in "
                    f"{sidecar} for a later attempt")
                with open(sidecar, "w") as f:
                    json.dump({"debts": unresolved}, f)
            else:
                os.unlink(sidecar)
        old = dst + ".old"
        if os.path.isdir(old):
            if os.path.isdir(dst):
                # Crash between _swap_in's final rename and its rmtree:
                # dst is the newer copy; the aside-rename is stale.
                shutil.rmtree(old)
            else:
                # Crash between the aside-rename and tmp's rename with no
                # surviving tmp: the aside copy is the only checkpoint.
                os.replace(old, dst)
    _sync_hosts("pvraft-ckpt-recover")


def _orbax_write(path: str, payload: Dict[str, Any], extras=()) -> None:
    import glob
    import shutil

    import orbax.checkpoint as ocp

    # Never overwrite the live checkpoint in place: orbax's force=True
    # deletes the destination at save() but only commits the replacement
    # when the background write finishes — a crash in between would leave
    # no checkpoint at all. Write to a tmp name and rename after commit
    # (the previous epoch's write settles first; that wait is what makes
    # the async overlap one-epoch deep rather than unbounded). The extra
    # name classes (NNN/best) become host-side copies at promote time, so
    # each epoch issues exactly one serialization pass.
    _orbax().wait_until_finished()
    _orbax_promote()
    _recover_leftover_tmp(path)
    tmp = path + ".tmp"
    if jax.process_index() == 0:
        # A kill mid-background-write leaves orbax's own uncommitted temp
        # next to our target (tmp.orbax-checkpoint-tmp-*); clear them so
        # crashed runs don't accumulate multi-MB orphans.
        for orphan in sorted(glob.glob(tmp + ".orbax-checkpoint-tmp-*")):
            shutil.rmtree(orphan, ignore_errors=True)
    if jax.process_index() == 0:
        # Tiny epoch sidecar so recovery / resume can learn the epoch of a
        # promoted checkpoint without a full orbax restore of
        # params+opt_state into host memory (_read_dst_epoch). Travels
        # with the directory through _promote_ckpt.
        import json as _json

        with open(tmp + ".epoch.json", "w") as f:
            _json.dump({"epoch": int(payload["epoch"])}, f)
    if extras and jax.process_index() == 0:
        # Sidecar so a crash after the async commit but before promote can
        # still re-create the NNN/best copies from the adopted tmp
        # (_recover_leftover_tmp reads and removes it). The epoch lets
        # recovery verify dst actually holds the owed payload.
        import json

        # abspath: the recovering run may start from a different cwd; a
        # relative extras path would re-create NNN/best somewhere else.
        # Append to (never clobber) debts a failed recovery kept above.
        debts = []
        if os.path.isfile(tmp + ".extras.json"):
            try:
                with open(tmp + ".extras.json") as f:
                    debts = _sidecar_debts(json.load(f))
            except (OSError, ValueError):
                debts = []
        debts.append({"epoch": int(payload["epoch"]),
                      "extras": [os.path.abspath(e) for e in extras]})
        with open(tmp + ".extras.json", "w") as f:
            json.dump({"debts": debts}, f)
    _orbax().save(os.path.abspath(tmp), args=ocp.args.StandardSave(payload))
    _orbax_pending.append((tmp, path, list(extras)))


def save_checkpoint(
    ckpt_dir: str,
    params: Any,
    opt_state: Any,
    epoch: int,
    checkpoint_interval: int = 5,
    best: bool = False,
    backend: str = "msgpack",
) -> None:
    """Write last/NNN/best checkpoints (naming of ``tools/utils.py:7-17``)."""
    if backend not in ("msgpack", "orbax"):
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    os.makedirs(ckpt_dir, exist_ok=True)
    payload = {
        "epoch": epoch,
        "params": jax.tree_util.tree_map(np.asarray, params),
        "opt_state": serialization.to_state_dict(opt_state),
    }
    suffix = SUFFIX if backend == "msgpack" else ORBAX_SUFFIX
    names = ["last_checkpoint"]
    if checkpoint_interval and (epoch + 1) % checkpoint_interval == 0:
        names.append(f"{epoch:03d}")
    if best:
        names.append("best_checkpoint")
    paths = [os.path.join(ckpt_dir, n + suffix) for n in names]
    if backend == "msgpack":
        # Process-0-only on shared filesystems: every process calls save
        # (the payload is replicated), but concurrent truncating writes to
        # the same '<path>.tmp' can interleave one process's truncate with
        # another's rename, corrupting last_checkpoint. Mirror the orbax
        # path: one writer, then a barrier so no process proceeds past an
        # epoch boundary before the checkpoint is durable.
        if jax.process_index() == 0:
            for p in paths:
                _write(p, payload)
        if jax.process_count() > 1:
            # Without a shared filesystem, the process-0-only write means
            # every other host has no checkpoint and a later resume would
            # silently diverge (host 0 at epoch N, the rest from scratch).
            # Barrier FIRST so no process samples the filesystem before
            # process 0's writes complete (an allgather synchronizes the
            # exchange of values, not when each process sampled its value
            # — sampling pre-barrier is a TOCTOU race), THEN sample with a
            # short bounded retry for FS attribute-cache propagation, THEN
            # gather visibility so EVERY process raises together — a
            # single-process raise would leave the others blocking in the
            # next collective (a distributed hang, not a clean error).
            import time

            from pvraft_tpu import compat

            compat.sync_global_devices(
                f"pvraft-msgpack-written-{epoch}")
            seen = os.path.exists(paths[0])
            for _ in range(10):
                if seen:
                    break
                time.sleep(0.5)
                seen = os.path.exists(paths[0])
            visible = compat.process_allgather(np.asarray([seen]))
            if not bool(np.asarray(visible).all()):
                raise RuntimeError(
                    f"msgpack checkpoint {paths[0]} written by process 0 "
                    "is not visible on every process: multi-host msgpack "
                    "checkpoints require a shared exp_path; use a shared "
                    "filesystem or ckpt_backend='orbax'"
                )
    else:
        # orbax StandardSave takes arrays (incl. 0-d), not numpy scalars.
        # One serialization pass; extra names become copies at promote.
        payload = dict(payload, epoch=np.asarray(epoch, np.int32))
        _orbax_write(paths[0], payload, extras=paths[1:])


def _load_orbax(path: str, params_template: Any,
                opt_state_template: Any) -> Tuple[Any, Any, int]:
    import orbax.checkpoint as ocp

    if opt_state_template is None:
        # Eval-only load: orbax restore templates must match the full saved
        # structure, so restore untemplated and take what we need. (The
        # extra optimizer-state read is noise at this model's ~1 MB scale.)
        restored = _orbax().restore(os.path.abspath(path))
    else:
        tmpl = {
            "epoch": np.asarray(0, np.int32),
            "params": jax.tree_util.tree_map(np.asarray, params_template),
            "opt_state": serialization.to_state_dict(opt_state_template),
        }
        restored = _orbax().restore(
            os.path.abspath(path), args=ocp.args.StandardRestore(tmpl)
        )
    params = serialization.from_state_dict(params_template, restored["params"])
    opt_state = None
    if opt_state_template is not None:
        opt_state = serialization.from_state_dict(
            opt_state_template, restored["opt_state"]
        )
    return params, opt_state, int(restored["epoch"])


def load_checkpoint(
    path: str,
    params_template: Any,
    opt_state_template: Any = None,
) -> Tuple[Any, Any, int]:
    """Restore (params, opt_state, epoch). ``opt_state_template=None`` skips
    optimizer state (the reference's eval-only load, ``test.py:101-106``).
    The backend is detected from the path: orbax checkpoints are
    ``.orbax`` directories, msgpack ones are files."""
    # A pending async save may still own this very path — settle writes
    # before looking at the filesystem (no-op without orbax).
    wait_for_saves()
    if path.endswith(ORBAX_SUFFIX) or os.path.isdir(path):
        _recover_leftover_tmp(path)  # --weights on a crashed run's dir
        return _load_orbax(path, params_template, opt_state_template)
    with open(path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    params = serialization.from_state_dict(params_template, payload["params"])
    opt_state = None
    if opt_state_template is not None:
        opt_state = serialization.from_state_dict(
            opt_state_template, payload["opt_state"]
        )
    return params, opt_state, int(payload["epoch"])


def load_payload(path: str) -> Dict[str, Any]:
    """Template-free read of a checkpoint written by either backend:
    ``{"epoch", "params", "opt_state"}`` with numpy leaves (``opt_state``
    in flax state-dict form). Used by tooling that doesn't hold a model
    (e.g. ``scripts/export_checkpoint.py``)."""
    wait_for_saves()
    if path.endswith(ORBAX_SUFFIX) or os.path.isdir(path):
        _recover_leftover_tmp(path)
        return dict(_orbax().restore(os.path.abspath(path)))
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def load_params(path: str) -> Tuple[Dict[str, Any], int]:
    """Parameters-only load for tooling that holds no optimizer: returns
    ``(variables, epoch)`` where ``variables`` is the flax variables dict
    (``{"params": tree}``) ready for ``model.apply``, whichever backend
    wrote the checkpoint. Accepts both payload shapes in the wild: the
    trainer saves the full variables dict; converters may hold the bare
    inner tree. A payload without an ``epoch`` key yields ``-1`` — an
    explicit "unknown" sentinel, deliberately NOT the pre-refactor fake
    epoch ``0`` (indistinguishable from a real first epoch). Used by the
    serve engine and ``scripts/export_checkpoint.py``."""
    payload = load_payload(path)
    tree = payload["params"]
    if set(tree.keys()) != {"params"}:
        tree = {"params": tree}
    return tree, int(payload.get("epoch", -1))


def find_checkpoint(ckpt_dir: str, name: str) -> Optional[str]:
    """Path of checkpoint ``name`` (e.g. ``best_checkpoint``) under either
    backend's naming, newest first if both exist. Settles pending async
    writes and adopts a committed tmp directory a previous run left
    unpromoted, so resume never silently loses the newest checkpoint."""
    wait_for_saves()
    _recover_leftover_tmp(os.path.join(ckpt_dir, name + ORBAX_SUFFIX))
    cands = [
        p for p in (os.path.join(ckpt_dir, name + SUFFIX),
                    os.path.join(ckpt_dir, name + ORBAX_SUFFIX))
        if os.path.exists(p)
    ]
    if not cands:
        return None
    return max(cands, key=os.path.getmtime)


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    return find_checkpoint(ckpt_dir, "last_checkpoint")


# ---------------------------------------------------------------------------
# torch -> jax parameter import (for reference-published checkpoints).
# ---------------------------------------------------------------------------

_REFINE_HEAD_KEYS = ("ref_conv1", "ref_conv2", "ref_conv3", "fc")


def load_torch_checkpoint(
    path: str, refine: bool = False
) -> Tuple[Dict[str, Any], int]:
    """Read a reference ``.params`` file (torch pickle of
    ``{'epoch', 'state_dict'}``, ``tools/utils.py:14-17``) and convert the
    state dict into this framework's param tree. Returns (tree, epoch).

    ``refine=True`` reshapes an ``RSF_refine`` checkpoint into the
    ``PVRaftRefine`` layout (stage-1 modules under ``backbone``, the
    refine head at top level). DataParallel-era ``module.``-prefixed keys
    are accepted (the reference unwraps them on save,
    ``tools/utils.py:19-28``, but published files may predate that)."""
    import torch

    payload = torch.load(path, map_location="cpu", weights_only=True)
    state_dict = payload.get("state_dict", payload)
    epoch = int(payload.get("epoch", -1)) if isinstance(payload, dict) else -1
    as_numpy = {
        (k[len("module."):] if k.startswith("module.") else k): v.numpy()
        for k, v in state_dict.items()
    }
    tree = import_torch_state_dict(as_numpy)
    if refine:
        backbone = {k: v for k, v in tree.items() if k not in _REFINE_HEAD_KEYS}
        head = {k: v for k, v in tree.items() if k in _REFINE_HEAD_KEYS}
        tree = {"backbone": backbone, **head}
    return tree, epoch


def _split_torch_key(key: str):
    # e.g. "feature_extractor.feat_conv1.fc1.weight"
    return key.split(".")


_ENCODER_CONV = {"feat_conv1": "conv1", "feat_conv2": "conv2", "feat_conv3": "conv3"}
_REFINE_CONV = {"ref_conv1": "ref_conv1", "ref_conv2": "ref_conv2", "ref_conv3": "ref_conv3"}


def _convert_tensor(path, t: np.ndarray) -> Tuple[str, np.ndarray]:
    """Map one torch parameter to (flax leaf name, transposed array).

    torch Conv1d/Conv2d 1x1 weights are (out, in, 1[, 1]) -> Dense kernels
    (in, out); GroupNorm weight/bias -> scale/bias; PReLU weight stays.
    """
    leaf = path[-1]
    if leaf == "weight":
        if t.ndim >= 3:           # 1x1 convs
            return "kernel", t.reshape(t.shape[0], t.shape[1]).T
        if t.ndim == 2:           # Linear
            return "kernel", t.T
        return "scale", t          # norm weight
    if leaf == "bias":
        return "bias", t
    raise ValueError(f"unhandled torch param {'.'.join(path)}")


def import_torch_state_dict(state_dict: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Convert a reference ``RSF`` state_dict (numpy-valued) into this
    framework's param-tree layout.

    Key mapping (reference module tree -> pvraft_tpu module tree):
      feature_extractor.feat_convN.*   -> feature_extractor.convN.*
      context_extractor.feat_convN.*   -> context_extractor.convN.*
      corr_block.out_conv.{0,1,2,3}    -> update_iter.corr_lookup.{out_conv1,out_gn,out_prelu,out_conv2}
      corr_block.knn_conv.{0,1,2}      -> update_iter.corr_lookup.{knn_conv,knn_gn,knn_prelu}
      corr_block.knn_out               -> update_iter.corr_lookup.knn_out
      update_block.*                   -> update_iter.update_block.*
      refine_block.*                   -> refine head (stage 2)
    GroupNorm weights inside SetConv keep their gn1/gn2/gn3 names; fc1-3
    likewise. PReLU single weights map to {name}.alpha.
    """
    out: Dict[str, Any] = {}

    def put(path, name, value):
        node = out
        for p in path:
            node = node.setdefault(p, {})
        node[name] = value

    seq_maps = {
        "out_conv": {"0": ("out_conv1", "dense"), "1": ("out_gn", "gn"),
                     "2": ("out_prelu", "prelu"), "3": ("out_conv2", "dense")},
        "knn_conv": {"0": ("knn_conv", "dense"), "1": ("knn_gn", "gn"),
                     "2": ("knn_prelu", "prelu")},
        "out_conv_head": {"0": ("out_conv1", "dense"), "2": ("out_conv2", "dense")},
    }

    for key, t in state_dict.items():
        t = np.asarray(t)
        parts = _split_torch_key(key)
        top = parts[0]
        if top in ("feature_extractor", "context_extractor"):
            conv = _ENCODER_CONV[parts[1]]
            name, arr = _convert_tensor(parts, t)
            put([top, conv, parts[2]], name, arr)
        elif top == "corr_block":
            block = parts[1]
            if block in ("out_conv", "knn_conv"):
                tgt, kind = seq_maps[block][parts[2]]
                if kind == "prelu":
                    put(["update_iter", "corr_lookup", tgt], "alpha", t.reshape(-1))
                else:
                    name, arr = _convert_tensor(parts, t)
                    put(["update_iter", "corr_lookup", tgt], name, arr)
            elif block == "knn_out":
                name, arr = _convert_tensor(parts, t)
                put(["update_iter", "corr_lookup", "knn_out"], name, arr)
            else:
                raise ValueError(f"unknown corr_block child {key}")
        elif top == "update_block":
            sub = parts[1]
            if sub == "motion_encoder":
                name, arr = _convert_tensor(parts, t)
                put(["update_iter", "update_block", "motion_encoder", parts[2]], name, arr)
            elif sub == "gru":
                name, arr = _convert_tensor(parts, t)
                put(["update_iter", "update_block", "gru", parts[2]], name, arr)
            elif sub == "flow_head":
                tail = parts[2]
                if tail == "conv1":
                    name, arr = _convert_tensor(parts, t)
                    put(["update_iter", "update_block", "flow_head", "conv1"], name, arr)
                elif tail == "setconv":
                    name, arr = _convert_tensor(parts, t)
                    put(["update_iter", "update_block", "flow_head", "setconv", parts[3]], name, arr)
                elif tail == "out_conv":
                    tgt, _ = seq_maps["out_conv_head"][parts[3]]
                    name, arr = _convert_tensor(parts, t)
                    put(["update_iter", "update_block", "flow_head", tgt], name, arr)
                else:
                    raise ValueError(f"unknown flow_head child {key}")
            else:
                raise ValueError(f"unknown update_block child {key}")
        elif top == "refine_block":
            sub = parts[1]
            if sub in _REFINE_CONV:
                name, arr = _convert_tensor(parts, t)
                put([_REFINE_CONV[sub], parts[2]], name, arr)
            elif sub == "fc":
                name, arr = _convert_tensor(parts, t)
                put(["fc"], name, arr)
            else:
                raise ValueError(f"unknown refine_block child {key}")
        else:
            raise ValueError(f"unknown top-level module {key}")
    return out


# ---------------------------------------------------------------- export ----

_SETCONV_KIND = {"fc1": "conv2d", "fc2": "conv1d", "fc3": "conv1d",
                 "gn1": "gn", "gn2": "gn", "gn3": "gn"}


def _to_torch_leaves(kind: str, leaves: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Invert :func:`_convert_tensor` for one torch module of known kind."""
    out: Dict[str, np.ndarray] = {}
    if kind in ("conv1d", "conv2d"):
        k = np.asarray(leaves["kernel"]).T          # (in,out) -> (out,in)
        out["weight"] = k[:, :, None] if kind == "conv1d" else k[:, :, None, None]
        if "bias" in leaves:
            out["bias"] = np.asarray(leaves["bias"])
    elif kind == "linear":
        out["weight"] = np.asarray(leaves["kernel"]).T
        if "bias" in leaves:
            out["bias"] = np.asarray(leaves["bias"])
    elif kind == "gn":
        out["weight"] = np.asarray(leaves["scale"])
        out["bias"] = np.asarray(leaves["bias"])
    elif kind == "prelu":
        out["weight"] = np.asarray(leaves["alpha"]).reshape(-1)
    else:
        raise ValueError(f"unknown module kind {kind}")
    return out


def export_torch_state_dict(
    tree: Dict[str, Any], refine: bool = False
) -> Dict[str, np.ndarray]:
    """Inverse of :func:`import_torch_state_dict`: convert this framework's
    param tree (the ``{"params": ...}`` inner dict) into a state dict the
    reference models load with ``strict=True`` — train here, evaluate in
    the reference (``model/RAFTSceneFlow.py`` / ``RAFTSceneFlowRefine.py``).
    ``refine=True`` expects the ``PVRaftRefine`` layout (stage-1 modules
    under ``backbone``, head at top level) and emits ``refine_block.*``.

    Conv dimensionality per reference module: ``SetConv.fc1`` and
    ``corr_block.knn_conv.0`` are Conv2d (``model/flot/gconv.py:26``,
    ``model/corr.py:23``); every other conv is a 1x1 Conv1d.

    NB the module mapping is intentionally written out a second time here
    rather than shared with the importer's parser: the two directions are
    kept honest by ``tests/test_reference_parity.py`` (strict=True load +
    import(export(x)) == x), which fails on any one-sided drift.
    """
    sd: Dict[str, np.ndarray] = {}

    def emit(prefix, kind, leaves):
        for nm, v in _to_torch_leaves(kind, leaves).items():
            sd[f"{prefix}.{nm}"] = v

    def emit_setconv(prefix, node):
        for sub, leaves in node.items():
            emit(f"{prefix}.{sub}", _SETCONV_KIND[sub], leaves)

    backbone = tree["backbone"] if refine else tree
    for enc in ("feature_extractor", "context_extractor"):
        for theirs, ours in _ENCODER_CONV.items():
            emit_setconv(f"{enc}.{theirs}", backbone[enc][ours])
    cl = backbone["update_iter"]["corr_lookup"]
    emit("corr_block.out_conv.0", "conv1d", cl["out_conv1"])
    emit("corr_block.out_conv.1", "gn", cl["out_gn"])
    emit("corr_block.out_conv.2", "prelu", cl["out_prelu"])
    emit("corr_block.out_conv.3", "conv1d", cl["out_conv2"])
    emit("corr_block.knn_conv.0", "conv2d", cl["knn_conv"])
    emit("corr_block.knn_conv.1", "gn", cl["knn_gn"])
    emit("corr_block.knn_conv.2", "prelu", cl["knn_prelu"])
    emit("corr_block.knn_out", "conv1d", cl["knn_out"])
    ub = backbone["update_iter"]["update_block"]
    for nm in ("conv_corr", "conv_flow", "conv"):
        emit(f"update_block.motion_encoder.{nm}", "conv1d",
             ub["motion_encoder"][nm])
    for nm in ("convz", "convr", "convq"):
        emit(f"update_block.gru.{nm}", "conv1d", ub["gru"][nm])
    fh = ub["flow_head"]
    emit("update_block.flow_head.conv1", "conv1d", fh["conv1"])
    emit_setconv("update_block.flow_head.setconv", fh["setconv"])
    emit("update_block.flow_head.out_conv.0", "conv1d", fh["out_conv1"])
    emit("update_block.flow_head.out_conv.2", "conv1d", fh["out_conv2"])
    if refine:
        for theirs, ours in _REFINE_CONV.items():
            emit_setconv(f"refine_block.{theirs}", tree[ours])
        emit("refine_block.fc", "linear", tree["fc"])
    return sd
