"""Training / evaluation engine.

Equivalent of the reference ``Trainer`` / ``RefineTrainer``
(``tools/engine.py:23-274``, ``tools/engine_refine.py:23-275``), rebuilt
around jitted steps and a device mesh:

  * datasets + prefetching loaders (train shuffled & drop_last, val/test
    bs=1 — ``tools/engine.py:43-48``);
  * Adam lr=1e-3 with the ``parity`` near-constant cosine quirk by default
    (``tools/engine.py:57-58,168``; see ``engine/schedule.py``);
  * per-epoch: train -> val at 32 GRU iters (``engine.py:197-198``), best-EPE
    checkpointing (``engine.py:247-250``), final test reloads the best
    checkpoint (``engine.py:191``);
  * stage 2 (refine): stage-1 weights imported non-strictly
    (``engine_refine.py:110``), backbone frozen via the model's
    ``stop_gradient`` AND an optax mask (the reference's module-attribute
    ``requires_grad=False`` froze nothing — ``engine_refine.py:51-54`` —
    freezing actually came from forward-side ``no_grad``; here both
    mechanisms are real), val at ``iters`` not 32
    (``engine_refine.py:199``);
  * TensorBoard scalars use the reference tag names
    (``engine.py:149-158,209-234``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pvraft_tpu.config import Config
from pvraft_tpu.data import FT3D, KITTI, PrefetchLoader, SyntheticDataset
from pvraft_tpu.data.loader import device_prefetch
from pvraft_tpu.engine.checkpoint import (
    find_checkpoint,
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
    wait_for_saves,
)
from pvraft_tpu.engine.schedule import make_lr_schedule
from pvraft_tpu.engine.steps import (
    make_eval_step,
    make_multistep_train_step,
    make_packed_train_step,
    make_refine_train_step,
    make_train_step,
)
from pvraft_tpu.models import PVRaft, PVRaftRefine
from pvraft_tpu.obs import DivergenceDetector, RunTelemetry, dump_snapshot
from pvraft_tpu.obs.device_memory import sample_device_memory
from pvraft_tpu.obs.divergence import DivergenceHalt
from pvraft_tpu.obs.retrace import RetraceWatchdog, args_signature
from pvraft_tpu.parallel.mesh import (
    batch_contract,
    device_batch,
    eval_scene_shard,
    make_mesh,
    replicate,
)
from pvraft_tpu.profiling import StepTimer, trace_context
from pvraft_tpu.rng import derive


def build_datasets(cfg: Config):
    d = cfg.data
    if d.dataset == "synthetic":
        mk = lambda seed: SyntheticDataset(
            size=d.synthetic_size, nb_points=d.max_points, noise=0.01,
            seed=seed, n_objects=d.synthetic_objects,
        )
        return mk(0), mk(1), mk(2)
    if d.dataset == "FT3D":
        return (
            FT3D(d.root, d.max_points, "train", strict_sizes=d.strict_sizes),
            FT3D(d.root, d.max_points, "val", strict_sizes=d.strict_sizes),
            FT3D(d.root, d.max_points, "test", strict_sizes=d.strict_sizes),
        )
    if d.dataset == "KITTI":
        # Eval-only, like the reference (tools/engine.py:40-41).
        raise NotImplementedError("KITTI is eval-only; use Evaluator/test.py")
    raise ValueError(f"unknown dataset {d.dataset!r}")


def _refine_mask(params) -> Any:
    """optax mask: train only the refine head (everything outside
    ``backbone``)."""
    def mark(path, _):
        return not any(
            getattr(k, "key", None) == "backbone" for k in path
        )
    return jax.tree_util.tree_map_with_path(mark, params)


class Trainer:
    def __init__(self, cfg: Config, mesh=None):
        self.cfg = cfg
        if cfg.parallel.steps_per_dispatch > 1 and jax.process_count() > 1:
            # The fused mode stacks K device batches with an EAGER
            # jnp.stack (training(), below); on multi-host meshes those are
            # non-fully-addressable global arrays and eager ops on them
            # raise mid-epoch in multi-process JAX. Fail at construction
            # with the fix in hand instead.
            raise ValueError(
                "parallel.steps_per_dispatch > 1 is single-process only "
                "(the fused mode stacks sharded device batches eagerly, "
                "which raises on non-fully-addressable arrays in "
                "multi-process JAX); set steps_per_dispatch=1 on "
                "multi-host meshes"
            )
        self.mesh = mesh if mesh is not None else make_mesh(n_seq=1)
        # One sink for everything the run reports: the pvraft_events/v1
        # JSONL (process 0 only), TensorBoard scalars, and the text log
        # all consume the same event stream (pvraft_tpu/obs/events.py).
        self.telemetry = RunTelemetry(cfg.exp_path, "Train", cfg.data.dataset)
        self.log = self.telemetry.log
        self.tb = self.telemetry.tb
        self.telemetry.emit_header(cfg, mode="train")
        # Divergence detection + crash snapshots (TrainConfig.telemetry).
        # Snapshots need host copies of the batch AND the pre-step train
        # state; on multi-process meshes the local host batch is only this
        # process's slice and np.asarray on the global batch raises, so
        # snapshot capture is single-process only (detection and the
        # in-jit monitors still run everywhere).
        self.detector = (
            DivergenceDetector(cfg.train.divergence_window,
                               cfg.train.divergence_zscore)
            if cfg.train.telemetry else None
        )
        self.snap_dir = os.path.join(cfg.exp_path, "snapshots")
        self.snapshots_taken = 0
        # Divergence events emitted so far: once state is corrupt every
        # later step re-trips the sentinel, and a 100k-step run must not
        # flood the event log with 100k identical records — after the cap
        # the stream is muted (one notice), the FIRST trips stay visible.
        self.trips_emitted = 0
        self._snap_capable = jax.process_count() == 1
        if cfg.train.telemetry and not self._snap_capable:
            self.log.info(
                "telemetry: divergence snapshots disabled on multi-process "
                "runs (the offending global batch is not host-addressable); "
                "monitors + detection stay on"
            )
        if cfg.train.telemetry and cfg.parallel.steps_per_dispatch > 1:
            self.log.info(
                "telemetry: divergence snapshots disabled with "
                "steps_per_dispatch > 1 (per-step pre-states never exist "
                "outside the fused scan); monitors + detection stay on at "
                "dispatch granularity"
            )
        self.best_epe = float("inf")
        self.begin_epoch = 0
        self.step_count = 0
        # Cost-surface honesty block (ISSUE 14): epoch_summary reports
        # measured step time against the committed inventory's
        # flagship-geometry prediction. False = not yet loaded; None =
        # unavailable (missing/stale artifact — training never fails
        # over an observability lookup). Host-side only: the jitted
        # step programs (and the telemetry-off jaxpr guarantee) are
        # untouched.
        self._cost_surface: Any = False

        self.train_ds, self.val_ds, self.test_ds = build_datasets(cfg)
        # batch_size is PER-DEVICE (the reference's DataParallel splits its
        # global bs=2 across 2 GPUs, tools/engine.py:63-64; here each chip
        # of the mesh data axis gets cfg.train.batch_size samples).
        # Multi-host: each process loads only the slice of the global batch
        # its local devices consume (PrefetchLoader shard + the
        # make_array_from_process_local_data path in parallel/mesh.py);
        # val/test loaders are scene-sharded per process too when the
        # counts divide evenly (see _eval_shard below), else they feed
        # identical data on every process and replication stays exact.
        # The global/local split itself is mesh.batch_contract — the one
        # declaration of the per-host batch relationship (GS005).
        n_data = self.mesh.shape["data"]
        n_proc = jax.process_count()
        self.global_batch, self.local_batch = batch_contract(
            cfg.train.batch_size, self.mesh)
        self.log.info(
            f"mesh {dict(self.mesh.shape)}: per-device batch "
            f"{cfg.train.batch_size} -> global batch {self.global_batch}"
            + (f" ({self.local_batch}/process x {n_proc})" if n_proc > 1 else "")
        )
        if self.global_batch > len(self.train_ds):
            raise ValueError(
                f"global batch {self.global_batch} "
                f"(= {cfg.train.batch_size}/device x {n_data} devices) "
                f"exceeds dataset size {len(self.train_ds)}; use a smaller "
                f"mesh or per-device batch"
            )
        self.train_loader = PrefetchLoader(
            self.train_ds,
            self.local_batch,
            shuffle=True,
            drop_last=True,
            num_workers=cfg.data.num_workers,
            seed=cfg.train.seed,
            native=cfg.data.native_loader,
            shard=(jax.process_index(), n_proc),
        )
        # Per-epoch val/test parallelize across the mesh data axis:
        # eval_batch scenes per step with per-scene metrics, so the means
        # stay exactly the bs=1 protocol's (tools/engine.py:197-198 runs
        # one replicated scene at a time — 8 chips doing 1 chip's work in
        # the loop that dominates epoch wall-clock on FT3D's 2,000-scene
        # val; the sharded loop is the same protocol, just parallel).
        eb = cfg.train.eval_batch
        self.eval_batch = max(1, n_data if eb <= 0 else eb)
        # Multi-host: also split the SCENES across processes — but only
        # when every per-process step is a full eval_batch (scene count
        # divisible by eval_batch * process_count). That keeps all ranks
        # in collective lockstep with no partial tail, whose per-process-
        # distinct rows would be assembled under a "replicated" sharding
        # and silently diverge. When it doesn't divide (e.g. KITTI's 142
        # scenes), every process feeds the same scenes and the mean*count
        # accumulation stays exact — redundant compute, never wrong.
        self._val_shard = eval_scene_shard(
            len(self.val_ds), self.eval_batch, self.mesh)
        self._test_shard = eval_scene_shard(
            len(self.test_ds), self.eval_batch, self.mesh)
        self.val_loader = PrefetchLoader(
            self.val_ds, self.eval_batch, drop_last=False,
            num_workers=min(2, cfg.data.num_workers),
            shard=self._val_shard,
        )
        self.test_loader = PrefetchLoader(
            self.test_ds, self.eval_batch, drop_last=False,
            num_workers=min(2, cfg.data.num_workers),
            shard=self._test_shard,
        )

        refine = cfg.train.refine
        self.model = (PVRaftRefine if refine else PVRaft)(
            cfg.model, mesh=self.mesh if cfg.model.seq_shard else None
        )
        rng = derive(cfg.train.seed, "model.init")
        sample = self._device_batch(next(iter(self.train_loader.epoch(0))))
        self.params = self.model.init(
            rng, sample["pc1"], sample["pc2"], cfg.train.iters
        )

        steps_per_epoch = max(1, len(self.train_loader))
        schedule = make_lr_schedule(
            cfg.train.lr_schedule,
            cfg.train.lr,
            cfg.train.num_epochs,
            steps_per_epoch,
            len(self.train_ds),
        )
        tx = optax.adam(schedule)
        if refine:
            tx = optax.masked(tx, _refine_mask(self.params))
        self.tx = tx
        self.opt_state = tx.init(self.params)
        self.params = replicate(self.params, self.mesh)
        self.opt_state = replicate(self.opt_state, self.mesh)

        if refine:
            self.train_step = make_refine_train_step(
                self.model, tx, cfg.train.iters, donate=cfg.parallel.donate,
                grad_dtype=cfg.train.grad_dtype,
                telemetry=cfg.train.telemetry,
            )
            # Refine trains and evals at args.iters (engine_refine.py:199).
            self.eval_iters = cfg.train.iters
        else:
            self.train_step = make_train_step(
                self.model, tx, cfg.train.gamma, cfg.train.iters,
                donate=cfg.parallel.donate,
                grad_dtype=cfg.train.grad_dtype,
                telemetry=cfg.train.telemetry,
            )
            # Stage-1 val/test run 32 iters (engine.py:197-198).
            self.eval_iters = cfg.train.eval_iters
        self.eval_step = make_eval_step(
            self.model, self.eval_iters, cfg.train.gamma, refine=refine,
            per_scene=True,
        )
        # Packed-state mode: the train loop carries one flat buffer instead
        # of the ~300-leaf (params, opt_state) tree; unpacked back into
        # self.params at epoch end so eval/checkpointing are unchanged.
        # Tradeoff: flat + unpacked trees are both device-resident (~2x the
        # train state; ~7 MB for the flagship model — dwarfed by
        # activations, so not offloaded).
        self.packed = cfg.parallel.packed_state
        if cfg.parallel.host_roundtrip and jax.process_count() > 1:
            # The per-step np.asarray(self.flat) requires the whole flat
            # buffer to be process-addressable; on a multi-host mesh it is
            # not, and the failure would be an opaque mid-epoch error. The
            # flag only makes sense on single-host remote-dispatch tunnels.
            raise ValueError(
                "parallel.host_roundtrip is single-host only (it round-trips "
                "the full train state through this process's host memory); "
                "disable it on multi-host meshes"
            )
        if self.packed:
            self.packed_step, self.flat, self.unravel = make_packed_train_step(
                self.model, tx, cfg.train.gamma, cfg.train.iters,
                self.params, self.opt_state, donate=cfg.parallel.donate,
                refine=refine, grad_dtype=cfg.train.grad_dtype,
                telemetry=cfg.train.telemetry,
            )
            # K>1: fuse K optimizer steps into one dispatch (lax.scan over
            # the packed step; engine/steps.py). The single packed_step
            # stays built for the epoch tail (n_steps % K != 0).
            if cfg.parallel.steps_per_dispatch > 1:
                self.multi_step, _, _ = make_multistep_train_step(
                    self.model, tx, cfg.train.gamma, cfg.train.iters,
                    self.params, self.opt_state,
                    cfg.parallel.steps_per_dispatch,
                    donate=cfg.parallel.donate, refine=refine,
                    grad_dtype=cfg.train.grad_dtype,
                    telemetry=cfg.train.telemetry,
                )

        self.ckpt_dir = os.path.join(cfg.exp_path, "checkpoints")

        # Retrace watchdog (obs/retrace.py): every train-loop program is
        # watched by jit-cache entry count — growth after warmup means a
        # silent retrace (the runtime complement of deepcheck GJ007) and
        # becomes a `recompile` event; cfg.train.strict_retrace raises.
        # eval_step is deliberately NOT watched: eval loaders run
        # drop_last=False, so a smaller tail batch legitimately compiles
        # a second entry every epoch.
        self.retrace = RetraceWatchdog(
            emit=self.telemetry.emit_recompile,
            strict=cfg.train.strict_retrace, context="train")
        step_name = "refine_train_step" if refine else "train_step"
        self.retrace.watch(step_name, self.train_step)
        if self.packed:
            self.retrace.watch("packed_train_step", self.packed_step)
            if cfg.parallel.steps_per_dispatch > 1:
                self.retrace.watch("multistep_train_step", self.multi_step)

    def _repack(self) -> None:
        """Refresh the packed train state after self.params/opt_state were
        replaced outside the train loop (weight load / resume)."""
        if self.packed:
            from jax.flatten_util import ravel_pytree

            self.flat, _ = ravel_pytree((self.params, self.opt_state))

    # -- checkpoint / resume -------------------------------------------------

    def load_weights(self, path: str, resume: bool = False) -> None:
        """Load params (and optimizer state + epoch when resuming —
        ``tools/engine.py:100-108``)."""
        tmpl_p = jax.tree_util.tree_map(np.asarray, self.params)
        tmpl_o = jax.tree_util.tree_map(np.asarray, self.opt_state)
        params, opt_state, epoch = load_checkpoint(
            path, tmpl_p, tmpl_o if resume else None
        )
        self.params = replicate(params, self.mesh)
        if resume:
            self.opt_state = replicate(opt_state, self.mesh)
            self.begin_epoch = epoch + 1
            # Keep the TB x-axis continuous across restarts (the optax
            # schedule itself continues from the restored optimizer count).
            self.step_count = self.begin_epoch * max(1, len(self.train_loader))
        self._repack()
        self.log.info(f"loaded weights from {path} (epoch {epoch})")

    def load_stage1_weights(self, path: str) -> None:
        """Non-strict import of stage-1 params into the refine model's
        ``backbone`` subtree (``engine_refine.py:110`` strict=False)."""
        params = jax.tree_util.tree_map(np.asarray, self.params)
        backbone_tmpl = params["params"]["backbone"]
        s1, _, epoch = load_checkpoint(path, {"params": backbone_tmpl}, None)
        params["params"]["backbone"] = s1["params"]
        self.params = replicate(params, self.mesh)
        self._repack()
        self.log.info(f"imported stage-1 weights from {path} (epoch {epoch})")

    # -- loops ---------------------------------------------------------------

    def _device_batch(self, batch: Dict[str, np.ndarray], on_indivisible="error"):
        return device_batch(batch, self.mesh, on_indivisible)

    # -- telemetry helpers ---------------------------------------------------

    # Divergence events emitted per run before muting (snapshots are
    # bounded separately by TrainConfig.max_snapshots).
    MAX_DIVERGENCE_EVENTS = 10

    def _capture_state(self):
        """DEVICE-side copy of the CURRENT (pre-step) train state, in
        whichever form the active mode carries it. A jnp copy dispatches
        asynchronously — no host sync in the hot loop; the D2H transfer
        happens only in ``_handle_trip`` when a snapshot is actually
        written. The copy is ordered before the step's donation by data
        dependence."""
        if self.packed:
            return ("flat", jnp.copy(self.flat))
        return ("trees", (jax.tree_util.tree_map(jnp.copy, self.params),
                          jax.tree_util.tree_map(jnp.copy, self.opt_state)))

    def _state_trees(self, state):
        """Fetch a ``_capture_state`` capture to numpy (params, opt_state)."""
        kind, payload = state
        if kind == "flat":
            params, opt_state = self.unravel(payload)
        else:
            params, opt_state = payload
        return (jax.tree_util.tree_map(np.asarray, params),
                jax.tree_util.tree_map(np.asarray, opt_state))

    def _handle_trip(self, trip, epoch: int, step: int, prev_state,
                     host_batch) -> None:
        """A divergence detector firing: snapshot (when the offending
        batch + pre-step state were captured and the budget allows), then
        the divergence event, then optionally halt."""
        if self.trips_emitted >= self.MAX_DIVERGENCE_EVENTS:
            return
        self.trips_emitted += 1
        snap_path = None
        if (prev_state is not None and host_batch is not None
                and self.snapshots_taken < self.cfg.train.max_snapshots):
            params_np, opt_np = self._state_trees(prev_state)
            snap_path = dump_snapshot(
                self.snap_dir, host_batch, params_np, opt_np,
                step=step, epoch=epoch, reason=trip.reason, loss=trip.loss,
                cfg=self.cfg,
                extra_meta={
                    "zscore": trip.zscore,
                    # The doctor rebuilds the optax chain exactly (the
                    # schedule's state shape differs from a constant-lr
                    # adam's, and restore is structural).
                    "schedule": {
                        "steps_per_epoch": max(1, len(self.train_loader)),
                        "dataset_size": len(self.train_ds),
                    },
                },
            )
            self.snapshots_taken += 1
            self.telemetry.emit_snapshot(epoch, step, snap_path, trip.reason)
        self.telemetry.emit_divergence(
            epoch, step, trip.reason, trip.loss, zscore=trip.zscore,
            snapshot=snap_path,
        )
        if self.trips_emitted == self.MAX_DIVERGENCE_EVENTS:
            self.log.info(
                f"telemetry: {self.trips_emitted} divergence events "
                "emitted; muting further divergence reporting for this "
                "run (state is likely persistently corrupt — see the "
                "first snapshot)"
            )
        if self.cfg.train.halt_on_divergence:
            # Caught by training(), which flushes the epoch's buffered
            # step events before re-raising.
            raise DivergenceHalt(
                f"training diverged at epoch {epoch} step {step} "
                f"({trip.reason}, loss={trip.loss})"
                + (f"; snapshot dumped to {snap_path} — replay with "
                   f"scripts/run_doctor.py" if snap_path else "")
            )

    @staticmethod
    def _tel_records(m) -> Optional[list]:
        """Per-optimizer-step host telemetry dicts from one metrics leaf
        (fused dispatches carry ``(K,)`` sub-leaves; ``delta_flow_norm``
        is a per-step ``(T,)`` vector and only exists unfused)."""
        tel = m.get("telemetry")
        if tel is None:
            return None
        host = jax.tree_util.tree_map(np.asarray, tel)
        n = len(np.atleast_1d(np.asarray(m["loss"])))

        def pick(v, j):
            arr = np.asarray(v)
            return (arr[j] if n > 1 else arr).tolist()

        return [
            {
                key: (
                    {g: pick(x, j) for g, x in value.items()}
                    if isinstance(value, dict) else pick(value, j)
                )
                for key, value in host.items()
            }
            for j in range(n)
        ]

    def _train_loop(self, stream, steps_k, watch, tel_on, observe,
                    dev_metrics) -> Optional[DivergenceHalt]:
        """One epoch's dispatch loop (all three modes). A
        ``halt_on_divergence`` trip is caught and RETURNED, not raised:
        the caller flushes the epoch's buffered step events — the
        trajectory leading into the trip — before re-raising."""
        cfg = self.cfg
        try:
            if steps_k > 1:
                # Fused mode: stack K sharded batches (leading axis K; the
                # batch-axis sharding propagates through the stack) and run
                # them in one dispatch. The tail reuses the single step.
                pending = []
                for b in stream:
                    pending.append(b)
                    if len(pending) == steps_k:
                        batches = jax.tree_util.tree_map(
                            lambda *xs: jnp.stack(xs), *pending
                        )
                        pending = []
                        self.flat, m = self.multi_step(self.flat, batches)
                        dev_metrics.append(m)
                        self.retrace.check(
                            signature=lambda b=batches: args_signature(b))
                        if tel_on:
                            observe(m, None, None)
                for b in pending:
                    self.flat, m = self.packed_step(self.flat, b)
                    dev_metrics.append(m)
                    self.retrace.check(
                        signature=lambda b=b: args_signature(b))
                    if tel_on:
                        observe(m, None, None)
            else:
                for item in stream:
                    hb, b = item if watch else (None, item)
                    prev_state = (
                        self._capture_state()
                        if watch and self.snapshots_taken < cfg.train.max_snapshots
                        else None
                    )
                    if self.packed:
                        if cfg.parallel.host_roundtrip:
                            # Break the chained-executable dependency
                            # through the host: D2H+H2D of one flat buffer
                            # per step (identical floats; see
                            # ParallelConfig).
                            self.flat = jnp.asarray(np.asarray(self.flat))
                        self.flat, m = self.packed_step(self.flat, b)
                    else:
                        self.params, self.opt_state, m = self.train_step(
                            self.params, self.opt_state, b
                        )
                    dev_metrics.append(m)
                    # One int compare per watched program; the signature
                    # is only rendered if something actually tripped.
                    self.retrace.check(
                        signature=lambda b=b: args_signature(b))
                    if tel_on:
                        observe(m, hb, prev_state)
        except DivergenceHalt as e:
            return e
        return None

    def _step_cost_report(self, step_s: float) -> Optional[Dict[str, Any]]:
        """The epoch_summary ``cost`` block: measured step seconds next
        to the committed inventory's flagship train-step prediction
        (``CostSurface.lookup_train_step``) and the flops-from-inventory
        hardware-utilization estimate. Pure host-side observability —
        the jitted step (and its telemetry-off jaxpr guarantee) never
        sees any of this, and every failure path degrades to None
        rather than touching training. ``comparable`` follows the
        pvraft_bench/v1 rule: a CPU step time is recorded against the
        TPU-topology prediction but never enforceable (and the record
        is the FLAGSHIP-geometry spec — a differently-shaped run reads
        the ratio as scale evidence, not a pass/fail)."""
        if self._cost_surface is False:
            try:
                from pvraft_tpu.programs.costs import CostSurface

                self._cost_surface = CostSurface.load()
            except Exception:  # noqa: BLE001 — observability must not fail training
                self._cost_surface = None
        surface = self._cost_surface
        if surface is None or step_s <= 0:
            return None
        from pvraft_tpu.programs.costs import hardware_utilization

        dtype = self.cfg.model.compute_dtype or "float32"
        rec = surface.lookup_train_step(dtype)
        if rec is None or rec.device_seconds <= 0:
            return None
        platform = jax.devices()[0].platform
        util = hardware_utilization(rec.flops, step_s, dtype)
        return {
            "program": rec.name,
            "basis": rec.basis,
            "predicted_step_ms": round(rec.device_seconds * 1e3, 3),
            "step_ratio": round(step_s / rec.device_seconds, 4),
            "hw_utilization": (round(util, 6)
                               if util is not None else None),
            "platform": platform,
            "comparable": platform == "tpu" and rec.comparable,
        }

    def training(self, epoch: int) -> Dict[str, float]:
        cfg = self.cfg
        timer = StepTimer()
        # Per-step metrics stay on device until the epoch ends, so host
        # logging never forces a dispatch sync inside the hot loop —
        # EXCEPT under telemetry, whose divergence check is one scalar
        # fetch per step (the documented cost of arming it; the jitted
        # program itself still has no host callback).
        dev_metrics = []
        profile = cfg.train.profile_dir if epoch == self.begin_epoch else None
        steps_k = cfg.parallel.steps_per_dispatch if self.packed else 1
        tel_on = self.detector is not None
        # Snapshot capture additionally keeps the host batch and a
        # device-side copy of the pre-step state per step (D2H happens
        # only when a snapshot is written); single-dispatch modes only
        # (a fused dispatch's intermediate states never exist outside
        # the scan).
        watch = tel_on and self._snap_capable and steps_k == 1
        steps_seen = 0

        def observe(m, host_batch, prev_state):
            nonlocal steps_seen
            losses = np.atleast_1d(np.asarray(m["loss"]))
            nonfinite = np.atleast_1d(np.asarray(m["telemetry"]["nonfinite"]))
            for j, loss in enumerate(losses):
                steps_seen += 1
                trip = self.detector.update(
                    float(loss), int(nonfinite[min(j, len(nonfinite) - 1)])
                )
                if trip is not None:
                    self._handle_trip(
                        trip, epoch, self.step_count + steps_seen,
                        prev_state, host_batch,
                    )

        if profile:
            self.telemetry.emit_trace_window("start", profile, epoch)
        with trace_context(profile or None):
            timer.start()
            prep = (
                (lambda hb: (hb, self._device_batch(hb))) if watch
                else self._device_batch
            )
            stream = device_prefetch(
                self.train_loader.epoch(epoch), prep,
                depth=cfg.parallel.device_prefetch,
            )
            halt = self._train_loop(
                stream, steps_k, watch, tel_on, observe, dev_metrics)
            if dev_metrics:
                timer.stop(dev_metrics[-1]["loss"])
        if profile:
            self.telemetry.emit_trace_window("stop", profile, epoch)
        if self.packed:
            # Unpack once per epoch so eval and checkpointing see the
            # trained state without per-step tree traffic.
            self.params, self.opt_state = self.unravel(self.flat)
        # Fused-dispatch metric leaves arrive as (K,) arrays; flattening
        # keeps per-optimizer-step logging identical in every mode. Each
        # flattened step becomes one structured `step` event (which also
        # writes the reference Train/Loss+Train/EPE TB scalars).
        step_rows = []
        for m in dev_metrics:
            ls = np.atleast_1d(np.asarray(m["loss"]))
            es = np.atleast_1d(np.asarray(m["epe"]))
            tels = self._tel_records(m) or [None] * len(ls)
            step_rows.extend(
                (float(l), float(e), t) for l, e, t in zip(ls, es, tels)
            )
        n_steps = len(step_rows)
        for i, (l, e, t) in enumerate(step_rows):
            self.telemetry.emit_step(
                epoch, self.step_count + i + 1, l, e, telemetry=t
            )
        self.step_count += n_steps
        # Per-epoch device-memory watermark (obs/device_memory.py): one
        # memory_stats() sample per local device onto the event stream.
        # CPU backends report no stats and emit nothing — zero noise in
        # CPU CI, real HBM occupancy in TPU runs.
        devmem = sample_device_memory()
        if devmem:
            self.telemetry.emit_device_memory(devmem, context="train")
        if halt is not None:
            # The step events above (the run's trajectory INTO the trip)
            # are flushed; no epoch summary or checkpoint for a halted
            # epoch — the state is corrupt by definition.
            raise halt
        if n_steps == 0:
            # Empty epoch (loader yielded nothing): an explicit steps=0
            # event instead of NaN means leaking into the TB/event
            # history downstream dashboards aggregate over.
            self.telemetry.emit_epoch_summary(epoch, steps=0)
            self.log.info(f"epoch {epoch}: steps=0 (empty epoch — loader "
                          "yielded no batches)")
            save_checkpoint(
                self.ckpt_dir,
                jax.tree_util.tree_map(np.asarray, self.params),
                jax.tree_util.tree_map(np.asarray, self.opt_state),
                epoch,
                cfg.train.checkpoint_interval,
                backend=cfg.train.ckpt_backend,
            )
            self.telemetry.emit_checkpoint(epoch, "last", path=self.ckpt_dir)
            return {"loss": float("nan"), "epe": float("nan"),
                    "step_ms": 0.0}
        losses = [l for l, _, _ in step_rows]
        epes = [e for _, e, _ in step_rows]
        mean_loss = float(np.mean(losses))
        mean_epe = float(np.mean(epes))
        step_ms = timer.mean / n_steps * 1e3
        cost = self._step_cost_report(step_ms / 1e3)
        self.telemetry.emit_epoch_summary(
            epoch, steps=n_steps, loss=mean_loss, epe=mean_epe,
            step_ms=round(step_ms, 3),
            **({"cost": cost} if cost is not None else {}),
        )
        self.log.info(
            f"epoch {epoch}: loss {mean_loss:.4f} epe {mean_epe:.4f} "
            f"step {step_ms:.1f} ms"
        )
        save_checkpoint(
            self.ckpt_dir,
            jax.tree_util.tree_map(np.asarray, self.params),
            jax.tree_util.tree_map(np.asarray, self.opt_state),
            epoch,
            cfg.train.checkpoint_interval,
            backend=cfg.train.ckpt_backend,
        )
        self.telemetry.emit_checkpoint(epoch, "last", path=self.ckpt_dir)
        return {"loss": mean_loss, "epe": mean_epe, "step_ms": step_ms}

    def val_test(self, epoch: int, mode: str = "val") -> Dict[str, float]:
        loader = self.val_loader if mode == "val" else self.test_loader
        # Distinct scenes per step: with scene-sharded loaders the global
        # batch holds bsize scenes from EACH process; unsharded loaders
        # duplicate the same bsize scenes process_count times (the mean
        # over the global axis is duplication-invariant either way).
        shard_world = (self._val_shard if mode == "val"
                       else self._test_shard)[1]
        if mode == "test":
            best = find_checkpoint(self.ckpt_dir, "best_checkpoint")
            if best is not None:
                self.load_weights(best)  # engine.py:191
        # Metric sums stay on device across the whole loop — a float() per
        # batch would stall dispatch once per scene (3,824 times on FT3D
        # test); one device->host transfer per epoch instead.
        import time as _time

        t0 = _time.perf_counter()
        dev_sums = None
        count = 0
        for bsize, b in device_prefetch(
            loader.epoch(0),
            # eval_batch scenes sharded over the data axis; a tail batch
            # smaller than the axis replicates (exact, just not parallel).
            lambda batch: (batch["pc1"].shape[0], self._device_batch(
                batch, on_indivisible="replicate")),
            depth=self.cfg.parallel.device_prefetch,
        ):
            metrics, _ = self.eval_step(self.params, b)
            # mean * (distinct scenes in the global batch): exact for both
            # the scene-sharded case (bsize * world distinct rows) and the
            # duplicated case (bsize distinct rows, each world times).
            eff = bsize * shard_world
            summed = jax.tree_util.tree_map(
                lambda v: jnp.mean(v, axis=0) * eff, metrics
            )
            dev_sums = summed if dev_sums is None else jax.tree_util.tree_map(
                jnp.add, dev_sums, summed
            )
            count += eff
        means = {
            k: float(v) / max(1, count) for k, v in (dev_sums or {}).items()
        }
        eval_s = _time.perf_counter() - t0
        self.log.info(
            f"{mode} epoch {epoch}: {count} scenes in {eval_s:.1f}s "
            f"({count / max(eval_s, 1e-9):.1f} scenes/s, "
            f"eval_batch={self.eval_batch})"
        )
        # One structured eval event; the sink writes the reference
        # <Mode>/<Metric> TB scalars from the same record.
        self.telemetry.emit_eval(mode, epoch, count, means)
        self.log.info(f"{mode} epoch {epoch}: " + " ".join(
            f"{k}={v:.4f}" for k, v in sorted(means.items())
        ))
        if mode == "val" and means.get("epe3d", float("inf")) < self.best_epe:
            self.best_epe = means["epe3d"]
            save_checkpoint(
                self.ckpt_dir,
                jax.tree_util.tree_map(np.asarray, self.params),
                jax.tree_util.tree_map(np.asarray, self.opt_state),
                epoch,
                checkpoint_interval=0,
                best=True,
                backend=self.cfg.train.ckpt_backend,
            )
            self.telemetry.emit_checkpoint(epoch, "best", path=self.ckpt_dir)
        return means

    def fit(self) -> Dict[str, float]:
        """Full schedule: train+val each epoch, test once at the end
        (``train.py:81-84``)."""
        for epoch in range(self.begin_epoch, self.cfg.train.num_epochs):
            self.training(epoch)
            self.val_test(epoch, "val")
        result = self.val_test(self.cfg.train.num_epochs - 1, "test")
        wait_for_saves()  # async (orbax) writes must land before exit
        self.close()
        return result

    def close(self) -> None:
        """Release the telemetry sink (event file, TB writer, log file
        handlers). Idempotent; Trainers used beyond ``fit`` (tests, drive
        scripts) should call this when done."""
        self.telemetry.close()
