"""Standalone evaluation (equivalent of ``test.py:70-156``).

Builds the FT3D-test or KITTI dataset, loads a checkpoint, runs the eval
loop at 32 GRU iterations (``test.py:120``), accumulates running-mean
metrics (``test.py:128-142``) and optionally dumps per-scene
``pc1/pc2/flow`` arrays for visualization (the ``result/`` layout consumed
by the reference's mayavi script, ``visual.py:14-21``)."""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pvraft_tpu.config import Config
from pvraft_tpu.data import FT3D, KITTI, PrefetchLoader, SyntheticDataset
from pvraft_tpu.data.loader import device_prefetch
from pvraft_tpu.engine.checkpoint import load_checkpoint, load_torch_checkpoint
from pvraft_tpu.engine.steps import make_eval_step
from pvraft_tpu.models import PVRaft, PVRaftRefine
from pvraft_tpu.parallel.mesh import device_batch, make_mesh, replicate
from pvraft_tpu.utils.logging import ExperimentLog


def build_eval_dataset(cfg: Config):
    d = cfg.data
    if d.dataset == "FT3D":
        return FT3D(d.root, d.max_points, "test", strict_sizes=d.strict_sizes)
    if d.dataset == "KITTI":
        return KITTI(d.root, d.max_points, strict_sizes=d.strict_sizes)
    if d.dataset == "synthetic":
        return SyntheticDataset(size=d.synthetic_size, nb_points=d.max_points,
                                noise=0.01, seed=2)
    raise ValueError(f"unknown dataset {d.dataset!r}")


class Evaluator:
    def __init__(self, cfg: Config, mesh=None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(n_seq=1)
        self.log = ExperimentLog(cfg.exp_path, "TestAlone", cfg.data.dataset)
        self.dataset = build_eval_dataset(cfg)
        self.loader = PrefetchLoader(
            self.dataset, 1, num_workers=min(2, cfg.data.num_workers)
        )
        refine = cfg.train.refine
        self.model = (PVRaftRefine if refine else PVRaft)(
            cfg.model, mesh=self.mesh if cfg.model.seq_shard else None
        )
        sample = next(iter(self.loader.epoch(0)))
        b = {k: jnp.asarray(v) for k, v in sample.items()}
        self.params = replicate(
            self.model.init(jax.random.key(0), b["pc1"], b["pc2"], 2),
            self.mesh,
        )
        self.eval_step = make_eval_step(
            self.model, cfg.train.eval_iters, cfg.train.gamma, refine=refine
        )

    def load(self, path: str) -> None:
        tmpl = jax.tree_util.tree_map(np.asarray, self.params)
        params, _, epoch = load_checkpoint(path, tmpl, None)
        self.params = replicate(params, self.mesh)
        self.log.info(f"loaded checkpoint {path} (epoch {epoch})")

    def load_torch(self, path: str) -> None:
        """Load a reference-published torch ``.params`` checkpoint
        (``test.py:101-106`` role) for eval parity."""
        tree, epoch = load_torch_checkpoint(path, refine=self.cfg.train.refine)
        self.params = replicate({"params": tree}, self.mesh)
        self.log.info(f"imported torch checkpoint {path} (epoch {epoch})")

    def run(
        self, dump_dir: Optional[str] = None, log_every: int = 50
    ) -> Dict[str, float]:
        # Metric sums accumulate on device; the host syncs only every
        # ``log_every`` scenes (the reference's tqdm-style running means,
        # test.py:128-142) instead of once per scene — eval wall-clock is
        # part of the protocol being raced.
        dev_sums = None
        count = 0
        for idx, (batch, b) in enumerate(device_prefetch(
            self.loader.epoch(0),
            # bs=1 protocol (test.py:92): replication is intended here; the
            # host batch rides along for --dump_dir. Keeping a batch in
            # flight overlaps its H2D copy with the previous scene's eval.
            lambda batch: (batch, device_batch(
                batch, self.mesh, on_indivisible="replicate")),
            depth=self.cfg.parallel.device_prefetch,
        )):
            metrics, flow = self.eval_step(self.params, b)
            dev_sums = metrics if dev_sums is None else jax.tree_util.tree_map(
                jnp.add, dev_sums, metrics
            )
            count += 1
            if log_every and count % log_every == 0:
                self.log.info(
                    f"[{count}/{len(self.loader)}] "
                    + " ".join(
                        f"{k}={float(v) / count:.4f}"
                        for k, v in sorted(dev_sums.items())
                    )
                )
            if dump_dir is not None:
                scene = os.path.join(dump_dir, self.cfg.data.dataset, str(idx))
                os.makedirs(scene, exist_ok=True)
                np.save(os.path.join(scene, "pc1.npy"), batch["pc1"][0])
                np.save(os.path.join(scene, "pc2.npy"), batch["pc2"][0])
                np.save(os.path.join(scene, "flow.npy"), np.asarray(flow)[0])
        means = {
            k: float(v) / max(1, count) for k, v in (dev_sums or {}).items()
        }
        self.log.info(
            f"{self.cfg.data.dataset} ({count} scenes): "
            + " ".join(f"{k}={v:.4f}" for k, v in sorted(means.items()))
        )
        return means
