"""Standalone evaluation (equivalent of ``test.py:70-156``).

Builds the FT3D-test or KITTI dataset, loads a checkpoint, runs the eval
loop at 32 GRU iterations (``test.py:120``), accumulates running-mean
metrics (``test.py:128-142``) and optionally dumps per-scene
``pc1/pc2/flow`` arrays for visualization (the ``result/`` layout consumed
by the reference's mayavi script, ``visual.py:14-21``)."""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pvraft_tpu.config import Config
from pvraft_tpu.data import FT3D, KITTI, PrefetchLoader, SyntheticDataset
from pvraft_tpu.data.loader import device_prefetch
from pvraft_tpu.engine.checkpoint import load_checkpoint, load_torch_checkpoint
from pvraft_tpu.engine.steps import make_eval_step
from pvraft_tpu.models import PVRaft, PVRaftRefine
from pvraft_tpu.obs import RunTelemetry
from pvraft_tpu.parallel.mesh import (
    device_batch,
    eval_scene_shard,
    make_mesh,
    replicate,
)
from pvraft_tpu.rng import derive


def build_eval_dataset(cfg: Config):
    d = cfg.data
    if d.dataset == "FT3D":
        return FT3D(d.root, d.max_points, "test", strict_sizes=d.strict_sizes)
    if d.dataset == "KITTI":
        return KITTI(d.root, d.max_points, strict_sizes=d.strict_sizes)
    if d.dataset == "synthetic":
        return SyntheticDataset(size=d.synthetic_size, nb_points=d.max_points,
                                noise=0.01, seed=2,
                                n_objects=d.synthetic_objects)
    raise ValueError(f"unknown dataset {d.dataset!r}")


class Evaluator:
    def __init__(self, cfg: Config, mesh=None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(n_seq=1)
        # Same unified sink as the Trainer: standalone eval runs emit a
        # pvraft_events/v1 stream (header + final eval event) next to the
        # text log, so run tooling reads one format for both entry points.
        self.telemetry = RunTelemetry(cfg.exp_path, "TestAlone",
                                      cfg.data.dataset)
        self.log = self.telemetry.log
        self.telemetry.emit_header(cfg, mode="eval")
        self.dataset = build_eval_dataset(cfg)
        # eval_batch scenes run concurrently, sharded over the mesh data
        # axis; 0 = one scene per data-axis device. Per-scene metrics keep
        # the bs=1 protocol's running means exact (test.py:92,128-142).
        eb = cfg.train.eval_batch
        n_data = self.mesh.shape["data"]
        self.eval_batch = max(1, n_data if eb <= 0 else eb)
        # Multi-host: scene-shard across processes when safe (the shared
        # gate encodes why — see eval_scene_shard); otherwise every
        # process feeds the same scenes and the mean*count accumulation
        # stays exact, just redundant.
        self.shard = eval_scene_shard(
            len(self.dataset), self.eval_batch, self.mesh)
        self.loader = PrefetchLoader(
            self.dataset, self.eval_batch, drop_last=False,
            num_workers=min(2, cfg.data.num_workers),
            shard=self.shard,
        )
        refine = cfg.train.refine
        self.model = (PVRaftRefine if refine else PVRaft)(
            cfg.model, mesh=self.mesh if cfg.model.seq_shard else None
        )
        sample = self.dataset[0]
        b = {k: jnp.asarray(v)[None] for k, v in sample.items()}
        self.params = replicate(
            self.model.init(
                derive(cfg.train.seed, "model.init"),
                b["pc1"], b["pc2"], 2),
            self.mesh,
        )
        self.eval_step = make_eval_step(
            self.model, cfg.train.eval_iters, cfg.train.gamma, refine=refine,
            per_scene=True,
        )
        # Scan-fused eval: one dispatch evaluates eval_scan stacked
        # batches (metrics only — flows are never materialized across the
        # group, which also caps memory). The per-batch step stays built
        # for the tail group and for --dump_dir runs.
        self.eval_scan = max(1, cfg.train.eval_scan)
        if self.eval_scan > 1 and jax.process_count() > 1:
            # flush_scanned stacks device batches with an EAGER jnp.stack;
            # on multi-process runs the scene-sharded loader yields
            # non-fully-addressable global arrays, and eager ops on those
            # raise. The per-batch path is protocol-identical (fusion only
            # amortizes dispatch overhead), so fall back rather than fail.
            self.log.info(
                "eval_scan > 1 is single-process only (eager stack of "
                "sharded device batches); falling back to per-batch eval"
            )
            self.eval_scan = 1
        if self.eval_scan > 1:
            step = self.eval_step

            @jax.jit
            def scan_step(params, stacked):
                def body(c, b):
                    m, _ = step(params, b)
                    return c, m

                return jax.lax.scan(body, 0, stacked)[1]

            self.eval_scan_step = scan_step

    def load(self, path: str) -> None:
        tmpl = jax.tree_util.tree_map(np.asarray, self.params)
        params, _, epoch = load_checkpoint(path, tmpl, None)
        self.params = replicate(params, self.mesh)
        self.log.info(f"loaded checkpoint {path} (epoch {epoch})")

    def load_torch(self, path: str) -> None:
        """Load a reference-published torch ``.params`` checkpoint
        (``test.py:101-106`` role) for eval parity."""
        tree, epoch = load_torch_checkpoint(path, refine=self.cfg.train.refine)
        self.params = replicate({"params": tree}, self.mesh)
        self.log.info(f"imported torch checkpoint {path} (epoch {epoch})")

    def run(
        self, dump_dir: Optional[str] = None, log_every: int = 50
    ) -> Dict[str, float]:
        # Metric sums accumulate on device; the host syncs only every
        # ``log_every`` scenes (the reference's tqdm-style running means,
        # test.py:128-142) instead of once per scene — eval wall-clock is
        # part of the protocol being raced. Each eval step returns per-
        # scene values, so batching/sharding scenes over the mesh leaves
        # the running means identical to the reference's bs=1 loop.
        if dump_dir is not None and jax.process_count() > 1:
            # On multi-host runs `flow` is globally sharded (np.asarray on a
            # non-fully-addressable array raises) and the unsharded eval
            # loader would have every process write the same scene files
            # concurrently. Dumping is a single-host visualization feature.
            raise ValueError(
                "--dump_dir is single-host only; re-run eval on one host "
                "to dump scenes for visualization"
            )
        dev_sums = None
        count = 0
        n_scenes = len(self.dataset)
        # Scan fusion groups full-size device batches; --dump_dir needs
        # per-batch flows, so it disables fusion for that run.
        scan_n = self.eval_scan if dump_dir is None else 1
        pending = []

        def accumulate(per_scene_metrics, bsize, scene_axis=0):
            """mean-over-scenes * (distinct scenes): exact for both the
            scene-sharded case (local_bsize * world distinct rows) and the
            unsharded multi-host case, where the global batch axis holds
            each scene process_count times (the mean over it is
            duplication-invariant, a raw sum is not)."""
            nonlocal dev_sums
            summed = jax.tree_util.tree_map(
                lambda v: jnp.mean(v, axis=scene_axis) * bsize,
                per_scene_metrics,
            )
            if scene_axis:  # scanned leaves are (S, B): sum the S groups
                summed = jax.tree_util.tree_map(
                    lambda v: jnp.sum(v, axis=0), summed
                )
            dev_sums = summed if dev_sums is None else jax.tree_util.tree_map(
                jnp.add, dev_sums, summed
            )

        def log_progress(added):
            nonlocal count
            crossed = (
                log_every and count // log_every != (count + added) // log_every
            )
            count += added
            if crossed:
                self.log.info(
                    f"[{count}/{n_scenes}] "
                    + " ".join(
                        f"{k}={float(v) / count:.4f}"
                        for k, v in sorted(dev_sums.items())
                    )
                )

        def flush_scanned():
            if not pending:
                return 0
            bsize = self.eval_batch * self.shard[1]
            group = list(pending)
            pending.clear()
            if len(group) < scan_n:
                # Partial group: the scan program is compiled for exactly
                # scan_n batches; re-lowering it for a one-off length
                # would cost a fresh compile. The per-batch step is
                # already built — run the stragglers through it.
                for gb in group:
                    m, _ = self.eval_step(self.params, gb)
                    accumulate(m, bsize)
            else:
                stacked = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *group
                )
                ms = self.eval_scan_step(self.params, stacked)
                accumulate(ms, bsize, scene_axis=1)  # leaves (S, B)
            return len(group) * bsize

        for batch, b in device_prefetch(
            self.loader.epoch(0),
            # A tail batch smaller than the data axis replicates — per-
            # scene metrics make that exact, just not parallel. The host
            # batch rides along for --dump_dir; keeping one in flight
            # overlaps its H2D copy with the previous batch's eval.
            lambda batch: (batch, device_batch(
                batch, self.mesh, on_indivisible="replicate")),
            depth=self.cfg.parallel.device_prefetch,
        ):
            if scan_n > 1 and batch["pc1"].shape[0] == self.eval_batch:
                pending.append(b)
                if len(pending) == scan_n:
                    log_progress(flush_scanned())
                continue
            # A smaller (tail) batch: flush any scanned group first so the
            # running means stay in scene order, then fall through to the
            # per-batch step. Through log_progress so a log_every crossing
            # inside the flushed group is not silently skipped.
            log_progress(flush_scanned())
            metrics, flow = self.eval_step(self.params, b)
            bsize = batch["pc1"].shape[0] * self.shard[1]
            accumulate(metrics, bsize)
            if dump_dir is not None:
                flow_host = np.asarray(flow)
                for row in range(bsize):
                    scene = os.path.join(
                        dump_dir, self.cfg.data.dataset, str(count + row)
                    )
                    os.makedirs(scene, exist_ok=True)
                    np.save(os.path.join(scene, "pc1.npy"), batch["pc1"][row])
                    np.save(os.path.join(scene, "pc2.npy"), batch["pc2"][row])
                    np.save(os.path.join(scene, "flow.npy"), flow_host[row])
            log_progress(bsize)
        log_progress(flush_scanned())  # partial final group
        means = {
            k: float(v) / max(1, count) for k, v in (dev_sums or {}).items()
        }
        self.log.info(
            f"{self.cfg.data.dataset} ({count} scenes): "
            + " ".join(f"{k}={v:.4f}" for k, v in sorted(means.items()))
        )
        # Standalone eval has no epoch axis; -1 marks "not an epoch loop"
        # in the event stream.
        self.telemetry.emit_eval(
            self.cfg.data.dataset, epoch=-1, scenes=count, metrics=means)
        return means

    def close(self) -> None:
        """Release the telemetry sink (event file, TB writer, log file
        handlers) — same contract as ``Trainer.close``. Idempotent."""
        self.telemetry.close()
