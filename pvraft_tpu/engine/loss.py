"""Training losses (equivalent of ``tools/loss.py``).

The reference's boolean-mask fancy indexing (``loss.py:37``) is replaced by
masked sums with static shapes, which is required under jit. For a mask m
and error e of shape (B, N, 3), ``mean(|e|[m>0])`` equals
``sum(|e| * m) / (3 * sum(m))``.
"""

from __future__ import annotations

import jax.numpy as jnp


def compute_loss(
    est_flow: jnp.ndarray, mask: jnp.ndarray, gt_flow: jnp.ndarray
) -> jnp.ndarray:
    """Masked mean-L1 flow loss (``tools/loss.py:16-40``).

    est_flow/gt_flow: (B, N, 3); mask: (B, N) or (B, N, 1).
    """
    if mask.ndim == 3:
        mask = mask[..., 0]
    m = (mask > 0).astype(est_flow.dtype)
    err = jnp.abs(est_flow - gt_flow) * m[..., None]
    return jnp.sum(err) / (3.0 * jnp.maximum(jnp.sum(m), 1.0))


def sequence_loss(
    flows: jnp.ndarray, mask: jnp.ndarray, gt_flow: jnp.ndarray, gamma: float = 0.8
) -> jnp.ndarray:
    """RAFT exponentially-weighted sequence loss (``tools/loss.py:4-13``).

    flows: (T, B, N, 3) stacked per-iteration predictions; weight of
    iteration i is gamma**(T-1-i).
    """
    t = flows.shape[0]
    weights = gamma ** jnp.arange(t - 1, -1, -1, dtype=flows.dtype)
    per_iter = jnp.stack([compute_loss(flows[i], mask, gt_flow) for i in range(t)])
    return jnp.sum(weights * per_iter)
