from pvraft_tpu.utils.logging import ExperimentLog, TBWriter
from pvraft_tpu.utils.profiling import StepTimer, trace_context

__all__ = ["ExperimentLog", "TBWriter", "StepTimer", "trace_context"]
