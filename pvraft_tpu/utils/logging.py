"""Experiment logging.

Mirrors the reference's observability surface (``tools/engine.py:72-98,
149-158``): ``experiments/<exp>/{logs,checkpoints}`` directories, a python
``logging`` file per mode, and TensorBoard scalars with the same tag names
(``Train/Loss``, ``Train/EPE``, ``Val/...``). TensorBoard is optional — if
no writer backend is importable the scalars are kept in-memory (inspectable
by tests) and the run proceeds.
"""

from __future__ import annotations

import logging
import os
from collections import defaultdict
from typing import Dict, List, Tuple


class TBWriter:
    """TensorBoard scalar writer with a no-op/in-memory fallback."""

    def __init__(self, log_dir: str):
        self.history: Dict[str, List[Tuple[int, float]]] = defaultdict(list)
        self._writer = None
        try:  # torch's pure-python writer is available in this image
            from torch.utils.tensorboard import SummaryWriter

            self._writer = SummaryWriter(log_dir=log_dir)
        except Exception:
            self._writer = None

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self.history[tag].append((step, float(value)))
        if self._writer is not None:
            self._writer.add_scalar(tag, float(value), step)

    def close(self) -> None:
        """Flush + release the backend writer. Exception-safe and
        idempotent: a writer whose flush dies mid-close (disk full,
        backend already torn down at interpreter exit) must not mask the
        error that actually killed the run — the in-memory history stays
        inspectable either way."""
        writer, self._writer = self._writer, None
        if writer is None:
            return
        try:
            writer.flush()
        except Exception:
            pass
        try:
            writer.close()
        except Exception:
            pass


class ExperimentLog:
    """Experiment directory layout + per-mode log files
    (``tools/engine.py:72-98``)."""

    def __init__(self, exp_path: str, mode: str = "Train", dataset: str = ""):
        self.root = exp_path
        self.log_dir = os.path.join(exp_path, "logs")
        self.ckpt_dir = os.path.join(exp_path, "checkpoints")
        os.makedirs(self.log_dir, exist_ok=True)
        os.makedirs(self.ckpt_dir, exist_ok=True)

        name = f"{mode}_{dataset}" if dataset else mode
        self.logger = logging.getLogger(f"pvraft_tpu.{name}")
        self.logger.setLevel(logging.INFO)
        self.logger.propagate = False
        path = os.path.join(self.log_dir, f"{name}.log")
        if not any(
            isinstance(h, logging.FileHandler)
            and getattr(h, "baseFilename", None) == os.path.abspath(path)
            for h in self.logger.handlers
        ):
            fh = logging.FileHandler(path)
            fh.setFormatter(
                logging.Formatter("%(asctime)s %(levelname)s %(message)s")
            )
            self.logger.addHandler(fh)

    def info(self, msg: str) -> None:
        self.logger.info(msg)

    def close(self) -> None:
        """Release this experiment's file handlers.

        Loggers are process-global (``logging.getLogger`` caches by
        name), so without this every Trainer/Evaluator instantiation in
        a long-lived process — pytest sessions most of all — leaks an
        open file descriptor per experiment dir. Only handlers attached
        by this class are removed; idempotent."""
        for handler in list(self.logger.handlers):
            if isinstance(handler, logging.FileHandler):
                self.logger.removeHandler(handler)
                try:
                    handler.close()
                except Exception:
                    pass
