"""Deprecated home — moved to :mod:`pvraft_tpu.profiling`.

Kept as a re-export shim so older callers keep working; new code should
import from ``pvraft_tpu.profiling`` (which also hosts the per-stage step
profiler behind ``artifacts/step_profile.json``)."""

from pvraft_tpu.profiling.timers import StepTimer, trace_context  # noqa: F401
