"""The AOT program catalog: every deviceless-certified entry point.

Registration site for the ProgramSpecs that are *compiled* (not just
traced): the Pallas kernel sweep (tag ``kernel`` — the Mosaic-drift
canary ``scripts/lint.sh`` runs), the flagship training programs, the
2x2 dp x sp sharded step, and the certified serve bucket programs. Also
registers the step profiler's measurement ladder (tag ``profile``) so
the registry's ``verify`` gate traces the same programs the profiler
times. The trace/deepcheck corpus (tag ``audit``) registers from
``pvraft_tpu/analysis/audit.py``; geometry *data* lives in
:mod:`pvraft_tpu.programs.geometries`.

Everything heavy is inside thunks (the audit-entry discipline): import
this module freely — no jax, no model build, until a spec is built.

Thunks here return plain ``jax.ShapeDtypeStruct`` args; the compile
driver (``programs/compile.py``) attaches a replicated single-device
sharding to any arg that carries none, so only genuinely sharded
programs (``dp_sp_2x2_train_step``) deal with meshes themselves.
"""

from __future__ import annotations

from pvraft_tpu.programs import geometries as g
from pvraft_tpu.programs.spec import register
from pvraft_tpu.rng import DEFAULT_SEED, derive

# Tiny trace dims for the profile.* specs — deliberately the audit
# module's pairwise-distinct dims so an axis mixup cannot type-check.
from pvraft_tpu.analysis.audit import B, K, N  # noqa: F401  (registers audit specs too)


def _f32(*shape):
    import jax

    return jax.ShapeDtypeStruct(shape, "float32")


def _flagship_arrays():
    b, n = g.FLAGSHIP_BATCH, g.FLAGSHIP_POINTS
    k = g.FLAGSHIP_TRUNCATE_K
    return (_f32(b, n, k), _f32(b, n, k, 3), _f32(b, n, 3))


# --- Pallas kernels (tag "kernel": the lint.sh/CI Mosaic-drift canary) -----
# Flagship-geometry Mosaic compiles of both kernels + their VJPs — every
# Pallas entry point in the repo. The fused-lookup kernel has already
# been silently broken once by Mosaic toolchain drift (integer-iota
# argmin, fixed in PR 5); these four specs make the next drift fail the
# gate loudly instead of rotting at HEAD.

@register("pallas_voxel_fwd", tags=("kernel", "pallas"),
          topology=g.TOPOLOGY)
def _k_voxel_fwd():
    """voxel_bin_means Pallas kernel, forward, flagship geometry."""
    from pvraft_tpu.ops.pallas.voxel_corr import voxel_bin_means_pallas

    corr, rel, _ = _flagship_arrays()
    return (lambda c, r: voxel_bin_means_pallas(c, r, 3, 0.25, 3),
            (corr, rel))


@register("pallas_voxel_grad", tags=("kernel", "pallas"),
          topology=g.TOPOLOGY)
def _k_voxel_grad():
    """voxel_bin_means Pallas kernel, VJP, flagship geometry."""
    import jax

    from pvraft_tpu.ops.pallas.voxel_corr import voxel_bin_means_pallas

    corr, rel, _ = _flagship_arrays()
    return (jax.grad(lambda c, r: voxel_bin_means_pallas(
        c, r, 3, 0.25, 3).sum()), (corr, rel))


@register("pallas_fused_lookup_fwd", tags=("kernel", "pallas"),
          topology=g.TOPOLOGY,
          determinism="unique-index-scatter; replay-certified")
def _k_fused_fwd():
    """Fused corr-lookup Pallas kernel, forward, flagship geometry."""
    from pvraft_tpu.ops.pallas.corr_lookup import fused_corr_lookup

    corr, rel, coords = _flagship_arrays()
    return (lambda c, x, q: fused_corr_lookup(c, x, q, 3, 0.25, 3, 32),
            (corr, rel, coords))


@register("pallas_fused_lookup_grad", tags=("kernel", "pallas"),
          topology=g.TOPOLOGY,
          determinism="unique-index-scatter; replay-certified")
def _k_fused_grad():
    """Fused corr-lookup Pallas kernel, VJP, flagship geometry."""
    import jax

    from pvraft_tpu.ops.pallas.corr_lookup import fused_corr_lookup

    corr, rel, coords = _flagship_arrays()
    return (jax.grad(lambda c, x, q: sum(
        o.sum() for o in fused_corr_lookup(c, x, q, 3, 0.25, 3, 32))),
        (corr, rel, coords))


def _gru_weight_structs():
    """The packed weight 8-tuple's shapes (hidden=64, context=64) —
    mirrors ``analysis/kernels/model._gru_env`` so the static VMEM model
    and the Mosaic compile evidence describe one program."""
    return (_f32(64, 64), _f32(8, 64), _f32(128, 64), _f32(64, 192),
            _f32(64, 192), _f32(64, 192), _f32(8, 192), _f32(8, 192))


@register("pallas_gru_iter_fwd", tags=("kernel", "pallas"),
          topology=g.TOPOLOGY)
def _k_gru_fwd():
    """Fused MotionEncoder+ConvGRU kernel, forward, flagship geometry."""
    from pvraft_tpu.ops.pallas.gru_iter import fused_gru_update

    b, n = g.FLAGSHIP_BATCH, g.FLAGSHIP_POINTS
    k = g.FLAGSHIP_TRUNCATE_K
    feat = _f32(b, n, 64)
    return (lambda ne, i, c, f, w: fused_gru_update(
        ne, i, c, f, w, "float32", k),
        (feat, feat, feat, _f32(b, n, 8), _gru_weight_structs()))


@register("pallas_gru_iter_grad", tags=("kernel", "pallas"),
          topology=g.TOPOLOGY)
def _k_gru_grad():
    """Fused MotionEncoder+ConvGRU kernel, VJP (all inputs incl. the
    packed weights), flagship geometry."""
    import jax

    from pvraft_tpu.ops.pallas.gru_iter import fused_gru_update

    b, n = g.FLAGSHIP_BATCH, g.FLAGSHIP_POINTS
    k = g.FLAGSHIP_TRUNCATE_K
    feat = _f32(b, n, 64)
    return (jax.grad(lambda ne, i, c, f, w: fused_gru_update(
        ne, i, c, f, w, "float32", k).sum(), argnums=(0, 1, 2, 3, 4)),
        (feat, feat, feat, _f32(b, n, 8), _gru_weight_structs()))


# --- flagship training programs -------------------------------------------

def _abstract_params(model, batch, n_points):
    """Shape-only params via eval_shape (init runs no FLOPs here)."""
    import jax
    import jax.numpy as jnp

    pc = jax.ShapeDtypeStruct((batch, n_points, 3), jnp.float32)
    return jax.eval_shape(
        lambda r, a, b: model.init(r, a, b, 2),
        derive(DEFAULT_SEED, "model.init"), pc, pc)


def _flagship_thunk(kind, model_kwargs):
    """fwd or full train-step (fwd+bwd+adam) at the flagship geometry."""

    def thunk():
        import jax
        import optax

        from pvraft_tpu.config import ModelConfig
        from pvraft_tpu.engine.loss import sequence_loss
        from pvraft_tpu.models import PVRaft

        b, n = g.FLAGSHIP_BATCH, g.FLAGSHIP_POINTS
        iters, k = g.FLAGSHIP_ITERS, g.FLAGSHIP_TRUNCATE_K
        cfg = ModelConfig(truncate_k=k, **model_kwargs)
        model = PVRaft(cfg)
        params = _abstract_params(model, b, max(256, k))
        pc = _f32(b, n, 3)
        mask = _f32(b, n)

        if kind == "fwd":
            def fwd(p, a, c):
                flows, _ = model.apply(p, a, c, iters)
                return flows[-1]

            return fwd, (params, pc, pc)

        tx = optax.adam(1e-3)
        opt_state = jax.eval_shape(tx.init, params)

        def train_step(p, o, a, c, m, gt):
            def loss_fn(pp):
                flows, _ = model.apply(pp, a, c, iters)
                return sequence_loss(flows, m, gt, 0.8)

            loss, grads = jax.value_and_grad(loss_fn)(p)
            updates, o2 = tx.update(grads, o, p)
            return optax.apply_updates(p, updates), o2, loss

        return train_step, (params, opt_state, pc, pc, mask, pc)

    return thunk


# The certified flagship variants: fp32 (documents the single-chip HBM
# limit), the remat fp32 path that fits, and the bench ladder's primary
# bf16+pallas+approx rung — model kwargs come from the SAME dicts
# bench.py measures (geometries.BENCH_VARIANTS).
_BENCH = dict(g.BENCH_VARIANTS)
_FLAGSHIP_VARIANTS = (
    # Round-5 AOT finding: plain fp32 fwd+bwd+adam needs 19.5 GiB of HBM
    # at the flagship shape — it does NOT fit a 16 GiB v5e chip; the
    # train-step leg stays to document that limit (expect hbm_oom).
    ("fp32", _BENCH["fp32"], ("fwd", "train_step"), "hbm_oom"),
    # remat (jax.checkpoint around each GRU iteration) is the supported
    # fp32 path on v5e; this leg certifies it fits (backward-only change,
    # no separate fwd program).
    ("fp32_remat", dict(_BENCH["fp32"], remat=True), ("train_step",), ""),
    ("bf16_pallas_approx", _BENCH["bf16+pallas+approx"],
     ("fwd", "train_step"), ""),
)

for _tag, _kwargs, _kinds, _expect in _FLAGSHIP_VARIANTS:
    for _kind in _kinds:
        register(
            f"flagship_{_kind}_{_tag}",
            tags=("flagship", "train" if _kind == "train_step" else "fwd"),
            precision="f32" if _tag.startswith("fp32") else "any",
            topology=g.TOPOLOGY,
            expect_failure=_expect if _kind == "train_step" else "",
            determinism="unique-index-scatter; replay-certified",
            description=f"flagship {_kind} ({_tag}), "
                        f"{g.FLAGSHIP_POINTS} pts x {g.FLAGSHIP_ITERS} iters",
        )(_flagship_thunk(_kind, _kwargs))


@register("dp_sp_2x2_train_step", tags=("flagship", "train", "sharded"),
          topology=g.TOPOLOGY, n_devices=4,
          determinism="unique-index-scatter; ring-fold fixed by mesh",
          description="2x2 dp x sp sharded train step (ring correlation)")
def _dp_sp(devices=None):
    """Batch over ``data``, points over ``seq`` (ring correlation),
    params placed by the declared ``PARTITION_RULES`` ladder — the
    registry spec and ``programs/partitioning.py`` cannot drift, and a
    param leaf no rule covers fails the build (exactly-once coverage,
    shardcheck GS001). Collectives must lower for the v5e slice. With
    no devices (the verify/trace path) the mesh degrades to whatever the
    host offers, the same discipline as the ring audit entries."""
    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.loss import sequence_loss
    from pvraft_tpu.models import PVRaft
    from pvraft_tpu.parallel.mesh import make_mesh
    from pvraft_tpu.programs.partitioning import (
        BATCH_PARTITION,
        PARTITION_RULES,
        match_partition_rules,
    )

    if devices is not None:
        mesh = make_mesh(n_data=2, n_seq=2, devices=list(devices)[:4])
    else:
        local = jax.devices()
        n_seq = 2 if len(local) >= 2 else 1
        n_data = 2 if len(local) >= 2 * n_seq else 1
        mesh = make_mesh(n_data=n_data, n_seq=n_seq)
    rep = NamedSharding(mesh, P())
    batch_s = NamedSharding(mesh, P(*BATCH_PARTITION))
    b, n = g.FLAGSHIP_BATCH, g.FLAGSHIP_POINTS
    iters, k = g.FLAGSHIP_ITERS, g.FLAGSHIP_TRUNCATE_K

    cfg = ModelConfig(truncate_k=k, seq_shard=mesh.shape["seq"] > 1)
    model = PVRaft(cfg, mesh=mesh)

    def shard(tree, s):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            tree)

    def leaf_key(path) -> str:
        return "/".join(str(getattr(kk, "key", kk)) for kk in path)

    params_abs = _abstract_params(model, b, max(256, k))
    flat_paths = [leaf_key(p) for p, _ in
                  jax.tree_util.tree_flatten_with_path(params_abs)[0]]
    spec_of = match_partition_rules(PARTITION_RULES, flat_paths)
    params = jax.tree_util.tree_map_with_path(
        lambda p, x: jax.ShapeDtypeStruct(
            x.shape, x.dtype,
            sharding=NamedSharding(mesh, P(*spec_of[leaf_key(p)]))),
        params_abs)
    pc = jax.ShapeDtypeStruct((b, n, 3), np.float32, sharding=batch_s)
    mask = jax.ShapeDtypeStruct((b, n), np.float32, sharding=batch_s)
    tx = optax.adam(1e-3)
    # Optimizer state replicates while every PARTITION_RULES spec does;
    # the first rule that shards a leaf must mirror the ladder over the
    # adam mu/nu trees here (their inner paths repeat the param paths).
    opt_state = shard(jax.eval_shape(tx.init, params), rep)

    def train_step(p, o, a, c, m, gt):
        def loss_fn(pp):
            flows, _ = model.apply(pp, a, c, iters)
            return sequence_loss(flows, m, gt, 0.8)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o2 = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o2, loss

    return train_step, (params, opt_state, pc, pc, mask, pc)


# --- certified serve bucket programs --------------------------------------
# The exact program the serve engine AOT-compiles (masked forward, pc1
# donated) at the certified (bucket, batch) geometries — claim-day
# readiness covers inference, not just training. One spec per geometry,
# enumerated from geometries.SERVE_CERTIFIED: bf16 covers BOTH
# geometries because it is the DEFAULT serving dtype (ISSUE 9). The
# replica pool runs this same single-device program on every replica,
# so certifying it once covers the pool's semantics — though each
# replica still pays its own backend compile at startup (device-bound
# executables; the engine compiles replica tables concurrently).

def _serve_thunk(model_kwargs, bucket, bs):
    def thunk():
        import jax

        from pvraft_tpu.config import ModelConfig
        from pvraft_tpu.models import PVRaft
        from pvraft_tpu.serve.engine import build_predict_fn

        cfg = ModelConfig(truncate_k=g.FLAGSHIP_TRUNCATE_K,
                          use_pallas=True, **model_kwargs)
        model = PVRaft(cfg)
        predict = build_predict_fn(model, g.SERVE_DEFAULT_ITERS)
        params = _abstract_params(model, bs, max(256, g.FLAGSHIP_TRUNCATE_K))
        pc = _f32(bs, bucket, 3)
        vm = jax.ShapeDtypeStruct((bs, bucket), "bool")
        return predict, (params, pc, pc, vm, vm)

    return thunk


for _tag, _kwargs, _geoms in g.SERVE_CERTIFIED:
    for _bucket, _bs in _geoms:
        register(
            f"serve_predict_{_tag}_b{_bucket}_bs{_bs}",
            tags=("serve", "aot"),
            precision="f32" if _tag == "fp32" else "any",
            donate_argnums=g.SERVE_PREDICT_DONATE,
            topology=g.TOPOLOGY,
            determinism="unique-index-scatter; replay-certified",
            description=f"serve predict ({_tag}) bucket {_bucket} x "
                        f"batch {_bs}, pc1 donated",
        )(_serve_thunk(_kwargs, _bucket, _bs))


# --- the step profiler's measurement ladder (tag "profile") ---------------
# One spec per ladder stage, built by the SAME ladder_programs the
# profiler times — registered at tiny audit dims so `programs verify`
# traces the full ladder in milliseconds.

def _profile_thunk(stage):
    def thunk():
        import jax
        import jax.numpy as jnp
        import optax

        from pvraft_tpu.config import ModelConfig
        from pvraft_tpu.models import PVRaft
        from pvraft_tpu.profiling.step_profiler import (
            ladder_programs,
            make_encoder,
        )

        cfg = ModelConfig(truncate_k=K, corr_knn=K // 2, graph_k=K // 2)
        model = PVRaft(cfg)
        enc = make_encoder(cfg)
        tx = optax.adam(1e-3)

        def fn(pc1, pc2, mask, gt):
            params = model.init(
                derive(DEFAULT_SEED, "model.init"), pc1, pc2, 2)
            enc_params = enc.init(
                derive(DEFAULT_SEED, "encoder.init"), pc1)
            opt_state = tx.init(params)
            progs = dict(ladder_programs(
                cfg, model, enc, params, enc_params, tx, opt_state,
                pc1, pc2, mask, gt, iters=3))
            return progs[stage](jnp.float32(0.0))

        # pc1/pc2 share N: the ladder profiles the serve/train layout
        # where both clouds fill one bucket (corr_init needs N >= k).
        return fn, (_f32(B, N, 3), _f32(B, N, 3), _f32(B, N), _f32(B, N, 3))

    return thunk


for _stage in g.PROFILE_LADDER_STAGES:
    register(f"profile.{_stage}", tags=("profile",),
             determinism="unique-index-scatter; replay-certified",
             description=f"step-profiler ladder stage {_stage!r} "
                         "(profiling/step_profiler.py)")(
        _profile_thunk(_stage))
