"""Program registry: what programs exist, at what shapes — queryable.

``pvraft_tpu/programs`` is the single place a jitted or AOT entry point
is declared (:class:`~pvraft_tpu.programs.spec.ProgramSpec`): its
abstract arg geometry, precision intent, donation/aliasing, sharding
group and tags. The trace audit + deepcheck corpus
(``analysis/audit.py``), the serve engine's bucket-program table,
``scripts/aot_readiness.py``, the step profiler's ladder and bench.py's
variant/A-B enumeration all iterate these records instead of hand-rolled
lists — registering one new spec buys audit + deepcheck + AOT compile
evidence + profiling for free.

CLI::

    python -m pvraft_tpu.programs list               # the inventory
    python -m pvraft_tpu.programs describe NAME      # geometry detail
    python -m pvraft_tpu.programs verify             # eval_shape all specs
    python -m pvraft_tpu.programs compile --tag kernel   # Mosaic gate

This module (and :mod:`~pvraft_tpu.programs.spec` /
:mod:`~pvraft_tpu.programs.geometries`) imports no jax: CLIs read the
registry's data before pinning a backend.
"""

from pvraft_tpu.programs import geometries                  # noqa: F401
from pvraft_tpu.programs.spec import (                      # noqa: F401
    DuplicateProgramError,
    ProgramSpec,
    by_tag,
    get,
    register,
    register_spec,
    specs,
)


def load_catalog() -> None:
    """Populate the registry: the audit corpus (``analysis/audit.py``)
    plus the AOT catalog (``programs/catalog.py``). Idempotent — module
    imports register once."""
    import pvraft_tpu.analysis.audit      # noqa: F401
    import pvraft_tpu.programs.catalog    # noqa: F401
