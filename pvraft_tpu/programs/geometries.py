"""Program geometry data: the (shape, dtype, donation) facts, once.

Pure data — imports nothing (not even jax) so any CLI can read the
enumeration before pinning a backend (bench.py's parent process never
imports jax; the serve entry points must parse flags before the platform
is committed). Everything here used to be duplicated literals across
``scripts/aot_readiness.py``, ``pvraft_tpu/serve/engine.py``,
``bench.py`` and ``analysis/audit.py``; a new program variant (a serve
bucket, a bench rung, an A/B lever) is declared HERE and the registry
(``programs/catalog.py``) turns it into audit + deepcheck + AOT
evidence. ``tests/test_programs.py`` guards that the old sites carry no
geometry literals of their own anymore.
"""

from __future__ import annotations

# --- AOT compile target ----------------------------------------------------

# Deviceless compile topology (scripts/aot_readiness.py rationale): the
# image's local libtpu lowers the REAL XLA:TPU + Mosaic pipeline for this
# v5e slice with no device attached.
TOPOLOGY = "v5e:2x2x1"
HBM_BYTES = 16 * 1024**3  # v5e chip HBM; fit is checked per program

# --- flagship training geometry (the reference run.sh configuration) -------

FLAGSHIP_BATCH = 2
FLAGSHIP_POINTS = 8192
FLAGSHIP_ITERS = 8
FLAGSHIP_TRUNCATE_K = 512

# --- bench variant ladder (bench.py, fastest-expected first) ---------------

# use_pallas pinned explicitly per variant (the config's None-auto default
# would silently turn Pallas on for every TPU variant, making the fallback
# ladder meaningless). bench.py iterates this; programs/catalog.py
# registers the AOT-certified flagship subset from the same dicts.
BENCH_VARIANTS = (
    ("bf16+pallas+approx", {"compute_dtype": "bfloat16", "use_pallas": True,
                            "approx_topk": True}),
    ("bf16+approx", {"compute_dtype": "bfloat16", "use_pallas": False,
                     "approx_topk": True}),
    ("bf16", {"compute_dtype": "bfloat16", "use_pallas": False}),
    ("fp32", {"use_pallas": False}),
)

# Backward-path A/B levers (PR 2): each record maps one bench env flag to
# the config/step field it toggles. "flag" levers arm on the literal "1";
# "str" levers arm on any non-empty value. ``step_arg`` levers are
# per-step-factory arguments (grad_dtype), not ModelConfig fields.
# bench.py's ab_flags enumeration iterates THIS, and the
# ``engine.train_step[optimized_backward]`` audit entry builds its config
# from AB_PRIMARY — the A/B variant a bench run measures and the variant
# deepcheck walks are the same declaration.
AB_LEVERS = (
    {"env": "PVRAFT_BENCH_SCATTER_FREE", "field": "scatter_free_vjp",
     "kind": "flag"},
    {"env": "PVRAFT_BENCH_REMAT_POLICY", "field": "remat_policy",
     "kind": "str"},
    {"env": "PVRAFT_BENCH_GRAD_DTYPE", "field": "grad_dtype",
     "kind": "str", "step_arg": True},
    # Fused MotionEncoder+ConvGRU Pallas kernel (ops/pallas/gru_iter.py,
    # PR 17): a forward-path lever, enumerated here so the bench headline
    # carries it in ab_flags like the backward levers.
    {"env": "PVRAFT_BENCH_FUSED_GRU", "field": "fused_gru",
     "kind": "flag"},
)

# The full optimized configuration (all four levers armed, forward and
# backward) — the decisive TPU A/B candidate (ROADMAP item 1).
AB_PRIMARY = {"scatter_free_vjp": True, "remat_policy": "dots",
              "grad_dtype": "bfloat16", "fused_gru": True}

# --- step-profiler measurement ladder --------------------------------------

# Cumulative host-synced profiler programs, in ladder order — THE step
# anatomy enumeration. profiling/step_profiler.py builds (and times) the
# programs in this order (its MEASUREMENTS is this tuple), and
# programs/catalog.py registers one `profile.<stage>` spec per entry.
# Lives here (pure data) so the catalog can enumerate the ladder without
# importing the profiler (which imports jax).
# "gru_fused" re-times the fwdN rung with ModelConfig.fused_gru=True
# (the Pallas fused-update kernel) — same params, same program shape, so
# fwdN vs gru_fused is the fused-kernel A/B inside one profile artifact.
PROFILE_LADDER_STAGES = ("encoder", "corr_cum", "fwd1", "fwdN",
                         "gru_fused", "fwdbwd", "step")

# The derived per-stage breakdown the ladder telescopes into
# (step_profiler.BREAKDOWN_STAGES is this tuple). Also the train-side
# stage vocabulary of the pvraft_trace/v1 span plane (obs/trace.py
# TRAIN_STAGES) — here, not in the profiler, so the jax-free trace
# validator can pin it without dragging jax into its import chain.
PROFILE_BREAKDOWN_STAGES = ("encoder", "corr_init", "gru_forward",
                            "backward", "optimizer")

# --- serve geometry --------------------------------------------------------

# Default production bucket table (ServeConfig defaults and the serve CLI
# flag defaults both read these).
SERVE_DEFAULT_BUCKETS = (2048, 4096, 8192)
SERVE_DEFAULT_BATCH_SIZES = (1, 4)
SERVE_DEFAULT_ITERS = 8

# Serving dtypes the engine may compile, short-tag spelling included in
# every per-dtype program name (and the SERVE_CERTIFIED variant tags).
SERVE_DTYPES = {"float32": "fp32", "bfloat16": "bf16"}

# bfloat16 is the DEFAULT serving dtype (the TPU fast path: half the HBM
# traffic per correlation volume, MXU-native matmuls). fp32 stays one
# flag away (`--dtype float32`), and the default is test-gated by the
# accuracy bound below rather than taken on faith.
SERVE_DEFAULT_DTYPE = "bfloat16"

# Accuracy bound for bf16-by-default, EPE-style: mean endpoint error
# (L2, scene units at coord_scale 1) of bf16 predictions vs the SAME
# params served fp32 must stay below this. Measured on the CPU test
# geometry (tiny random-init model, flow magnitude ~0.7): mean EPE
# ~0.033, relative-to-flow-magnitude ~0.047. Pinned at ~3-4x measured
# so toolchain noise does not flake while a real precision regression
# (one lost mantissa bit ~= 2x) still fails; the relative bound is the
# portable one (absolute EPE scales with flow magnitude).
# tests/test_serve_pool.py enforces both.
SERVE_BF16_EPE_BOUND = 0.13        # mean |flow_bf16 - flow_fp32| (units)
SERVE_BF16_REL_EPE_BOUND = 0.15    # same, relative to mean |flow_fp32|

# Replica pool size: one single-device executor per replica, data-
# parallel across the host's local devices. 0 = one replica per local
# device (the production default); CPU CI exercises >= 2 replicas via
# the conftest-forced --xla_force_host_platform_device_count.
SERVE_DEFAULT_REPLICAS = 0

# Replica supervision thresholds (serve/supervisor.py SupervisorConfig
# reads THESE — the one place the health state machine's trip points
# live, per the geometry-data discipline above). The state machine:
# healthy -> degraded -> quarantined -> probing -> healthy.
SUPERVISOR_DEFAULTS = {
    # Consecutive hard dispatch failures before a replica is marked
    # degraded (still serving, visibly unhealthy) / pulled from the
    # work-stealing rotation entirely.
    "degraded_after": 1,
    "quarantine_after": 3,
    # Latency-outlier signal: a dispatch slower than factor x the
    # per-bucket EWMA (after min_samples warmup, above the absolute
    # floor) is an outlier; this many CONSECUTIVE outliers degrade the
    # replica. Slow is not dead: outliers never quarantine on their own.
    "latency_outlier_factor": 4.0,
    "latency_outlier_after": 4,
    "latency_min_samples": 8,
    "latency_floor_ms": 1.0,
    # Probe cadence: how often quarantined replicas get a synthetic
    # min-points request through their own AOT program (and wedge scans
    # run). Also the source of the 503 Retry-After header — a shed
    # client retrying after one probe cycle meets a re-evaluated pool.
    "probe_interval_s": 0.5,
    # One probe's budget: a replica that hangs mid-probe (dead device)
    # costs the supervisor loop at most this long, then counts as a
    # failed probe — wedge scans and other replicas' revival continue.
    "probe_timeout_s": 10.0,
    # A dispatch in flight longer than this is a wedged executor: the
    # replica is quarantined (capacity visibly down) even though the
    # stuck thread can't be killed.
    "wedge_timeout_s": 30.0,
}

# Fleet-router defaults (pvraft_tpu/fleet reads THESE — same geometry-
# data discipline as SUPERVISOR_DEFAULTS above). The router is a thin
# HTTP fan-out tier over N backend hosts (each a serve.build_service
# replica pool): it routes per-bucket by backend queue depth plus
# cost-surface-predicted device-seconds, spills over on 503, and
# quarantines backends with the supervisor's state vocabulary driven
# from polled /healthz.
FLEET_DEFAULTS = {
    # Backend health poll cadence (GET /healthz per backend) and one
    # poll's budget. A backend that misses `degraded_after` consecutive
    # polls is degraded (still routable, visibly unhealthy); at
    # `quarantine_after` it leaves the rotation until a probe poll
    # succeeds — the same healthy -> degraded -> quarantined -> probing
    # machine the replica supervisor runs one level down.
    "poll_interval_s": 0.5,
    "poll_timeout_s": 5.0,
    "degraded_after": 1,
    "quarantine_after": 3,
    # Retry-After (seconds) the router sends when EVERY backend shed or
    # is out of rotation — one poll cycle, like the supervisor's.
    "retry_after_s": 1,
    # Per-request forward budget against one backend.
    "predict_timeout_s": 60.0,
    # Canary promotion gate: the interleaved traffic fraction routed to
    # the new-weight backend, the sample count the verdict needs, and
    # the EPE bounds versus the incumbent — the SERVE_BF16_EPE_BOUND
    # precedent (a weight swap that moves predictions more than a
    # precision change would is not silently promoted).
    "canary_fraction": 0.25,
    "canary_min_samples": 8,
    "canary_epe_bound": SERVE_BF16_EPE_BOUND,
    "canary_rel_epe_bound": SERVE_BF16_REL_EPE_BOUND,
}

# pc1 is donated to every predict program: the unique input whose
# (shape, dtype) matches the flow output, so XLA aliases instead of
# allocating (deepcheck GJ004/GJ005 verify this on the serve.predict
# audit entries). Positions: (params, pc1, pc2, valid1, valid2).
SERVE_PREDICT_DONATE = (1,)

# AOT-certified serve geometries (the aot_readiness serve leg): per
# variant tag, the model-config overrides and the (bucket, batch_size)
# pairs certified for the v5e topology — the latency bucket at bs 1 and
# the throughput bucket at bs 4. bf16/Pallas covers BOTH because bf16 is
# the default serving dtype; fp32 stays certified as the flag-guarded
# fallback.
SERVE_CERTIFIED = (
    ("fp32", {}, ((2048, 1), (8192, 4))),
    ("bf16_pallas", {"compute_dtype": "bfloat16"}, ((2048, 1), (8192, 4))),
)


def predict_program_name(bucket: int, batch_size: int,
                         dtype: str = "float32") -> str:
    """The serve engine's per-program name — what /healthz,
    serve_compile events and profiles report. fp32 keeps the historical
    'predict_b{bucket}_bs{bs}' spelling (committed artifacts join on
    it); other dtypes splice their short tag ('predict_bf16_b..')."""
    short = SERVE_DTYPES[dtype]
    prefix = "predict" if dtype == "float32" else f"predict_{short}"
    return f"{prefix}_b{bucket}_bs{batch_size}"


def serve_program_keys(buckets, batch_sizes):
    """The (bucket, batch_size) program table a serve config compiles —
    THE enumeration behind InferenceEngine startup (one AOT program per
    key, in this order)."""
    for bucket in buckets:
        for bs in batch_sizes:
            yield bucket, bs
